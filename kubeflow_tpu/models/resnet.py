"""ResNet (v1.5) for image classification — BASELINE config 1.

The reference's entire training payload is tf_cnn_benchmarks ResNet-50 under
parameter-server TFJobs (reference: tf-controller-examples/tf-cnn/
create_job_specs.py:96-180, launcher.py:59-93). Here it is a first-class
flax model trained data-parallel with XLA allreduce instead of PS gRPC.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 activations,
f32 batch-norm statistics. Under pjit the batch axis is sharded on
("dp","fsdp") and BN reductions become global automatically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from kubeflow_tpu.parallel.context import constrain


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(stage_sizes=(3, 4, 6, 3), **kw)

    @classmethod
    def resnet101(cls, **kw) -> "ResNetConfig":
        return cls(stage_sizes=(3, 4, 23, 3), **kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        kw.setdefault("stage_sizes", (1, 1))
        kw.setdefault("width", 8)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


def _conv(features: int, kernel: Tuple[int, int], strides: int, cfg, name: str):
    return nn.Conv(
        features,
        kernel,
        strides=(strides, strides),
        padding=[(k // 2, k // 2) for k in kernel],
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
            ("conv_h", "conv_w", "conv_in", "conv_out"),
        ),
        name=name,
    )


def _bn(cfg, name: str):
    return nn.BatchNorm(
        use_running_average=None,  # passed at call time
        momentum=0.9,
        epsilon=1e-5,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        scale_init=nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("norm",)),
        name=name,
    )


class BottleneckBlock(nn.Module):
    cfg: ResNetConfig
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        cfg = self.cfg
        residual = x
        y = _conv(self.features, (1, 1), 1, cfg, "conv1")(x)
        y = _bn(cfg, "bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = _conv(self.features, (3, 3), self.strides, cfg, "conv2")(y)
        y = _bn(cfg, "bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = _conv(self.features * 4, (1, 1), 1, cfg, "conv3")(y)
        bn3 = _bn(cfg, "bn3")
        y = bn3(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = _conv(
                self.features * 4, (1, 1), self.strides, cfg, "conv_proj"
            )(residual)
            residual = _bn(cfg, "bn_proj")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, images: jax.Array, *, train: bool = True) -> jax.Array:
        """images: [B, H, W, 3] NHWC. Returns logits [B, num_classes]."""
        cfg = self.cfg
        x = images.astype(cfg.dtype)
        x = nn.Conv(
            cfg.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
                ("conv_h", "conv_w", "conv_in", "conv_out"),
            ),
            name="conv_init",
        )(x)
        x = _bn(cfg, "bn_init")(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    cfg, cfg.width * 2 ** i, strides, name=f"stage{i}_block{j}"
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = constrain(x, ("act_batch", "act_embed"))
        logits = nn.Dense(
            cfg.num_classes,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", "vocab")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("vocab",)
            ),
            name="head",
        )(x)
        return logits.astype(jnp.float32)
