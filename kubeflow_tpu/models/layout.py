"""Param-tree layout conversion between scanned and unrolled layer stacks.

Models here follow one convention (models/llama.py): ``scan_layers=True``
stores the decoder stack as one ``"layers"`` subtree with leaves stacked
``[L, ...]``; ``scan_layers=False`` stores ``"layer_0" .. "layer_{L-1}"``.
Training wants the scanned form (O(1) compile); serving decode wants the
unrolled form — a scanned stacked KV cache pays a whole-layer-cache
slice + writeback on every scan step, measured +18% gen tok/s unrolled
at 700M (BASELINE.md). These helpers let a server restore a checkpoint
trained in either layout into a model built in the other, so the
train→serve handoff is layout-independent.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def to_layer_layout(params: Dict[str, Any],
                    num_layers: int) -> Dict[str, Any]:
    """Scanned {'layers': [L, ...]} -> unrolled {'layer_i': ...}.
    Identity when already unrolled (or no layer stack at all)."""
    if "layers" not in params:
        return params
    # Validate before indexing: jax indexing CLAMPS out of bounds, so a
    # checkpoint with fewer stacked layers than the model would otherwise
    # silently serve its last layer repeated.
    for leaf in jax.tree.leaves(params["layers"]):
        if leaf.shape[0] != num_layers:
            raise ValueError(
                f"scanned checkpoint has {leaf.shape[0]} stacked layers, "
                f"model expects {num_layers}"
            )
    out = {k: v for k, v in params.items() if k != "layers"}
    for i in range(num_layers):
        out[f"layer_{i}"] = jax.tree.map(
            lambda x, i=i: x[i], params["layers"])
    return out


def to_scanned_layout(params: Dict[str, Any],
                      num_layers: int) -> Dict[str, Any]:
    """Unrolled {'layer_i': ...} -> scanned {'layers': [L, ...]}.
    Identity when already scanned (or no layer stack at all)."""
    if "layers" in params or "layer_0" not in params:
        return params
    have = {int(k[6:]) for k in params
            if k.startswith("layer_") and k[6:].isdigit()}
    if have != set(range(num_layers)):
        raise ValueError(
            f"unrolled checkpoint has layers {sorted(have)}, model "
            f"expects 0..{num_layers - 1}"
        )
    out = {k: v for k, v in params.items()
           if not (k.startswith("layer_") and k[6:].isdigit())}
    stack = [params[f"layer_{i}"] for i in range(num_layers)]
    out["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *stack)
    return out


def adapt_layout(params: Dict[str, Any], num_layers: int,
                 scanned: bool) -> Dict[str, Any]:
    """Convert ``params`` to the layout a model with
    ``scan_layers=scanned`` expects."""
    return (to_scanned_layout if scanned else to_layer_layout)(
        params, num_layers)
