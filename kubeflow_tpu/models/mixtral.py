"""Mixtral-style MoE transformer (BASELINE config 3: expert-parallel
all-to-all over ICI).

Reuses the Llama decoder wholesale; the dense MLP is replaced with a
top-2-routed expert bank whose leading expert dim is sharded on the ``ep``
mesh axis. Dispatch/combine are the static-capacity einsums from
kubeflow_tpu.parallel.moe, so XLA emits the token<->expert all-to-all when
tokens are dp-sharded and experts ep-sharded (SURVEY.md §2.5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from kubeflow_tpu.models import llama as llama_mod
from kubeflow_tpu.models.llama import (
    Attention,
    LlamaConfig,
    RMSNorm,
    _dense,
)
from kubeflow_tpu.parallel.context import constrain
from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    capacity_factor: float = 1.25
    router_jitter: float = 0.0
    aux_loss_weight: float = 0.02

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        return cls(
            vocab_size=32000, embed_dim=4096, num_layers=32, num_heads=32,
            num_kv_heads=8, head_dim=128, mlp_dim=14336, rope_theta=1e6,
            num_experts=8, **kw,
        )

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        kw.setdefault("num_experts", 4)
        kw.setdefault("capacity_factor", 2.0)
        base = LlamaConfig.tiny()
        for f in dataclasses.fields(LlamaConfig):
            kw.setdefault(f.name, getattr(base, f.name))
        return cls(**kw)


class MoeMlp(nn.Module):
    """Expert bank: stacked SwiGLU experts [E, ...] + top-2 router."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        B, S, M = x.shape
        E = cfg.num_experts

        router = _dense(E, ("embed", None), cfg, "router")
        logits = router(x).astype(jnp.float32)  # [B, S, E]

        def pinit(key, shape, dtype):
            return nn.initializers.normal(stddev=0.02)(key, shape, dtype)

        w_gate = self.param(
            "w_gate",
            nn.with_logical_partitioning(pinit, ("expert", "embed", "mlp")),
            (E, M, cfg.mlp_dim), cfg.param_dtype,
        )
        w_up = self.param(
            "w_up",
            nn.with_logical_partitioning(pinit, ("expert", "embed", "mlp")),
            (E, M, cfg.mlp_dim), cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_logical_partitioning(pinit, ("expert", "mlp", "embed")),
            (E, cfg.mlp_dim, M), cfg.param_dtype,
        )

        def expert_fn(e_in: jax.Array) -> jax.Array:
            # e_in: [E, C, M] (ep-sharded on E under pjit)
            e_in = constrain(e_in, ("act_expert", None, "act_embed"))
            gate = jnp.einsum(
                "ecm,emh->ech", e_in, w_gate.astype(e_in.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            up = jnp.einsum(
                "ecm,emh->ech", e_in, w_up.astype(e_in.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            h = nn.silu(gate) * up
            out = jnp.einsum(
                "ech,ehm->ecm", h, w_down.astype(h.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            return constrain(out, ("act_expert", None, "act_embed"))

        gate_cfg = Top2GateConfig(
            num_experts=E,
            capacity_factor=cfg.capacity_factor,
            jitter_eps=cfg.router_jitter,
        )
        rng = None
        if cfg.router_jitter > 0 and self.has_rng("router"):
            rng = self.make_rng("router")
        out_flat, aux = moe_dispatch(
            x.reshape(B * S, M), logits.reshape(B * S, E), expert_fn,
            gate_cfg, rng=rng,
        )
        self.sow("losses", "moe_aux_loss", aux)
        out = out_flat.reshape(B, S, M)
        return constrain(out, ("act_batch", "act_seq", "act_embed"))


class MixtralLayer(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: jax.Array, decode: bool = False
    ) -> jax.Array:
        cfg = self.cfg
        h = RMSNorm(cfg, name="input_norm")(x)
        h = Attention(cfg, name="attn")(h, positions, decode=decode)
        x = x + h
        h = RMSNorm(cfg, name="post_attn_norm")(x)
        h = MoeMlp(cfg, name="moe")(h)
        return x + h


class Mixtral(nn.Module):
    """Mixtral LM: Llama skeleton with MoE layers. Aux losses are sowed into
    the "losses" collection; the train step adds cfg.aux_loss_weight * sum."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: Optional[jax.Array] = None,
        decode: bool = False,
    ) -> jax.Array:
        cfg = self.cfg
        B, S = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))

        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.embed_dim),
            cfg.param_dtype,
        )
        x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
        x = constrain(x, ("act_batch", "act_seq", "act_embed"))

        layer_cls = MixtralLayer
        if cfg.remat:
            layer_cls = nn.remat(
                MixtralLayer, prevent_cse=not cfg.scan_layers, static_argnums=(3,)
            )

        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry, positions, decode), None),
                variable_axes={"params": 0, "cache": 0, "losses": 0},
                split_rngs={"params": True, "router": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(layer_cls(cfg, name="layers"), x, None)
        else:
            for i in range(cfg.num_layers):
                x = layer_cls(cfg, name=f"layer_{i}")(x, positions, decode)

        x = RMSNorm(cfg, name="final_norm")(x)
        logits = _dense(cfg.vocab_size, ("embed", "vocab"), cfg, "lm_head")(
            x
        ).astype(jnp.float32)
        return constrain(logits, ("act_batch", "act_seq", "act_vocab"))
