"""Mixtral-style MoE transformer (BASELINE config 3: expert-parallel
all-to-all over ICI).

Reuses the Llama decoder wholesale; the dense MLP is replaced with a
top-2-routed expert bank whose leading expert dim is sharded on the ``ep``
mesh axis. Dispatch/combine are the static-capacity einsums from
kubeflow_tpu.parallel.moe, so XLA emits the token<->expert all-to-all when
tokens are dp-sharded and experts ep-sharded (SURVEY.md §2.5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from kubeflow_tpu.models.llama import (
    Attention,
    Llama,
    LlamaConfig,
    RMSNorm,
    _dense,
)
from kubeflow_tpu.parallel.context import constrain
from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    capacity_factor: float = 1.25
    router_jitter: float = 0.0
    aux_loss_weight: float = 0.02
    # parallel.moe dispatch mechanism: "auto" picks index-gather when the
    # expert axis is unsharded, GShard einsum (clean all-to-all) when
    # ep-sharded.
    moe_dispatch: str = "auto"

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        return cls(
            vocab_size=32000, embed_dim=4096, num_layers=32, num_heads=32,
            num_kv_heads=8, head_dim=128, mlp_dim=14336, rope_theta=1e6,
            num_experts=8, **kw,
        )

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        kw.setdefault("num_experts", 4)
        kw.setdefault("capacity_factor", 2.0)
        base = LlamaConfig.tiny()
        for f in dataclasses.fields(LlamaConfig):
            kw.setdefault(f.name, getattr(base, f.name))
        return cls(**kw)


class MoeMlp(nn.Module):
    """Expert bank: stacked SwiGLU experts [E, ...] + top-2 router."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        B, S, M = x.shape
        E = cfg.num_experts

        router = _dense(E, ("embed", None), cfg, "router")
        logits = router(x).astype(jnp.float32)  # [B, S, E]

        def pinit(key, shape, dtype):
            return nn.initializers.normal(stddev=0.02)(key, shape, dtype)

        w_gate = self.param(
            "w_gate",
            nn.with_logical_partitioning(pinit, ("expert", "embed", "mlp")),
            (E, M, cfg.mlp_dim), cfg.param_dtype,
        )
        w_up = self.param(
            "w_up",
            nn.with_logical_partitioning(pinit, ("expert", "embed", "mlp")),
            (E, M, cfg.mlp_dim), cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_logical_partitioning(pinit, ("expert", "mlp", "embed")),
            (E, cfg.mlp_dim, M), cfg.param_dtype,
        )

        def expert_fn(e_in: jax.Array) -> jax.Array:
            # e_in: [E, C, M] (ep-sharded on E under pjit)
            e_in = constrain(e_in, ("act_expert", None, "act_embed"))
            gate = jnp.einsum(
                "ecm,emh->ech", e_in, w_gate.astype(e_in.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            up = jnp.einsum(
                "ecm,emh->ech", e_in, w_up.astype(e_in.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            # Same tag names as the dense MLP so the "minimal"/"mlp_only"
            # remat policies cover MoE experts too: without these, every
            # selective policy replays the full dispatch+expert block in
            # backward (the 44%-elementwise profile slice, BASELINE.md).
            gate = checkpoint_name(gate, "mlp_gate")
            up = checkpoint_name(up, "mlp_up")
            h = nn.silu(gate) * up
            out = jnp.einsum(
                "ech,ehm->ecm", h, w_down.astype(h.dtype),
                preferred_element_type=jnp.float32,
            ).astype(e_in.dtype)
            return constrain(out, ("act_expert", None, "act_embed"))

        gate_cfg = Top2GateConfig(
            num_experts=E,
            capacity_factor=cfg.capacity_factor,
            jitter_eps=cfg.router_jitter,
            dispatch=cfg.moe_dispatch,
        )
        rng = None
        if cfg.router_jitter > 0 and self.has_rng("router"):
            rng = self.make_rng("router")
        out_flat, aux = moe_dispatch(
            x.reshape(B * S, M), logits.reshape(B * S, E), expert_fn,
            gate_cfg, rng=rng,
        )
        self.sow("losses", "moe_aux_loss", aux)
        out = out_flat.reshape(B, S, M)
        return constrain(out, ("act_batch", "act_seq", "act_embed"))


class MixtralLayer(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: jax.Array, decode: bool = False,
        stage_step=None, block_tables=None, write_lens=None,
    ) -> jax.Array:
        cfg = self.cfg
        h = RMSNorm(cfg, name="input_norm")(x)
        h = Attention(cfg, name="attn")(h, positions, decode=decode,
                                        stage_step=stage_step,
                                        block_tables=block_tables,
                                        write_lens=write_lens)
        x = x + h
        h = RMSNorm(cfg, name="post_attn_norm")(x)
        h = MoeMlp(cfg, name="moe")(h)
        return x + h


class Mixtral(Llama):
    """Mixtral LM: the Llama backbone with MoE layers (see Llama's subclass
    hook points — tie_embeddings, logits_softcap, scan/remat all shared).
    Aux losses are sowed into the "losses" collection; the train step adds
    aux_loss_weight * mean."""

    cfg: MixtralConfig

    LAYER_CLS = MixtralLayer
    SCAN_COLLECTIONS = ("params", "cache", "losses")
    SCAN_RNGS = ("params", "router")
