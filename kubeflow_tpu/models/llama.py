"""Llama-family decoder-only transformer (flagship model, BASELINE config 2).

TPU-first choices:
- bf16 activations / f32 params by default; all softmax/norm statistics f32.
- Logical-axis annotations on every param and activation so one model serves
  dp/fsdp/tp/sp layouts by swapping rule tables (kubeflow_tpu.parallel).
- ``lax.scan`` over layers (config.scan_layers) for O(1) compile scaling.
- Attention dispatches through the ambient ParallelContext: "full" reference
  softmax, "ring" (ppermute context parallelism), or "ulysses" (all-to-all).
- Autoregressive decode cache (flax "cache" collection) for the serving
  engine's continuous batching.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from jax.ad_checkpoint import checkpoint_name

from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.ops.flash_attention import flash_attention
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.paged_attention import (
    gather_kv_pages,
    paged_decode_attention,
    physical_rows,
    pool_shape,
    scatter_kv_rows,
)
from kubeflow_tpu.ops.rope import apply_rope, rope_frequencies
from kubeflow_tpu.parallel.context import constrain, get_context
from kubeflow_tpu.parallel.pipeline import PipelinedLayers
from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded
from kubeflow_tpu.parallel.ulysses import ulysses_attention_sharded

Dtype = Any


def _vocab_axis_sharded() -> bool:
    """True when the ambient context shards the "vocab" logical axis over a
    >1-sized mesh axis (the embedding lookup then switches to a one-hot
    contraction; see Llama.__call__)."""
    ctx = get_context()
    if ctx.mesh is None:
        return False
    rule = dict(ctx.rules).get("vocab")
    if rule is None:
        return False
    axes = (rule,) if isinstance(rule, str) else tuple(rule)
    return any(ctx.mesh.shape.get(a, 1) > 1 for a in axes)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    embed_dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    # Rematerialisation policy (only meaningful with remat=True):
    #   "full"    — save nothing per layer; backward replays the whole layer
    #               (lowest memory, ~4/3 hardware-FLOP overhead).
    #   "minimal" — save the projection outputs tagged with checkpoint_name
    #               (qkv post-rope, pre-o_proj attention context, mlp
    #               gate/up); backward replays only norms, rope arithmetic
    #               and the flash-attention forward (its custom-VJP
    #               residuals), cutting the remat overhead to a few percent
    #               for ~2.1x the activation memory of "full".
    #   "dots"    — XLA's dots_with_no_batch_dims_saveable (save every
    #               matmul output inside the layer).
    remat_policy: str = "full"
    # Fused projections. In isolation one [E, 11264] gate+up matmul
    # sustains ~90% of v5e bf16 peak vs ~76% for two [E, 5632] matmuls,
    # but in the full model XLA already co-schedules the sibling matmuls:
    # measured end-to-end, fused_gate_up is neutral and fused_qkv is ~4%
    # SLOWER (the [E, Hkv, G+2, Dh] grouped layout costs more in
    # slice/reshape than it wins on MXU shape), so both default off.
    # fused_qkv keeps the canonical GQA grouping under tp sharding
    # (kv-head groups shard whole; reshaped head h uses kv head h // G).
    fused_qkv: bool = False
    fused_gate_up: bool = False
    tie_embeddings: bool = False
    logits_softcap: float = 0.0
    # >1 switches the layer stack to the GPipe SPMD pipeline layout
    # (params stacked [stages, layers/stage, ...] on the "pp" mesh axis;
    # see parallel/pipeline.py). Training layout only — decode keeps tp/sp.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0      # 0 => defaults to pipeline_stages
    # Emit logits in activation dtype instead of f32: halves the [B,S,V]
    # HBM traffic; the loss upcasts to f32 for its softmax statistics
    # either way (losses.cross_entropy_loss), so accuracy is preserved to
    # bf16 logit precision (z-loss keeps logits small).
    logits_f32: bool = True
    # "" (activation dtype) or "int8": quantize the decode KV cache with
    # per-(slot, position, kv-head) absmax scales — halves the KV
    # footprint, which is what caps the serving batch at flagship sizes.
    # Prefill attends the live k/v, so only decode reads dequantized
    # cache rows (dequant fuses into the attention matmuls).
    kv_cache_dtype: str = ""
    # >0: decode steps write k/v into a [B, C, Hkv, D] staging buffer at
    # the chunk-step index (ONE cheap scalar-index DUS — the same column
    # for every slot) instead of per-slot scatters into the main cache;
    # the engine flushes the staging rows into the cache once per decode
    # chunk in C-row granules. The per-step per-slot scatters this
    # replaces measured 25% of decode device time (3072 four-KB scatters
    # per 32-step chunk at bs24). Requires the engine to pass
    # ``stage_step`` and flush (ServingEngine does); 0 = classic per-step
    # writes.
    decode_staging: int = 0
    # >0: the decode KV cache is a PHYSICALLY PAGED pool (ISSUE 18) —
    # one [paged_kv_blocks + 1, paged_kv_block_size, Hkv, D] pool per
    # layer shared by every slot instead of a dense [B, max_seq_len,
    # Hkv, D] cache, with block id paged_kv_blocks reserved as the
    # scratch page (see ops/paged_attention.py for the layout and
    # exactness contract). Requires the caller to thread
    # ``block_tables`` [B, max_blocks] (ServingEngine does, backed by
    # serving/blocks.py tables with copy-on-write prefix sharing);
    # shrinking the pool shrinks actual HBM, not just admission.
    paged_kv_blocks: int = 0
    paged_kv_block_size: int = 16

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(
            vocab_size=128256, embed_dim=4096, num_layers=32, num_heads=32,
            num_kv_heads=8, head_dim=128, mlp_dim=14336, rope_theta=500000.0,
            **kw,
        )

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        return cls(
            vocab_size=128256, embed_dim=8192, num_layers=80, num_heads=64,
            num_kv_heads=8, head_dim=128, mlp_dim=28672, rope_theta=500000.0,
            **kw,
        )

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test/dryrun config: real architecture, toy widths."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("embed_dim", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("mlp_dim", 128)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("scan_layers", False)
        kw.setdefault("remat", False)
        return cls(**kw)


def _remat_policy(name: str):
    """LlamaConfig.remat_policy -> jax checkpoint policy (None = save
    nothing, i.e. classic full remat)."""
    if name == "full":
        return None
    if name == "minimal":
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_out", "attn_resid", "mlp_gate", "mlp_up",
            "moe_route"
        )
    if name == "qkv_attn":
        # Lighter variant: backward replays the MLP but not the attention
        # projections; fits larger batches than "minimal".
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_out"
        )
    if name == "qkv_attn_lse":
        # qkv_attn + the flash kernel's custom-VJP residuals (o + lse):
        # saving them keeps the backward from replaying the forward
        # kernel. Measured (r4, 1x v5e): +4% at 8k ctx where the S^2
        # replay dominates, but -2.5% for the 700M config at 2k/bs12
        # (residual pressure beats the smaller replay) — hence a separate
        # policy, not a default.
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_out", "attn_resid"
        )
    if name == "attn_only":
        # Save just the attention context: the backward replays the
        # projections and the MLP (cheap, MXU-efficient); fits the largest
        # batches of the selective policies.
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if name == "mlp_only":
        # Save the (large) gate/up projections, replay the (cheap)
        # attention block: the opposite trade to "qkv_attn".
        return jax.checkpoint_policies.save_only_these_names(
            "mlp_gate", "mlp_up"
        )
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat_policy {name!r}")


def _dense(
    features, kernel_axes, cfg: LlamaConfig, name: str, axis=-1
) -> nn.DenseGeneral:
    return nn.DenseGeneral(
        features=features,
        axis=axis,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), kernel_axes
        ),
        name=name,
    )


class RMSNorm(nn.Module):
    cfg: LlamaConfig
    def setup(self) -> None:
        self.weight = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (self.cfg.embed_dim,),
            self.cfg.param_dtype,
        )

    def __call__(self, x: jax.Array) -> jax.Array:
        return rms_norm(x, self.weight, eps=self.cfg.norm_eps)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: jax.Array,
        *,
        decode: bool = False,
        stage_step=None,
        block_tables=None,
        write_lens=None,
    ) -> jax.Array:
        cfg = self.cfg
        H, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        if cfg.fused_qkv and H % Hkv == 0:
            G = H // Hkv
            qkv = _dense(
                (Hkv, G + 2, Dh),
                ("embed", "kv_heads", "qkv_group", "head_dim"),
                cfg, "qkv_proj",
            )(x)                                   # [B, S, Hkv, G+2, Dh]
            B_, S_ = qkv.shape[:2]
            q = qkv[..., :G, :].reshape(B_, S_, H, Dh)
            k = qkv[..., G, :]
            v = qkv[..., G + 1, :]
        else:
            q = _dense((H, Dh), ("embed", "heads", "head_dim"), cfg, "q_proj")(x)
            k = _dense((Hkv, Dh), ("embed", "kv_heads", "head_dim"), cfg, "k_proj")(x)
            v = _dense((Hkv, Dh), ("embed", "kv_heads", "head_dim"), cfg, "v_proj")(x)
        q = constrain(q, ("act_batch", "act_seq", "act_heads", "act_kv"))
        k = constrain(k, ("act_batch", "act_seq", "act_heads", "act_kv"))
        v = constrain(v, ("act_batch", "act_seq", "act_heads", "act_kv"))

        cos, sin = rope_frequencies(
            Dh, cfg.max_seq_len, theta=cfg.rope_theta
        )
        q = apply_rope(q, cos, sin, positions=positions)
        k = apply_rope(k, cos, sin, positions=positions)
        # Tags are no-ops unless remat_policy="minimal" selects them.
        q = checkpoint_name(q, "qkv")
        k = checkpoint_name(k, "qkv")
        v = checkpoint_name(v, "qkv")

        if decode:
            # decode is True (single-step against filled cache) or
            # "prefill" (fresh rows — causal over the incoming block).
            if cfg.paged_kv_blocks > 0:
                out = self._paged_decode_attention(
                    q, k, v, mode=decode, stage_step=stage_step,
                    block_tables=block_tables, write_lens=write_lens)
            else:
                out = self._decode_attention(q, k, v, mode=decode,
                                             stage_step=stage_step)
        else:
            out = self._train_attention(q, k, v)
        out = constrain(out, ("act_batch", "act_seq", "act_heads", "act_kv"))
        out = checkpoint_name(out, "attn_out")
        out = _dense(
            cfg.embed_dim, ("heads", "head_dim", "embed"), cfg, "o_proj",
            axis=(-2, -1),
        )(out)
        return constrain(out, ("act_batch", "act_seq", "act_embed"))

    def _train_attention(self, q, k, v) -> jax.Array:
        ctx = get_context()
        impl = ctx.attn_impl
        if impl == "sp_auto":
            # Resolve the measured ring/Ulysses crossover at trace time —
            # shapes here are global (sharding is logical), so seq_len is
            # the full context and sp_size the mesh extent.
            from kubeflow_tpu.parallel.policy import choose_sp_impl

            impl = choose_sp_impl(
                seq_len=q.shape[1], sp=ctx.sp_size,
                num_heads=q.shape[2], num_kv_heads=k.shape[2],
            ) if ctx.sp_size > 1 else "flash"
        if impl == "ring" and ctx.sp_size > 1:
            return ring_attention_sharded(
                q, k, v, ctx.mesh, causal=True
            )
        if impl == "ulysses" and ctx.sp_size > 1:
            return ulysses_attention_sharded(
                q, k, v, ctx.mesh, causal=True
            )
        if impl == "flash":
            if ctx.sp_size > 1:
                # Sequence-sharded activations: the pallas call can't be
                # SPMD-partitioned on seq, so route through the ring (which
                # itself uses the flash kernel per block when supported).
                return ring_attention_sharded(q, k, v, ctx.mesh, causal=True)
            # Fused pallas kernel (falls back to reference on un-blockable
            # shapes).
            return flash_attention(q, k, v, causal=True)
        return mha_reference(q, k, v, causal=True)

    def _decode_attention(self, q, k, v, mode=True,
                          stage_step=None) -> jax.Array:
        """Single-step (or prefill) attention against a mutable KV cache.

        Cache layout: [B, max_len, Hkv, Dh]; cache_index is **per-slot**
        ([B] int32) so the serving engine's continuous batching can hold
        sequences at different positions in one batch (each slot admits,
        prefills and decodes independently).

        ``mode == "prefill"`` asserts every row is fresh (cache_index 0, no
        prior context): the cache write still happens, but attention runs
        causally over just the incoming S_new tokens — via the flash kernel
        when blockable — instead of mask-attending the full max_len cache
        (8x less HBM traffic at bucket 128 vs max_len 1024)."""
        cfg = self.cfg
        B = q.shape[0]
        quant = cfg.kv_cache_dtype == "int8"
        store_dtype = jnp.int8 if quant else cfg.dtype
        is_init = not self.has_variable("cache", "cached_key")
        cached_key = self.variable(
            "cache", "cached_key",
            jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim),
            store_dtype,
        )
        cached_value = self.variable(
            "cache", "cached_value",
            jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim),
            store_dtype,
        )
        if quant:
            # Per-(slot, position, kv-head) absmax scales. Rank-4 with a
            # trailing singleton so engine cache sharding (which patterns
            # on [.., B, S, H, D] ranks) applies unchanged; f32 — the
            # scale overhead is 4 bytes per 128-byte row (~3%), which
            # still halves the KV footprint vs bf16.
            key_scale = self.variable(
                "cache", "key_scale",
                jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, 1),
                jnp.float32,
            )
            value_scale = self.variable(
                "cache", "value_scale",
                jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, 1),
                jnp.float32,
            )
        staging = cfg.decode_staging
        if staging > 0:
            # Chunk staging buffers (see LlamaConfig.decode_staging): the
            # decode write becomes one scalar-index DUS shared by every
            # slot; the engine flushes these into the main cache once per
            # chunk. Always the activation dtype — with an int8 main
            # cache, quantization happens at flush over C rows at once.
            stage_key = self.variable(
                "cache", "stage_key",
                jnp.zeros, (B, staging, cfg.num_kv_heads, cfg.head_dim),
                cfg.dtype,
            )
            stage_value = self.variable(
                "cache", "stage_value",
                jnp.zeros, (B, staging, cfg.num_kv_heads, cfg.head_dim),
                cfg.dtype,
            )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((B,), jnp.int32)
        )
        if not is_init and mode is True and staging > 0 \
                and stage_step is not None:
            # Staged decode step: write this step's k/v at the chunk-step
            # column (uniform across slots), attend over
            # [flushed cache | staged rows 0..stage_step].
            idx = cache_index.value                # flushed length [B]
            kc = k.astype(cfg.dtype)               # [B, 1, Hkv, D]
            vc = v.astype(cfg.dtype)
            stage_key.value = jax.lax.dynamic_update_slice_in_dim(
                stage_key.value, kc, stage_step, axis=1)
            stage_value.value = jax.lax.dynamic_update_slice_in_dim(
                stage_value.value, vc, stage_step, axis=1)
            return _staged_decode_attention(
                cfg, q, idx, stage_step,
                cached_key.value, cached_value.value,
                stage_key.value, stage_value.value,
                key_scale.value if quant else None,
                value_scale.value if quant else None,
            )
        if not is_init:
            idx = cache_index.value           # [B]
            S_new = q.shape[1]

            def upd(cache_row, new_row, i):
                return jax.lax.dynamic_update_slice(
                    cache_row, new_row,
                    (i,) + (0,) * (cache_row.ndim - 1)
                )

            if quant:
                k8, ks = quantize_kv_rows(k)
                v8, vs = quantize_kv_rows(v)
                cached_key.value = jax.vmap(upd)(cached_key.value, k8, idx)
                cached_value.value = jax.vmap(upd)(
                    cached_value.value, v8, idx)
                key_scale.value = jax.vmap(upd)(key_scale.value, ks, idx)
                value_scale.value = jax.vmap(upd)(value_scale.value, vs, idx)
            else:
                cached_key.value = jax.vmap(upd)(
                    cached_key.value, k.astype(cfg.dtype), idx)
                cached_value.value = jax.vmap(upd)(
                    cached_value.value, v.astype(cfg.dtype), idx)
            cache_index.value = idx + S_new
            if mode == "prefill":
                # Fresh rows: context == the incoming tokens themselves
                # (flash kernel when blockable; falls back internally) —
                # attention reads the LIVE k/v, so prefill accuracy is
                # unaffected by cache quantization.
                return flash_attention(q, k, v, causal=True)
            # Per-slot causal mask offset to each slot's filled prefix (the
            # not-yet-written tail is masked too: tail positions > q_pos).
            q_pos = idx[:, None] + jnp.arange(S_new)[None, :]      # [B,S]
            kv_pos = jnp.arange(cfg.max_seq_len)[None, None, :]
            mask = kv_pos <= q_pos[:, :, None]                      # [B,S,L]
            if quant:
                # The int8 cache enters the attention einsums through a
                # bare convert (fused as an operand conversion — NO
                # dequantized cache copy in HBM; a materialised dequant
                # measured -20% tok/s at 8B); scales apply on the small
                # logits/weights side inside mha_reference.
                return mha_reference(
                    q, cached_key.value, cached_value.value,
                    mask=mask[:, None, :, :],
                    k_scale=key_scale.value, v_scale=value_scale.value,
                )
            return mha_reference(q, cached_key.value, cached_value.value,
                                 mask=mask[:, None, :, :])
        return mha_reference(q, k, v, causal=True)

    def _paged_decode_attention(self, q, k, v, mode=True, stage_step=None,
                                block_tables=None, write_lens=None):
        """Decode/prefill attention against the PHYSICALLY PAGED pool
        (cfg.paged_kv_blocks > 0; layout + exactness contract in
        ops/paged_attention.py).

        Cache layout per layer: cached_key/cached_value are one
        [P + 1, block_size, Hkv, Dh] pool shared by every slot (block P
        = the scratch page); cache_index stays per-slot [B]. Writes land
        at the physical rows ``block_tables`` maps each position to —
        ``write_lens`` (prefill) redirects pad columns past each row's
        true length to scratch, and positions past a table's allocated
        span redirect automatically, so no junk write can touch a live
        (possibly SHARED, copy-on-write) page. Reads gather the pages
        back into dense position order and run the same reference
        attention the dense cache runs — including the int8-KV
        fused-dequant path via gathered scale pools."""
        cfg = self.cfg
        B = q.shape[0]
        quant = cfg.kv_cache_dtype == "int8"
        store_dtype = jnp.int8 if quant else cfg.dtype
        P, bs = cfg.paged_kv_blocks, cfg.paged_kv_block_size
        Hkv, Dh = cfg.num_kv_heads, cfg.head_dim
        is_init = not self.has_variable("cache", "cached_key")
        cached_key = self.variable(
            "cache", "cached_key",
            jnp.zeros, pool_shape(P, bs, Hkv, Dh), store_dtype,
        )
        cached_value = self.variable(
            "cache", "cached_value",
            jnp.zeros, pool_shape(P, bs, Hkv, Dh), store_dtype,
        )
        if quant:
            key_scale = self.variable(
                "cache", "key_scale",
                jnp.zeros, pool_shape(P, bs, Hkv, Dh, trailing=1),
                jnp.float32,
            )
            value_scale = self.variable(
                "cache", "value_scale",
                jnp.zeros, pool_shape(P, bs, Hkv, Dh, trailing=1),
                jnp.float32,
            )
        staging = cfg.decode_staging
        if staging > 0:
            stage_key = self.variable(
                "cache", "stage_key",
                jnp.zeros, (B, staging, Hkv, Dh), cfg.dtype,
            )
            stage_value = self.variable(
                "cache", "stage_value",
                jnp.zeros, (B, staging, Hkv, Dh), cfg.dtype,
            )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((B,), jnp.int32)
        )
        if is_init or block_tables is None:
            # Shape-only init (engine _init_cache) or a caller that never
            # wired tables: no pool I/O, plain causal attention.
            return mha_reference(q, k, v, causal=True)
        idx = cache_index.value                    # [B]
        S_new = q.shape[1]
        if mode is True and staging > 0 and stage_step is not None:
            # Staged decode step: stage write is identical to dense
            # (per-slot staging rows are NOT paged — they are B x C
            # working rows, not cache residency); attention gathers the
            # pool into dense order and joins [pool | staged] in one
            # softmax exactly as the dense staged path does.
            stage_key.value = jax.lax.dynamic_update_slice_in_dim(
                stage_key.value, k.astype(cfg.dtype), stage_step, axis=1)
            stage_value.value = jax.lax.dynamic_update_slice_in_dim(
                stage_value.value, v.astype(cfg.dtype), stage_step, axis=1)
            return _staged_decode_attention(
                cfg, q, idx, stage_step,
                gather_kv_pages(cached_key.value, block_tables, bs),
                gather_kv_pages(cached_value.value, block_tables, bs),
                stage_key.value, stage_value.value,
                gather_kv_pages(key_scale.value, block_tables, bs)
                if quant else None,
                gather_kv_pages(value_scale.value, block_tables, bs)
                if quant else None,
            )
        positions = idx[:, None] + jnp.arange(S_new)[None, :]   # [B, S]
        valid = None
        if write_lens is not None:
            valid = positions < write_lens[:, None]
        rows = physical_rows(block_tables, positions, bs,
                             num_blocks=P, valid=valid)
        if quant:
            k8, ks = quantize_kv_rows(k)
            v8, vs = quantize_kv_rows(v)
            cached_key.value = scatter_kv_rows(cached_key.value, rows, k8)
            cached_value.value = scatter_kv_rows(
                cached_value.value, rows, v8)
            key_scale.value = scatter_kv_rows(key_scale.value, rows, ks)
            value_scale.value = scatter_kv_rows(value_scale.value, rows, vs)
        else:
            cached_key.value = scatter_kv_rows(
                cached_key.value, rows, k.astype(cfg.dtype))
            cached_value.value = scatter_kv_rows(
                cached_value.value, rows, v.astype(cfg.dtype))
        cache_index.value = idx + S_new
        if mode == "prefill":
            # Fresh rows attend only the LIVE k/v (same as dense prefill:
            # no cache read, quantization-independent accuracy).
            return flash_attention(q, k, v, causal=True)
        return paged_decode_attention(
            q, cached_key.value, cached_value.value, block_tables,
            positions, bs,
            key_scale_pool=key_scale.value if quant else None,
            value_scale_pool=value_scale.value if quant else None,
        )


def quantize_kv_rows(x):
    """Absmax int8 per (.., position, kv-head) row: returns (int8 rows,
    f32 scales [..., 1]). Shared by the per-step cache write and the
    serving engine's staged-chunk flush so the two paths cannot diverge."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    return jnp.round(x.astype(jnp.float32) / s).astype(jnp.int8), s


def _staged_decode_attention(cfg, q, idx, stage_step, ck, cv, sk, sv,
                             k_scale, v_scale):
    """One decode step's attention over [flushed cache | staging rows].
    The big cache tensors never concatenate — only the [.., S] and
    [.., C] SCORE vectors do, and one softmax spans both parts (exactly
    the joint distribution). Mirrors mha_reference's GQA fold and its
    int8 scale placement (scales on the score/weight side, cache through
    a fused convert)."""
    B, Sq, H, D = q.shape                      # Sq == 1 at decode
    S, C = ck.shape[1], sk.shape[1]
    Hkv = ck.shape[2]
    G = H // Hkv
    scale = D ** -0.5
    qg = q.reshape(B, Sq, Hkv, G, D)
    s1 = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, ck.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    if k_scale is not None:
        s1 = s1 * k_scale[..., 0].transpose(0, 2, 1)[:, :, None, None, :]
    valid_main = jnp.arange(S)[None, :] < idx[:, None]          # [B, S]
    s1 = jnp.where(valid_main[:, None, None, None, :], s1, -jnp.inf)
    s2 = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, sk.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    valid_stage = jnp.arange(C) <= stage_step                   # [C]
    s2 = jnp.where(valid_stage[None, None, None, None, :], s2, -jnp.inf)
    w = jax.nn.softmax(jnp.concatenate([s1, s2], axis=-1), axis=-1)
    w1, w2 = w[..., :S], w[..., S:]
    if v_scale is not None:
        w1 = w1 * v_scale[..., 0].transpose(0, 2, 1)[:, :, None, None, :]
    out = (
        jnp.einsum(
            "bhgqk,bkhd->bqhgd", w1.astype(q.dtype), cv.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )
        + jnp.einsum(
            "bhgqk,bkhd->bqhgd", w2.astype(q.dtype), sv.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )
    )
    return out.reshape(B, Sq, H, D).astype(q.dtype)


class Mlp(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        if cfg.fused_gate_up:
            gu = _dense(
                (2, cfg.mlp_dim), ("embed", "gate_up", "mlp"), cfg,
                "gate_up_proj",
            )(x)                                   # [B, S, 2, mlp]
            gate, up = gu[..., 0, :], gu[..., 1, :]
        else:
            gate = _dense(cfg.mlp_dim, ("embed", "mlp"), cfg, "gate_proj")(x)
            up = _dense(cfg.mlp_dim, ("embed", "mlp"), cfg, "up_proj")(x)
        gate = checkpoint_name(gate, "mlp_gate")
        up = checkpoint_name(up, "mlp_up")
        h = nn.silu(gate) * up
        h = constrain(h, ("act_batch", "act_seq", "act_mlp"))
        out = _dense(cfg.embed_dim, ("mlp", "embed"), cfg, "down_proj")(h)
        return constrain(out, ("act_batch", "act_seq", "act_embed"))


class DecoderLayer(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: jax.Array, decode: bool = False,
        stage_step=None, block_tables=None, write_lens=None,
    ) -> jax.Array:
        cfg = self.cfg
        h = RMSNorm(cfg, name="input_norm")(x)
        h = Attention(cfg, name="attn")(h, positions, decode=decode,
                                        stage_step=stage_step,
                                        block_tables=block_tables,
                                        write_lens=write_lens)
        x = x + h
        h = RMSNorm(cfg, name="post_attn_norm")(x)
        h = Mlp(cfg, name="mlp")(h)
        return x + h


class Llama(nn.Module):
    """Decoder-only LM. __call__ returns logits [B, S, vocab].

    Subclass hook points (Mixtral overrides these; everything else —
    embedding, scan/remat plumbing, final norm, lm head, tied embeddings,
    logit softcap — is shared backbone):
    - ``LAYER_CLS``: the per-layer module
    - ``SCAN_COLLECTIONS`` / ``SCAN_RNGS``: extra variable collections /
      rng streams threaded through nn.scan
    """

    cfg: LlamaConfig

    # Deliberately un-annotated: annotations would make these flax dataclass
    # fields, whose parent defaults shadow subclass overrides.
    LAYER_CLS = DecoderLayer
    SCAN_COLLECTIONS = ("params", "cache")
    SCAN_RNGS = ("params",)

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: Optional[jax.Array] = None,
        decode: bool = False,
        return_hidden: bool = False,
        stage_step=None,
        block_tables=None,
        write_lens=None,
    ) -> jax.Array:
        cfg = self.cfg
        B, S = tokens.shape
        if positions is None:
            # Decode callers pass absolute positions explicitly (the serving
            # engine tracks per-sequence offsets); default is prefill order.
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))

        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.embed_dim),
            cfg.param_dtype,
        )
        if _vocab_axis_sharded():
            # One-hot matmul instead of gather: SPMD partitions a contraction
            # over the tp-sharded vocab axis cleanly (psum over shards),
            # whereas a gather whose indexed dim is sharded forces XLA into
            # "involuntary full rematerialization" (replicate + repartition
            # of [B,S,E] every step). XLA fuses the one-hot into the matmul,
            # so it never materialises [B,S,V].
            one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
            x = jnp.einsum(
                "bsv,ve->bse", one_hot, embed.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            ).astype(cfg.dtype)
        else:
            x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
        x = constrain(x, ("act_batch", "act_seq", "act_embed"))

        layer_cls = type(self).LAYER_CLS
        if cfg.remat:
            layer_cls = nn.remat(
                layer_cls,
                # Inside any scan (layer scan or pipeline stage scan) XLA's
                # loop structure already prevents the CSE remat defends against.
                prevent_cse=not (cfg.scan_layers or cfg.pipeline_stages > 1),
                static_argnums=(3,),  # decode flag (self is argnum 0)
                policy=_remat_policy(cfg.remat_policy),
            )

        if cfg.pipeline_stages > 1:
            if decode:
                raise ValueError(
                    "pipeline_stages>1 is a training layout; decode/serving "
                    "uses tp/sp (a one-token step is all pipeline bubble)"
                )
            x = PipelinedLayers(
                cfg,
                layer_cls=layer_cls,
                num_stages=cfg.pipeline_stages,
                num_microbatches=cfg.pipeline_microbatches
                or cfg.pipeline_stages,
                name="pipeline",
            )(x, positions)
        elif cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (
                    mdl(carry, positions, decode, stage_step,
                        block_tables, write_lens), None),
                variable_axes={c: 0 for c in self.SCAN_COLLECTIONS},
                split_rngs={r: True for r in self.SCAN_RNGS},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(layer_cls(cfg, name="layers"), x, None)
        else:
            for i in range(cfg.num_layers):
                x = layer_cls(cfg, name=f"layer_{i}")(
                    x, positions, decode, stage_step,
                    block_tables, write_lens)

        x = RMSNorm(cfg, name="final_norm")(x)
        if return_hidden:
            # Chunked-loss path (train.losses.chunked_cross_entropy): the
            # caller owns the lm_head matmul so [B,S,V] logits never
            # materialise. The lm_head params must still exist for
            # checkpoints/serving parity, so touch the Dense without
            # running it on real data (init cost: one [1,E] row).
            if not cfg.tie_embeddings:
                _dense(cfg.vocab_size, ("embed", "vocab"), cfg, "lm_head")(
                    jax.lax.stop_gradient(x[:1, :1])
                )
            return x
        out_dtype = jnp.float32 if cfg.logits_f32 else cfg.dtype
        if cfg.tie_embeddings:
            logits = jnp.einsum(
                "bse,ve->bsv", x, embed.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            ).astype(out_dtype)
        else:
            logits = _dense(
                cfg.vocab_size, ("embed", "vocab"), cfg, "lm_head"
            )(x).astype(out_dtype)
        if cfg.logits_softcap > 0:
            logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
        return constrain(logits, ("act_batch", "act_seq", "act_vocab"))

    # Bound at module bottom: HEAD_LOGITS = staticmethod(head_logits) —
    # the serving engine calls type(model).HEAD_LOGITS(cfg, params, x) to
    # run the logits tail on one position per row at prefill. Carried as
    # the callable (not a flag) so a model family with a different param
    # tree must supply its own implementation rather than inheriting a
    # llama-shaped one by accident.

    def num_params(self) -> int:
        cfg = self.cfg
        per_layer = (
            cfg.embed_dim * cfg.num_heads * cfg.head_dim
            + 2 * cfg.embed_dim * cfg.num_kv_heads * cfg.head_dim
            + cfg.num_heads * cfg.head_dim * cfg.embed_dim
            + 3 * cfg.embed_dim * cfg.mlp_dim
            + 2 * cfg.embed_dim
        )
        head = 0 if cfg.tie_embeddings else cfg.vocab_size * cfg.embed_dim
        return (
            cfg.vocab_size * cfg.embed_dim
            + cfg.num_layers * per_layer
            + cfg.embed_dim
            + head
        )


def head_logits(cfg: LlamaConfig, params, x: jax.Array) -> jax.Array:
    """The logits tail (lm_head / tied embedding + softcap) as a pure
    function over the param tree: serving prefill runs it on just each
    row's LAST hidden state — the full [k, bucket, V] prefill logits are
    discarded except one row each, and at 128k vocab x bucket 512 they
    are a 3.9 GB HBM blocker for 8B serving. Mirrors Llama.__call__'s
    tail op-for-op (same dtype promotion as the DenseGeneral it
    replaces); pinned against the model by
    tests/test_models.py::TestHeadLogits."""
    params = nn.meta.unbox(params)
    x = x.astype(cfg.dtype)
    out_dtype = jnp.float32 if cfg.logits_f32 else cfg.dtype
    if cfg.tie_embeddings:
        logits = jnp.einsum(
            "bse,ve->bsv", x, params["embed"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ).astype(out_dtype)
    else:
        logits = jnp.einsum(
            "bse,ev->bsv", x,
            params["lm_head"]["kernel"].astype(cfg.dtype),
        ).astype(out_dtype)
    if cfg.logits_softcap > 0:
        logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
    return logits


Llama.HEAD_LOGITS = staticmethod(head_logits)
