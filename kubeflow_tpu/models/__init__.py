"""Model zoo: the training/serving payloads of the platform.

The reference's "model zoo" is a single containerised tf_cnn_benchmarks
ResNet-50 TFJob payload (reference: tf-controller-examples/tf-cnn/
create_job_specs.py:96-180); here the models are first-class framework code,
written once with logical-axis sharding and reused by training, serving and
HPO (BASELINE.md configs 1-5).
"""

from kubeflow_tpu.models.llama import Llama, LlamaConfig
from kubeflow_tpu.models.mixtral import Mixtral, MixtralConfig
from kubeflow_tpu.models.resnet import ResNet, ResNetConfig
from kubeflow_tpu.models.vit import ViT, ViTConfig
from kubeflow_tpu.models.registry import get_model, list_models, register_model

__all__ = [
    "Llama",
    "LlamaConfig",
    "Mixtral",
    "MixtralConfig",
    "ResNet",
    "ResNetConfig",
    "ViT",
    "ViTConfig",
    "get_model",
    "list_models",
    "register_model",
]
