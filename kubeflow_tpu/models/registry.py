"""Model registry: name -> (module class, config factory).

The platform's job specs reference models by name (the analogue of the
reference's image+flags payload contract, tf-controller-examples/tf-cnn/
create_job_specs.py:96-117); the registry is how the TpuJob runtime, the
serving engine and HPO trials resolve them.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

_REGISTRY: Dict[str, Tuple[type, Callable[..., object]]] = {}


def register_model(name: str, module_cls: type, config_factory) -> None:
    if name in _REGISTRY:
        raise ValueError(f"model {name!r} already registered")
    _REGISTRY[name] = (module_cls, config_factory)


def get_model(name: str, **config_kw):
    """Returns (flax module instance, config)."""
    try:
        module_cls, factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    cfg = factory(**config_kw)
    return module_cls(cfg), cfg


def list_models():
    return sorted(_REGISTRY)


def _register_defaults() -> None:
    from kubeflow_tpu.models.llama import Llama, LlamaConfig
    from kubeflow_tpu.models.mixtral import Mixtral, MixtralConfig
    from kubeflow_tpu.models.resnet import ResNet, ResNetConfig
    from kubeflow_tpu.models.vit import ViT, ViTConfig

    register_model("llama3-8b", Llama, LlamaConfig.llama3_8b)
    register_model("llama3-70b", Llama, LlamaConfig.llama3_70b)
    register_model("llama-tiny", Llama, LlamaConfig.tiny)
    register_model("mixtral-8x7b", Mixtral, MixtralConfig.mixtral_8x7b)
    register_model("mixtral-tiny", Mixtral, MixtralConfig.tiny)
    register_model("resnet50", ResNet, ResNetConfig.resnet50)
    register_model("resnet101", ResNet, ResNetConfig.resnet101)
    register_model("resnet-tiny", ResNet, ResNetConfig.tiny)
    register_model("vit-l16", ViT, ViTConfig.vit_l16)
    register_model("vit-b16", ViT, ViTConfig.vit_b16)
    register_model("vit-tiny", ViT, ViTConfig.tiny)


_register_defaults()
