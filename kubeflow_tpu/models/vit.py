"""Vision Transformer — the HPO trial workload (BASELINE config 4:
Katib-equivalent sweeps run ViT-L/16 trial workers on TPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.parallel.context import constrain


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    embed_dim: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    mlp_dim: int = 4096
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def vit_l16(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def vit_b16(cls, **kw) -> "ViTConfig":
        return cls(embed_dim=768, num_layers=12, num_heads=12, mlp_dim=3072, **kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("num_classes", 10)
        kw.setdefault("embed_dim", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("mlp_dim", 128)
        return cls(**kw)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def _dense(features, kernel_axes, cfg, name, axis=-1):
    return nn.DenseGeneral(
        features=features,
        axis=axis,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.xavier_uniform(), kernel_axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros, kernel_axes[-1:]
        ),
        name=name,
    )


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        cfg = self.cfg
        H = cfg.num_heads
        Dh = cfg.embed_dim // H
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln1")(x)
        q = _dense((H, Dh), ("embed", "heads", "head_dim"), cfg, "q")(h)
        k = _dense((H, Dh), ("embed", "heads", "head_dim"), cfg, "k")(h)
        v = _dense((H, Dh), ("embed", "heads", "head_dim"), cfg, "v")(h)
        attn = mha_reference(q, k, v, causal=False)
        attn = _dense(
            cfg.embed_dim, ("heads", "head_dim", "embed"), cfg, "out",
            axis=(-2, -1),
        )(attn)
        attn = nn.Dropout(cfg.dropout, name="drop_attn")(
            attn, deterministic=deterministic
        )
        x = x + attn
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln2")(x)
        h = _dense(cfg.mlp_dim, ("embed", "mlp"), cfg, "mlp_in")(h)
        h = nn.gelu(h)
        h = constrain(h, ("act_batch", "act_seq", "act_mlp"))
        h = _dense(cfg.embed_dim, ("mlp", "embed"), cfg, "mlp_out")(h)
        h = nn.Dropout(cfg.dropout, name="drop_mlp")(
            h, deterministic=deterministic
        )
        return x + h


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images: jax.Array, *, train: bool = False) -> jax.Array:
        """images: [B, H, W, 3]. Returns logits [B, num_classes]."""
        cfg = self.cfg
        B = images.shape[0]
        p = cfg.patch_size
        # Stride-p conv IS the right TPU form for patch embedding: a
        # reshape+transpose+matmul formulation was measured 30x slower
        # (the [B,gh,p,gw,p,C] transpose with C=3 in the minor dim is a
        # strided-HBM shuffle; XLA's conv path handles the layout on the
        # way into the MXU instead).
        x = nn.Conv(
            cfg.embed_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(),
                ("conv_h", "conv_w", "conv_in", "embed"),
            ),
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.embed_dim)  # [B, N, E]

        cls_tok = self.param(
            "cls",
            nn.with_logical_partitioning(nn.initializers.zeros, (None, None, "embed")),
            (1, 1, cfg.embed_dim), cfg.param_dtype,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls_tok, (B, 1, cfg.embed_dim)).astype(cfg.dtype), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (None, None, "embed")
            ),
            (1, cfg.num_patches + 1, cfg.embed_dim), cfg.param_dtype,
        )
        x = x + pos.astype(cfg.dtype)
        x = constrain(x, ("act_batch", "act_seq", "act_embed"))

        for i in range(cfg.num_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, deterministic=not train)

        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln_final")(x)
        logits = nn.Dense(
            cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", "vocab")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("vocab",)
            ),
            name="head",
        )(x[:, 0])
        return logits.astype(jnp.float32)
