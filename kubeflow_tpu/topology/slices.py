"""TPU slice catalogue: generations, ICI topologies, host layouts.

Replaces the reference's accelerator model (a bare integer of
``nvidia.com/gpu`` on an interchangeable node,
reference: components/jupyter-web-app/backend/kubeflow_jupyter/common/utils.py:390-443)
with a typed slice spec. A slice name like ``v5e-16`` fully determines:
chip count, ICI topology shape (mesh or torus per dimension), number of
TPU-VM hosts, and chips per host — everything the gang scheduler and the
mesh planner need.

Numbers follow the public Cloud TPU documentation: v4/v5p are 3D tori
(4 chips/host), v5e/v6e are 2D meshes (up to 8 chips/host single-host,
4 chips/host multi-host), with wraparound links on dimensions of size >= 16
for v5e-256-class slices.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, List, Tuple


class TpuGeneration(str, enum.Enum):
    V4 = "v4"
    V5E = "v5e"
    V5P = "v5p"
    V6E = "v6e"

    @property
    def hbm_gib_per_chip(self) -> float:
        return {"v4": 32.0, "v5e": 16.0, "v5p": 95.0, "v6e": 32.0}[self.value]

    @property
    def bf16_tflops_per_chip(self) -> float:
        return {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}[self.value]

    @property
    def is_3d(self) -> bool:
        return self in (TpuGeneration.V4, TpuGeneration.V5P)


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """ICI topology of a slice: per-dimension extent and wraparound."""

    dims: Tuple[int, ...]            # e.g. (4, 4) for v5e-16, (4, 4, 4) for v4-128
    wrap: Tuple[bool, ...]           # torus link per dimension

    def __post_init__(self) -> None:
        if len(self.dims) != len(self.wrap):
            raise ValueError(f"dims {self.dims} and wrap {self.wrap} length mismatch")

    @property
    def num_chips(self) -> int:
        return math.prod(self.dims)

    @property
    def rank(self) -> int:
        return len(self.dims)

    def ring_dims(self) -> List[int]:
        """Indices of dimensions that form a true ICI ring (wraparound, or
        extent <= 2 where a mesh is trivially a ring)."""
        return [i for i, (d, w) in enumerate(zip(self.dims, self.wrap)) if w or d <= 2]

    def largest_ring(self) -> int:
        """Extent of the largest dimension usable as a true bidirectional
        ring (wraparound, or extent <= 2). Open mesh lines are excluded —
        callers sizing ring-dependent axes (sp/ep) must not land on them;
        use max(dims) directly for span-tolerant axes."""
        return max((self.dims[i] for i in self.ring_dims()), default=1)


@dataclasses.dataclass(frozen=True)
class SliceType:
    """A named, schedulable TPU slice (the unit TpuJob gangs are placed on)."""

    name: str                        # e.g. "v5e-16"
    generation: TpuGeneration
    topology: SliceTopology
    chips_per_host: int              # chips on one TPU-VM host
    # GKE node-selector values, the TPU analogue of the reference's
    # nvidia.com/gpu limit + accelerator node selectors.
    gke_accelerator: str = ""
    gke_topology: str = ""           # e.g. "4x4"

    @property
    def num_chips(self) -> int:
        return self.topology.num_chips

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)

    @property
    def hbm_gib_total(self) -> float:
        return self.num_chips * self.generation.hbm_gib_per_chip

    @property
    def bf16_tflops_total(self) -> float:
        return self.num_chips * self.generation.bf16_tflops_per_chip

    def node_selectors(self) -> Dict[str, str]:
        """K8s node selectors for ICI-topology-aware placement — replaces the
        reference's GPU vendor selectors (SURVEY.md §2.5 gang-scheduling row)."""
        return {
            "cloud.google.com/gke-tpu-accelerator": self.gke_accelerator,
            "cloud.google.com/gke-tpu-topology": self.gke_topology,
        }

    def resource_name(self) -> str:
        """K8s extended-resource name requested per pod (chips per host)."""
        return "google.com/tpu"


_REGISTRY: Dict[str, SliceType] = {}


def register_slice(s: SliceType) -> SliceType:
    if s.name in _REGISTRY:
        raise ValueError(f"slice {s.name!r} already registered")
    _REGISTRY[s.name] = s
    return s


def get_slice(name: str) -> SliceType:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown slice type {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def list_slices() -> List[str]:
    return sorted(_REGISTRY)


def _mk2d(x: int, y: int, wrap: bool = False) -> SliceTopology:
    return SliceTopology(dims=(x, y), wrap=(wrap, wrap))


def _mk3d(x: int, y: int, z: int, wrap: Tuple[bool, bool, bool]) -> SliceTopology:
    return SliceTopology(dims=(x, y, z), wrap=wrap)


def _register_defaults() -> None:
    v5e = "tpu-v5-lite-podslice"
    # v5e: 2D mesh; single-host slices up to 8 chips, multi-host 4 chips/host.
    for name, (x, y), cph in [
        ("v5e-1", (1, 1), 1),
        ("v5e-4", (2, 2), 4),
        ("v5e-8", (2, 4), 8),
        ("v5e-16", (4, 4), 4),
        ("v5e-32", (4, 8), 4),
        ("v5e-64", (8, 8), 4),
        ("v5e-128", (8, 16), 4),
        ("v5e-256", (16, 16), 4),
    ]:
        wrap = x >= 16 and y >= 16
        register_slice(
            SliceType(
                name=name,
                generation=TpuGeneration.V5E,
                topology=_mk2d(x, y, wrap),
                chips_per_host=cph,
                gke_accelerator=v5e,
                gke_topology=f"{x}x{y}",
            )
        )

    v6e = "tpu-v6e-slice"
    for name, (x, y), cph in [
        ("v6e-1", (1, 1), 1),
        ("v6e-4", (2, 2), 4),
        ("v6e-8", (2, 4), 8),
        ("v6e-16", (4, 4), 4),
        ("v6e-64", (8, 8), 4),
        ("v6e-256", (16, 16), 4),
    ]:
        wrap = x >= 16 and y >= 16
        register_slice(
            SliceType(
                name=name,
                generation=TpuGeneration.V6E,
                topology=_mk2d(x, y, wrap),
                chips_per_host=cph,
                gke_accelerator=v6e,
                gke_topology=f"{x}x{y}",
            )
        )

    # v4 / v5p: 3D; wraparound when a dimension reaches the full cube extent
    # (public rule of thumb: dims that are a multiple of 4 on full-cube slices
    # get torus links; we wrap dims >= 4 when the slice is a full cube).
    for gen, accel, cases in [
        (
            TpuGeneration.V4,
            "tpu-v4-podslice",
            [
                ("v4-8", (2, 2, 1)),
                ("v4-16", (2, 2, 2)),
                ("v4-32", (2, 2, 4)),
                ("v4-64", (2, 4, 4)),
                ("v4-128", (4, 4, 4)),
                ("v4-256", (4, 4, 8)),
                ("v4-512", (4, 8, 8)),
            ],
        ),
        (
            TpuGeneration.V5P,
            "tpu-v5p-slice",
            [
                ("v5p-8", (2, 2, 1)),
                ("v5p-16", (2, 2, 2)),
                ("v5p-32", (2, 2, 4)),
                ("v5p-64", (2, 4, 4)),
                ("v5p-128", (4, 4, 4)),
                ("v5p-256", (4, 4, 8)),
            ],
        ),
    ]:
        for name, (x, y, z) in cases:
            cube = x == y == z
            wrap = tuple(cube and d >= 4 for d in (x, y, z))
            register_slice(
                SliceType(
                    name=name,
                    generation=gen,
                    topology=_mk3d(x, y, z, wrap),  # type: ignore[arg-type]
                    chips_per_host=4,
                    gke_accelerator=accel,
                    gke_topology=f"{x}x{y}x{z}",
                )
            )


_register_defaults()
