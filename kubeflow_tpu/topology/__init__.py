"""TPU slice topology: the scheduling substrate of the platform.

In the reference, accelerators are an opaque resource count
(``nvidia.com/gpu`` limits injected by the spawner UI,
reference: components/jupyter-web-app/backend/kubeflow_jupyter/common/utils.py:390-443)
and multi-worker wiring is a flat hostname list (``TF_CONFIG``,
reference: tf-controller-examples/tf-cnn/launcher.py:68-80). On TPU the
interconnect topology *is* the resource: a slice is a named ICI mesh/torus
(e.g. ``v5e-16`` = a 4x4 mesh of chips across 4 hosts) and performance
depends on mapping parallelism axes onto ICI rings. This package owns that
mapping.
"""

from kubeflow_tpu.topology.slices import (
    SliceType,
    SliceTopology,
    TpuGeneration,
    get_slice,
    list_slices,
    register_slice,
)
from kubeflow_tpu.topology.mesh import (
    AxisSpec,
    MeshPlan,
    plan_mesh,
    make_mesh,
    make_host_local_mesh,
    make_multislice_mesh,
)

__all__ = [
    "SliceType",
    "SliceTopology",
    "TpuGeneration",
    "get_slice",
    "list_slices",
    "register_slice",
    "AxisSpec",
    "MeshPlan",
    "plan_mesh",
    "make_mesh",
    "make_host_local_mesh",
    "make_multislice_mesh",
]
