"""ICI-topology-aware mesh planning.

Given a slice type and a logical parallelism request (dp/fsdp/tp/sp/ep
extents), produce a ``jax.sharding.Mesh`` whose logical axes map onto ICI
dimensions so that the heaviest collectives ride physical rings:

- ``tp`` (tensor parallel, per-layer allreduce/reduce-scatter) gets the
  innermost / smallest ICI span — its collectives are on the critical path
  of every matmul.
- ``sp`` (sequence/context parallel, ring attention ppermute) must map onto
  a contiguous ICI line or ring — neighbour exchange is its whole traffic.
- ``fsdp`` (weight allgather / grad reduce-scatter) next.
- ``dp`` (pure data parallel, one allreduce per step) tolerates the longest
  span, including DCN across slices.
- ``ep`` (expert parallel all-to-all) prefers a full ring dimension.

The reference has no analogue — its deepest parallelism wiring is replica
counts + a hostname list (reference: tf-controller-examples/tf-cnn/
create_job_specs.py:96-180, launcher.py:68-80); mapping onto the physical
interconnect was NCCL's job inside opaque images. On TPU this mapping is the
framework's job and is decided *before* the gang is scheduled, so the
controller can request a matching GKE topology.
"""

from __future__ import annotations

import dataclasses
import math
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.topology.slices import SliceType, get_slice

if TYPE_CHECKING:  # pragma: no cover
    import jax
    from jax.sharding import Mesh

# jax/numpy are imported lazily inside the mesh-MATERIALISING functions:
# planning (plan_mesh/AxisSpec) is pure math, and the control plane — in
# particular every sharded shard process (controlplane/shard.py) — imports
# this module only to plan and validate. Keeping jax off that path cuts a
# shard's cold start from ~4s to well under a second, which is what makes
# crash-replay restarts and per-(kind, namespace) shard processes cheap.

# Canonical logical axis order: outermost (cheapest collectives / DCN-ok)
# first, innermost (latency-critical) last. This is also the mesh-axis order
# used by every sharding rule in kubeflow_tpu.parallel.
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "ep", "fsdp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class AxisSpec:
    """Logical parallelism extents. -1 for at most one axis means 'absorb all
    remaining chips' (mirrors jnp reshape convention)."""

    dp: int = 1
    pp: int = 1
    ep: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    def as_dict(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, num_chips: int) -> "AxisSpec":
        d = self.as_dict()
        bad = [a for a, v in d.items() if v < 1 and v != -1]
        if bad:
            raise ValueError(
                f"axis extents must be >= 1 (or -1 wildcard); got "
                f"{ {a: d[a] for a in bad} }"
            )
        wild = [a for a, v in d.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in d.values() if v != -1)
        if wild:
            if num_chips % fixed != 0:
                raise ValueError(
                    f"chips {num_chips} not divisible by fixed axes product {fixed}"
                )
            d[wild[0]] = num_chips // fixed
        total = math.prod(d.values())
        if total != num_chips:
            raise ValueError(
                f"axis product {total} != chips {num_chips} (spec {d})"
            )
        return AxisSpec(**d)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A fully resolved plan: logical axes, their extents, and the physical
    ICI assignment behind each (for the scheduler and for diagnostics)."""

    slice_name: str
    axes: AxisSpec
    axis_names: Tuple[str, ...]          # always all of AXIS_ORDER (size-1 axes kept)
    axis_sizes: Tuple[int, ...]
    # Heuristic, human-readable account of which ICI dims *should* back each
    # logical axis. Diagnostics and scheduler hints only: make_mesh delegates
    # the actual device arrangement to mesh_utils.create_device_mesh, whose
    # placement may differ. Do not treat as the runtime mapping.
    ici_assignment: Dict[str, str]

    @property
    def num_chips(self) -> int:
        return math.prod(self.axis_sizes)


def plan_mesh(slice_type: str | SliceType, axes: AxisSpec) -> MeshPlan:
    """Resolve an AxisSpec against a slice and record the ICI assignment.

    Assignment strategy: walk axes innermost-first (tp, sp, fsdp, ep, dp) and
    greedily consume ICI dimensions smallest-first for tp (minimise hop count)
    and ring-dims-first for sp/ep (neighbour exchange wants wraparound).
    """
    st = get_slice(slice_type) if isinstance(slice_type, str) else slice_type
    resolved = axes.resolve(st.num_chips)
    d = resolved.as_dict()

    # Track remaining capacity per physical dim.
    capacity = list(st.topology.dims)
    ring = set(st.topology.ring_dims())
    assignment: Dict[str, str] = {}

    def consume(axis: str, extent: int, dim_pref: List[int]) -> None:
        if extent == 1:
            assignment[axis] = "-"
            return
        rem = extent
        parts = []
        for i in dim_pref:
            if rem == 1:
                break
            g = math.gcd(rem, capacity[i])
            if g > 1:
                capacity[i] //= g
                rem //= g
                parts.append(f"ici{i}:{g}")
        if rem != 1:
            # Fall back: the axis spans host boundaries / mixed dims; still
            # valid for XLA, just record it as spanning.
            parts.append(f"span:{rem}")
            # consume whatever is left
            for i in range(len(capacity)):
                g = math.gcd(rem, capacity[i])
                capacity[i] //= g
                rem //= g
            if rem != 1:
                raise ValueError(
                    f"axis {axis}={extent} does not fit slice {st.name} "
                    f"(topology {st.topology.dims})"
                )
        assignment[axis] = "*".join(parts)

    n = len(capacity)
    by_small = sorted(range(n), key=lambda i: st.topology.dims[i])
    by_ring_then_large = sorted(
        range(n), key=lambda i: (0 if i in ring else 1, -st.topology.dims[i])
    )
    by_large = sorted(range(n), key=lambda i: -st.topology.dims[i])

    consume("tp", d["tp"], by_small)
    consume("sp", d["sp"], by_ring_then_large)
    consume("fsdp", d["fsdp"], by_large)
    consume("ep", d["ep"], by_ring_then_large)
    # pp's one-hop-per-tick CollectivePermute tolerates long spans (even
    # DCN between slices), so it consumes after the bandwidth-bound axes.
    consume("pp", d["pp"], by_ring_then_large)
    consume("dp", d["dp"], by_large)

    names = tuple(AXIS_ORDER)
    sizes = tuple(d[a] for a in names)
    return MeshPlan(
        slice_name=st.name,
        axes=resolved,
        axis_names=names,
        axis_sizes=sizes,
        ici_assignment=assignment,
    )


def make_mesh(
    plan: MeshPlan,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Materialise a MeshPlan as a jax.sharding.Mesh over real devices.

    On real TPU hardware we delegate device ordering to
    ``jax.experimental.mesh_utils.create_device_mesh``, which knows the
    physical coordinates and keeps mesh-adjacent devices ICI-adjacent. On CPU
    (tests, dryrun) a plain reshape is used.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    if ndev != plan.num_chips:
        raise ValueError(
            f"plan {plan.slice_name} wants {plan.num_chips} devices, have {ndev}"
        )
    shape = plan.axis_sizes
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    else:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, plan.axis_names)


def make_multislice_mesh(
    axes: AxisSpec,
    num_slices: int,
    *,
    dcn_axis: str = "dp",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Hybrid ICI+DCN mesh for multi-slice (megascale) jobs.

    ``axes`` gives the GLOBAL extents (product == total devices across all
    slices). ``dcn_axis`` — "dp" or "pp", the only axes whose collectives
    tolerate DCN latency (one allreduce per step / one boundary hop per
    microbatch tick) — takes ``num_slices`` as its *outer* factor, so its
    inter-slice segment crosses DCN and every other axis stays inside a
    slice's ICI. The reference's analogue was launching one MPI world per
    cluster with no topology awareness at all (SURVEY §2.5); here the
    slice boundary is explicit in the mesh.

    On TPU the device order comes from
    ``mesh_utils.create_hybrid_device_mesh`` (reads device.slice_index);
    on CPU (tests/dryrun) contiguous device blocks emulate slices.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if dcn_axis not in ("dp", "pp"):
        raise ValueError(
            f"dcn_axis must be 'dp' or 'pp' (latency-tolerant collectives); "
            f"got {dcn_axis!r}"
        )
    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    if ndev % num_slices != 0:
        raise ValueError(f"{ndev} devices not divisible into {num_slices} slices")
    resolved = axes.resolve(ndev)
    d = resolved.as_dict()
    if d[dcn_axis] % num_slices != 0:
        raise ValueError(
            f"{dcn_axis}={d[dcn_axis]} not divisible by num_slices={num_slices}"
        )
    per_slice = dict(d)
    per_slice[dcn_axis] //= num_slices
    per_shape = tuple(per_slice[a] for a in AXIS_ORDER)
    if math.prod(per_shape) * num_slices != ndev:
        raise ValueError(
            f"axes {d} x {num_slices} slices != {ndev} devices"
        )
    dcn_shape = tuple(
        num_slices if a == dcn_axis else 1 for a in AXIS_ORDER
    )
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            per_shape, dcn_shape, devices=list(devices)
        )
    else:
        idx = AXIS_ORDER.index(dcn_axis)
        arr = np.asarray(list(devices)).reshape((num_slices,) + per_shape)
        arr = np.moveaxis(arr, 0, idx)   # slice id becomes dcn_axis's outer factor
        dev_array = arr.reshape(tuple(d[a] for a in AXIS_ORDER))
    return Mesh(dev_array, AXIS_ORDER)


def make_host_local_mesh(axes: AxisSpec) -> Mesh:
    """Convenience: build a mesh over whatever devices this process sees
    (single-host dev loop / unit tests)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    ndev = len(jax.devices())
    resolved = axes.resolve(ndev)
    shape = tuple(resolved.as_dict()[a] for a in AXIS_ORDER)
    if jax.devices()[0].platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape)
    else:
        dev_array = np.asarray(jax.devices()).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)
