"""HBM capacity planning: does this model fit this slice — *before* the
gang is scheduled.

The reference's only capacity knob was a GPU count string the spawner
stuffed into pod resource limits (reference: components/jupyter-web-app/
backend/kubeflow_jupyter/common/utils.py:390-443); an over-committed job
was discovered by CUDA OOM at runtime. A TPU/XLA platform can do
categorically better because the memory program is static:

- **Analytic tier** (``analytic_report``): pure ``jax.eval_shape`` — no
  devices, milliseconds. Params/grads/optimizer bytes are EXACT (computed
  from the abstract param tree and the same logical sharding rules the
  trainer resolves); activation bytes follow a documented per-remat-policy
  residual model for transformer LMs. This is what the TpuJob controller
  runs at admission: a v5e-16 job for llama3-70b is rejected with
  "CapacityExceeded" instead of OOMing 20 minutes into a schedule.
- **AOT tier** (``aot_report``): ``jax.jit(step).lower(...).compile()``
  against a mesh of virtual devices and read XLA's own per-device
  ``memory_analysis()`` — argument/temp/output buffer-assignment bytes,
  the exact numbers the TPU compiler would bake. Needs
  ``xla_force_host_platform_device_count`` >= the slice's chip count, so
  ``tpuctl plan --aot`` re-execs itself with the right flags.

Both tiers share one report shape so BASELINE/CI can pin them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.topology.mesh import AXIS_ORDER, AxisSpec, plan_mesh
from kubeflow_tpu.topology.slices import SliceType, get_slice
from kubeflow_tpu.utils import get_logger

log = get_logger("capacity")

GiB = 1024 ** 3


class InvalidTrainingConfig(ValueError):
    """A training-config contradiction the job owner must fix (e.g.
    grad_accum not dividing the batch, unknown optimizer name). Admission
    REJECTS on this; any other estimator failure stays fail-open."""


@dataclasses.dataclass(frozen=True)
class CapacityReport:
    model: str
    slice_name: str
    num_slices: int
    axes: Dict[str, int]
    num_chips: int
    method: str                       # "analytic" | "aot"
    hbm_per_chip: int                 # bytes
    # Per-device byte accounting. For "aot", params/grads/opt are folded
    # into ``arguments`` (XLA's input-buffer view) and ``activations``
    # carries temp_size; the analytic tier itemises.
    params: int = 0
    grads: int = 0
    opt_state: int = 0
    activations: int = 0
    arguments: int = 0                # aot only: per-device argument bytes
    outputs: int = 0                  # aot only
    detail: str = ""

    @property
    def total(self) -> int:
        if self.method == "aot":
            # Donated state aliases outputs; temp covers the backward's
            # working set. arguments already includes params+opt+batch.
            return self.arguments + self.activations
        return self.params + self.grads + self.opt_state + self.activations

    def fits(self, utilization_cap: float = 0.92) -> bool:
        """True when the estimate fits under ``utilization_cap`` x HBM
        (the cap absorbs allocator fragmentation + XLA scratch)."""
        return self.total <= self.hbm_per_chip * utilization_cap

    @property
    def headroom(self) -> int:
        return self.hbm_per_chip - self.total

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["total"] = self.total
        d["fits"] = self.fits()
        d["headroom"] = self.headroom
        d["total_gib"] = round(self.total / GiB, 3)
        d["hbm_per_chip_gib"] = round(self.hbm_per_chip / GiB, 3)
        return d


# ------------------------------------------------------------- shared bits


def _resolve(slice_type: str | SliceType, axes: AxisSpec,
             num_slices: int = 1):
    st = get_slice(slice_type) if isinstance(slice_type, str) else slice_type
    total_chips = st.num_chips * num_slices
    resolved = axes.resolve(total_chips)
    return st, resolved, total_chips


def _shard_factor(spec, extents: Dict[str, int]) -> int:
    """Number of shards a PartitionSpec splits a tensor into."""
    n = 1
    for entry in spec:
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for name in names:
            n *= extents.get(name, 1)
    return n


def _abstract_params(model, batch_shape: Tuple[int, int]):
    """eval_shape the model init (LM contract: int32 token batch)."""
    import jax
    import jax.numpy as jnp

    tokens = jax.ShapeDtypeStruct(batch_shape, jnp.int32)

    def init(rng):
        return model.init(rng, tokens)

    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    return jax.eval_shape(init, rng)


def _param_spec_tree(abstract_variables, rules):
    from flax import linen as nn
    from flax.linen import spmd as flax_spmd

    logical = nn.get_partition_spec(abstract_variables)
    return flax_spmd.logical_to_mesh(logical, tuple(rules))


def _dtype_bytes(dt) -> int:
    import numpy as np

    return np.dtype(dt).itemsize


def _build_model(model_name: str, param_dtype: Optional[str],
                 remat_policy: Optional[str], model_kw: Optional[dict]):
    """get_model with the same knobs the runner will use: explicit args
    win, then ``model_kw`` (the KFTPU_MODEL_KW contract), then registry
    defaults. Knobs a config doesn't accept are dropped one by one so an
    image model ignores remat_policy instead of failing the plan."""
    from kubeflow_tpu.models import get_model

    kw = dict(model_kw or {})
    if param_dtype:
        kw["param_dtype"] = param_dtype
    if remat_policy:
        kw["remat_policy"] = remat_policy
    while True:
        try:
            return get_model(model_name, **kw)
        except TypeError as e:
            dropped = next((k for k in list(kw) if f"'{k}'" in str(e)), None)
            if dropped is None:
                raise
            kw.pop(dropped)


# ------------------------------------------------------------- analytic


def analytic_report(
    model_name: str,
    slice_type: str,
    axes: AxisSpec,
    *,
    num_slices: int = 1,
    global_batch: int = 8,
    seq_len: int = 1024,
    remat_policy: Optional[str] = None,
    mu_dtype: str = "",
    param_dtype: Optional[str] = None,
    model_kw: Optional[dict] = None,
    optimizer: str = "adamw",
    grad_accum: int = 1,
    rules=None,
) -> CapacityReport:
    """Device-free per-chip HBM estimate for a registry LM.

    Exact terms (from the abstract param tree + sharding rules):
      params        size x itemsize / shard_factor per leaf
      grads         params-shaped in the param dtype (value_and_grad)
      opt_state     per TrainConfig.optimizer family — adamw: mu in
                    ``mu_dtype`` + nu in f32 (train.trainer._f32_moments
                    keeps nu f32); lion/sgd: one moment; adafactor:
                    factored f32 row+col stats for matrices, full f32
                    for vectors — sharded like params
    Modeled term (transformer residual model, stated in ``detail``):
      activations   per-layer saved residuals under ``remat_policy``
                    + logits/CE buffers + a backward working-set term
    Non-LM models get activations=0 and a detail note — their admission
    check covers state only (image-model activations are small at the
    batch sizes v5e slices run).
    """
    import jax
    import numpy as np

    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES

    rules = rules or DEFAULT_RULES
    if grad_accum > 1 and global_batch % grad_accum:
        # The trainer's microbatch split asserts divisibility at trace
        # time; green-lighting the config here would admit a job that
        # crashes on step 1.
        raise InvalidTrainingConfig(
            f"grad_accum_steps {grad_accum} does not divide global batch "
            f"{global_batch}"
        )
    st, resolved, total_chips = _resolve(slice_type, axes, num_slices)
    extents = resolved.as_dict()

    model, cfg = _build_model(model_name, param_dtype, remat_policy,
                              model_kw)
    remat_policy = getattr(cfg, "remat_policy", remat_policy or "full")
    is_lm = hasattr(cfg, "embed_dim") and hasattr(cfg, "num_layers") \
        and hasattr(cfg, "vocab_size")

    if is_lm:
        abstract = _abstract_params(model, (max(1, global_batch), seq_len))
    else:
        # image models: NHWC batch at the model's own image size (ViT
        # position embeddings are patch-count-shaped, so a hardcoded 224
        # would fail init for smaller configs)
        import jax.numpy as jnp

        side = int(getattr(cfg, "image_size", 224))
        x = jax.ShapeDtypeStruct((max(1, global_batch), side, side, 3),
                                 jnp.float32)
        rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        abstract = jax.eval_shape(
            lambda r, xx: model.init(r, xx, train=False), rng, x)

    from flax import linen as nn

    spec_tree = _param_spec_tree(abstract, rules)
    abstract_unboxed = nn.meta.unbox(abstract)
    params_leaves = jax.tree_util.tree_leaves_with_path(
        abstract_unboxed.get("params", {}))
    spec_unboxed = nn.meta.unbox(spec_tree)
    spec_by_path = {
        tuple(str(k) for k in p): s
        for p, s in jax.tree_util.tree_flatten_with_path(
            spec_unboxed.get("params", {}),
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
        )[0]
    }

    params_b = 0
    mu_b = 0
    nu_b = 0
    f32_acc_b = 0
    mu_itemsize = _dtype_bytes(mu_dtype or "float32")
    for path, leaf in params_leaves:
        key = tuple(str(k) for k in path)
        spec = spec_by_path.get(key, jax.sharding.PartitionSpec())
        shards = _shard_factor(spec, extents)
        per_dev = leaf.size // shards
        params_b += per_dev * _dtype_bytes(leaf.dtype)
        if grad_accum > 1:
            f32_acc_b += per_dev * 4
        if optimizer in ("adamw", "lion"):
            mu_b += per_dev * mu_itemsize
            if optimizer == "adamw":
                nu_b += per_dev * 4      # nu pinned f32 (_f32_moments)
        elif optimizer == "sgd":
            mu_b += per_dev * 4          # momentum trace, f32
        elif optimizer == "adafactor":
            # Factored second moments, mirroring optax's rule: factor
            # over the TWO LARGEST dims (stats = param shape minus one
            # factored dim each) when the second-largest dim >= 128,
            # else a full f32 stat. Factored stats REPLICATE (their
            # shapes don't match any param, so the trainer's path-suffix
            # matcher replicates them — no shard division); full stats
            # are params-shaped and shard like the param.
            shape = sorted(leaf.shape)
            if len(shape) >= 2 and shape[-2] >= 128:
                mu_b += (leaf.size // shape[-1]
                         + leaf.size // shape[-2]) * 4
            else:
                mu_b += per_dev * 4
        else:
            raise InvalidTrainingConfig(f"unknown optimizer {optimizer!r}")
    # Grads live in the param dtype; under microbatch accumulation
    # (TrainConfig.grad_accum_steps) the f32 accumulator tree rides with
    # them, while the activation model below shrinks by 1/K.
    grads_b = params_b + f32_acc_b

    act_b = 0
    detail = ""
    if is_lm:
        act_bytes = 2                    # bf16 activations
        B, S = max(1, global_batch // max(1, grad_accum)), seq_len
        E = cfg.embed_dim
        L = cfg.num_layers
        heads = getattr(cfg, "num_heads", 0) * getattr(cfg, "head_dim", 0)
        kv = 2 * getattr(cfg, "num_kv_heads", 0) * getattr(cfg, "head_dim", 0)
        mlp = getattr(cfg, "mlp_dim", 0)
        tok_shards = extents["dp"] * extents["fsdp"] * extents["sp"]
        t_dev = max(1, (B * S) // max(1, tok_shards))
        # attn_resid (the flash custom-VJP residuals saved by
        # minimal/qkv_attn_lse): a second bf16 copy of the attention
        # context plus the f32 lse — expressed in bf16-element units
        # since per_layer is multiplied by act_bytes=2.
        attn_resid = heads + 2 * getattr(cfg, "num_heads", 0)
        per_layer = {
            # saved residuals per layer per policy (models/llama.py
            # remat taxonomy): full = scan carry only; qkv_attn adds
            # q/k/v + attention context; minimal adds mlp gate/up and
            # the flash custom-VJP residuals; dots approximates every
            # matmul output.
            "full": E,
            "qkv_attn": 2 * E + heads + kv,
            "qkv_attn_lse": 2 * E + heads + kv + attn_resid,
            "attn_only": 2 * E + heads + kv,
            "minimal": 2 * E + heads + kv + 2 * mlp + attn_resid,
            "mlp_only": E + 2 * mlp,
            "dots": 3 * E + heads + kv + 3 * mlp,
        }.get(remat_policy, 2 * E + heads + kv)
        saved = L * t_dev * per_layer * act_bytes
        # logits + CE statistics: [B,S,V] in the logits dtype, vocab over
        # tp; x2 for the softmax/CE workspace the loss materialises.
        logits_dt = 4 if getattr(cfg, "logits_f32", True) else 2
        t_nosp = max(1, (B * S) // max(1, extents["dp"] * extents["fsdp"]))
        logits = 2 * t_nosp * (cfg.vocab_size //
                               max(1, extents["tp"])) * logits_dt
        # backward working set: one layer's recompute + its grads in
        # flight (heuristic, stated; the AOT tier measures it exactly)
        transient = 4 * t_dev * (E + max(mlp, heads)) * act_bytes
        act_b = saved + logits + transient
        detail = (
            f"act model: {remat_policy} saved={saved/GiB:.2f}GiB "
            f"logits={logits/GiB:.2f}GiB transient={transient/GiB:.2f}GiB "
            f"(B={B} S={S} tok_shards={tok_shards})"
        )
    else:
        detail = "activations not modeled for non-LM (state-only check)"

    return CapacityReport(
        model=model_name,
        slice_name=st.name,
        num_slices=num_slices,
        axes=extents,
        num_chips=total_chips,
        method="analytic",
        hbm_per_chip=int(st.generation.hbm_gib_per_chip * GiB),
        params=params_b,
        grads=grads_b,
        opt_state=mu_b + nu_b,
        activations=act_b,
        detail=detail,
    )


# ------------------------------------------------------------- AOT


def aot_report(
    model_name: str,
    slice_type: str,
    axes: AxisSpec,
    *,
    num_slices: int = 1,
    global_batch: int = 8,
    seq_len: int = 1024,
    remat_policy: Optional[str] = None,
    mu_dtype: str = "",
    param_dtype: Optional[str] = None,
    model_kw: Optional[dict] = None,
    train_kw: Optional[dict] = None,
    optimizer: str = "adamw",
    grad_accum: int = 1,
) -> CapacityReport:
    """Compile the real sharded train step (no execution, no buffers) and
    read XLA's per-device buffer assignment. Ground truth for the analytic
    tier; requires len(jax.devices()) >= the slice's chip count
    (``xla_force_host_platform_device_count`` for the virtual backend).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.topology.mesh import make_mesh
    from kubeflow_tpu.train.trainer import TrainConfig, Trainer

    st, resolved, total_chips = _resolve(slice_type, axes, num_slices)
    devices = jax.devices()
    if len(devices) < total_chips:
        raise RuntimeError(
            f"AOT plan for {st.name} x{num_slices} needs {total_chips} "
            f"devices, have {len(devices)}; run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={total_chips} "
            f"JAX_PLATFORMS=cpu (tpuctl plan --aot does this for you)"
        )
    plan = plan_mesh(st, resolved)
    mesh = make_mesh(plan, devices[:total_chips])

    model, cfg = _build_model(model_name, param_dtype, remat_policy,
                              model_kw)
    task = "lm" if hasattr(cfg, "vocab_size") else "image"
    tcfg = TrainConfig(task=task, mu_dtype=mu_dtype, optimizer=optimizer,
                       grad_accum_steps=max(1, grad_accum),
                       **(train_kw or {}))
    trainer = Trainer(model, tcfg, mesh)

    if task == "lm":
        batch_abs = {"inputs": jax.ShapeDtypeStruct(
            (global_batch, seq_len + 1), jnp.int32,
            sharding=NamedSharding(mesh, P(("dp", "fsdp"))),
        )}
    else:
        batch_abs = {
            "inputs": jax.ShapeDtypeStruct(
                (global_batch, 224, 224, 3), jnp.float32,
                sharding=NamedSharding(mesh, P(("dp", "fsdp"))),
            ),
            "labels": jax.ShapeDtypeStruct(
                (global_batch,), jnp.int32,
                sharding=NamedSharding(mesh, P(("dp", "fsdp"))),
            ),
        }
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    state_abs, state_shardings = trainer.abstract_state(rng, batch_abs)
    state_in = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        state_abs, state_shardings,
    )
    with mesh:
        lowered = trainer.compile_step().lower(
            state_in, batch_abs, jax.random.PRNGKey(0))
        compiled = lowered.compile()
    ma = compiled.memory_analysis()
    return CapacityReport(
        model=model_name,
        slice_name=st.name,
        num_slices=num_slices,
        axes=resolved.as_dict(),
        num_chips=total_chips,
        method="aot",
        hbm_per_chip=int(st.generation.hbm_gib_per_chip * GiB),
        arguments=int(ma.argument_size_in_bytes),
        outputs=int(ma.output_size_in_bytes),
        activations=int(ma.temp_size_in_bytes),
        detail=(
            f"xla buffer assignment: args={ma.argument_size_in_bytes} "
            f"temp={ma.temp_size_in_bytes} out={ma.output_size_in_bytes} "
            f"alias={ma.alias_size_in_bytes} "
            f"peak={getattr(ma, 'peak_memory_in_bytes', 0)}"
        ),
    )


# ------------------------------------------------------------- CLI seam

def _main(argv=None) -> int:
    """Subprocess entrypoint used by ``tpuctl plan --aot`` (re-exec'd with
    the forced device count). Prints one JSON report."""
    import argparse
    import json as _json
    import os

    p = argparse.ArgumentParser(prog="kubeflow_tpu.topology.capacity")
    p.add_argument("--model", required=True)
    p.add_argument("--slice-type", required=True)
    p.add_argument("--num-slices", type=int, default=1)
    p.add_argument("--axes", default="{}",
                   help='JSON axis extents, e.g. {"fsdp": -1}')
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--remat-policy", default="")
    p.add_argument("--mu-dtype", default="")
    p.add_argument("--optimizer", default="adamw")
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--param-dtype", default="")
    p.add_argument("--model-kw", default="{}")
    p.add_argument("--aot", action="store_true")
    args = p.parse_args(argv)

    # Same contract as train.runner: environments whose site config
    # registers a TPU plugin need an explicit platform override to get the
    # virtual CPU mesh (tpuctl plan --aot sets KFTPU_PLATFORM=cpu).
    plat = os.environ.get("KFTPU_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    axes = AxisSpec(**{k: int(v)
                       for k, v in _json.loads(args.axes).items()})
    fn = aot_report if args.aot else analytic_report
    rep = fn(
        args.model, args.slice_type, axes,
        num_slices=args.num_slices,
        global_batch=args.global_batch, seq_len=args.seq_len,
        remat_policy=args.remat_policy or None,
        mu_dtype=args.mu_dtype,
        param_dtype=args.param_dtype or None,
        model_kw=_json.loads(args.model_kw or "{}"),
        optimizer=args.optimizer or "adamw",
        grad_accum=args.grad_accum,
    )
    print(_json.dumps(rep.to_dict()))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
