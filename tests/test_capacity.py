"""HBM capacity planning (topology/capacity.py) + the TpuJob admission
gate + tpuctl plan.

Pins the flagship grids VERDICT r3 asked for: llama3-8b fits v5e-16
(fsdp), llama3-70b fits v5e-64 and is REJECTED on v5e-16 — the only
8B/70B validation a chip-less environment permits, and one the reference
never had (its capacity model was a GPU limit string,
reference: components/jupyter-web-app/backend/kubeflow_jupyter/common/
utils.py:390-443).
"""

import json

import jax
import pytest
import yaml

from kubeflow_tpu.topology.capacity import (
    GiB,
    analytic_report,
    aot_report,
)
from kubeflow_tpu.topology.mesh import AxisSpec


class TestAnalytic:
    def test_llama3_8b_fits_v5e16_fsdp(self):
        """The flagship single-slice grid: 8B, bf16 params, fsdp over 16
        chips, the bench recipe's qkv_attn remat."""
        rep = analytic_report(
            "llama3-8b", "v5e-16", AxisSpec(fsdp=-1),
            global_batch=16, seq_len=2048,
            param_dtype="bfloat16", mu_dtype="bfloat16",
            remat_policy="qkv_attn",
        )
        assert rep.fits(), rep.to_dict()
        # bf16 8B params over 16 chips: ~1 GiB/chip, exactly
        assert rep.params == pytest.approx(8.03e9 * 2 / 16, rel=0.05)
        # mu bf16 (2 bytes) + nu f32 (4 bytes) = 6 bytes/param
        assert rep.opt_state == pytest.approx(8.03e9 * 6 / 16, rel=0.05)
        assert rep.total < 12 * GiB

    def test_optimizer_families_order_opt_state(self):
        """adamw (mu+nu) > lion/sgd (one moment) > adafactor (factored):
        the planner models TrainConfig.optimizer, so an adafactor job can
        admit where adamw is rejected."""
        kw = dict(global_batch=16, seq_len=2048, param_dtype="bfloat16",
                  remat_policy="qkv_attn")
        reps = {name: analytic_report("llama3-8b", "v5e-16",
                                      AxisSpec(fsdp=-1), optimizer=name,
                                      **kw)
                for name in ("adamw", "lion", "sgd", "adafactor")}
        n = 8.03e9
        assert reps["adamw"].opt_state == pytest.approx(n * 8 / 16, rel=0.05)
        assert reps["lion"].opt_state == pytest.approx(n * 4 / 16, rel=0.05)
        assert reps["sgd"].opt_state == pytest.approx(n * 4 / 16, rel=0.05)
        # Factored stats are ~size/min(rows,cols) and replicate: tiny
        # next to any moment tree, but nonzero.
        assert 0 < reps["adafactor"].opt_state < reps["lion"].opt_state / 10

    def test_grad_accum_indivisible_is_config_error(self):
        """Non-divisible grad_accum raises the dedicated config-error
        type: admission REJECTS it (the trainer would assert at step 1)
        while other estimator failures stay fail-open."""
        from kubeflow_tpu.topology.capacity import InvalidTrainingConfig

        with pytest.raises(InvalidTrainingConfig, match="does not divide"):
            analytic_report("llama3-8b", "v5e-16", AxisSpec(fsdp=-1),
                            global_batch=16, grad_accum=3)

    def test_grad_accum_shrinks_activations(self):
        """grad_accum=K models 1/K activation tokens plus the f32
        accumulator tree riding with the grads."""
        kw = dict(global_batch=16, seq_len=2048, param_dtype="bfloat16",
                  remat_policy="qkv_attn")
        base = analytic_report("llama3-8b", "v5e-16", AxisSpec(fsdp=-1),
                               **kw)
        acc = analytic_report("llama3-8b", "v5e-16", AxisSpec(fsdp=-1),
                              grad_accum=4, **kw)
        assert acc.activations < base.activations / 2
        # grads gain the f32 accumulator: bf16 grads (2B) + f32 tree (4B)
        assert acc.grads == pytest.approx(base.grads * 3, rel=0.01)

    def test_llama3_70b_rejected_on_v5e16(self):
        rep = analytic_report(
            "llama3-70b", "v5e-16", AxisSpec(fsdp=-1),
            global_batch=16, seq_len=2048,
            param_dtype="bfloat16", mu_dtype="bfloat16",
        )
        assert not rep.fits()
        assert rep.total > 2 * rep.hbm_per_chip   # not marginal: 70B
        # params alone: 70.6e9 x 2 bytes / 16 chips ~ 8.2 GiB
        assert rep.params == pytest.approx(70.6e9 * 2 / 16, rel=0.05)

    def test_llama3_70b_fits_v5e64_fsdp(self):
        """The flagship multi-host grid VERDICT asked to pin."""
        rep = analytic_report(
            "llama3-70b", "v5e-64", AxisSpec(fsdp=-1),
            global_batch=32, seq_len=2048,
            param_dtype="bfloat16", mu_dtype="bfloat16",
            remat_policy="full",
        )
        assert rep.fits(), rep.to_dict()

    def test_f32_defaults_cost_double(self):
        """Registry-default llama3-8b keeps f32 params — the planner must
        see that reality (the runner builds from the same defaults)."""
        bf16 = analytic_report("llama3-8b", "v5e-16", AxisSpec(fsdp=-1),
                               param_dtype="bfloat16")
        f32 = analytic_report("llama3-8b", "v5e-16", AxisSpec(fsdp=-1))
        assert f32.params == pytest.approx(2 * bf16.params, rel=0.01)

    def test_tp_shards_param_bytes(self):
        base = analytic_report("llama-tiny", "v5e-8", AxisSpec(dp=-1),
                               global_batch=8, seq_len=64)
        tp = analytic_report("llama-tiny", "v5e-8",
                             AxisSpec(dp=-1, tp=2),
                             global_batch=8, seq_len=64)
        # attention/mlp/vocab kernels halve; norms/replicated leaves don't
        assert tp.params < base.params
        assert tp.params > base.params / 2

    def test_unsharded_params_exact(self):
        """With no model sharding, per-device param bytes == the literal
        tree size (ground truth for the sharding arithmetic)."""
        import numpy as np

        from kubeflow_tpu.models import get_model

        rep = analytic_report("llama-tiny", "v5e-8", AxisSpec(dp=-1),
                              global_batch=8, seq_len=64)
        model, _ = get_model("llama-tiny")
        variables = jax.eval_shape(
            lambda r: model.init(r, jax.ShapeDtypeStruct((1, 8), "int32")),
            jax.eval_shape(lambda: jax.random.PRNGKey(0)),
        )
        from flax import linen as nn

        leaves = jax.tree.leaves(nn.meta.unbox(variables)["params"])
        total = sum(x.size * np.dtype(x.dtype).itemsize for x in leaves)
        assert rep.params == total

    def test_image_model_state_only(self):
        rep = analytic_report("resnet50", "v5e-8", AxisSpec(dp=-1))
        assert rep.activations == 0
        assert rep.params > 0
        assert "not modeled" in rep.detail


class TestAot:
    def test_aot_tiny_on_virtual_mesh(self):
        """AOT tier on the 8-device test mesh: XLA buffer assignment comes
        back per-device and nonzero."""
        rep = aot_report("llama-tiny", "v5e-8", AxisSpec(fsdp=-1),
                         global_batch=8, seq_len=64)
        assert rep.method == "aot"
        assert rep.arguments > 0
        assert rep.activations > 0      # temp: backward working set
        assert rep.fits()

    def test_analytic_state_matches_xla_arguments(self):
        """Cross-validate the tiers: XLA's per-device argument bytes
        (train state + batch) must match the analytic params + opt_state
        + batch arithmetic — the analytic tier's exactness claim, checked
        against the compiler's own buffer assignment."""
        import numpy as np

        kw = dict(global_batch=8, seq_len=64, mu_dtype="bfloat16",
                  param_dtype="bfloat16")
        ana = analytic_report("llama-tiny", "v5e-8", AxisSpec(fsdp=-1),
                              **kw)
        aot = aot_report("llama-tiny", "v5e-8", AxisSpec(fsdp=-1), **kw)
        batch_bytes = 8 * 65 * 4 // 8        # int32 tokens over 8 chips
        want = ana.params + ana.opt_state + batch_bytes
        # slack: step counters, schedule state, padding
        assert abs(aot.arguments - want) / want < 0.10, (
            f"aot args {aot.arguments} vs analytic state {want}")
        with pytest.raises(RuntimeError, match="device_count=16"):
            aot_report("llama-tiny", "v5e-16", AxisSpec(fsdp=-1))


class TestTpuctlPlan:
    def _job_yaml(self, tmp_path, model, slice_type, env=None):
        doc = {
            "kind": "TpuJob",
            "metadata": {"name": f"{model}-job", "namespace": "team-a"},
            "spec": {
                "sliceType": slice_type,
                "mesh": {"dp": 1, "fsdp": -1},
                "model": model,
                "env": [{"name": k, "value": v}
                        for k, v in (env or {}).items()],
            },
        }
        p = tmp_path / f"{model}.yaml"
        p.write_text(yaml.safe_dump(doc))
        return str(p)

    def test_plan_fits_exit_zero(self, tmp_path, capsys):
        from kubeflow_tpu.tools.tpuctl import main

        f = self._job_yaml(
            tmp_path, "llama3-8b", "v5e-16",
            env={"KFTPU_MODEL_KW": json.dumps(
                {"param_dtype": "bfloat16"})},
        )
        rc = main(["plan", "-f", f])
        out = capsys.readouterr().out
        assert rc == 0
        assert "FITS" in out and "params" in out

    def test_plan_reject_exit_two(self, tmp_path, capsys):
        from kubeflow_tpu.tools.tpuctl import main

        f = self._job_yaml(tmp_path, "llama3-70b", "v5e-16")
        rc = main(["plan", "-f", f])
        out = capsys.readouterr().out
        assert rc == 2
        assert "DOES NOT FIT" in out

    def test_plan_aot_subprocess(self, tmp_path, capsys):
        """--aot re-execs the planner under a virtual mesh of the slice's
        chip count and reads XLA's buffer assignment; the subprocess env
        wiring (forced device count + platform override) is the part only
        this test exercises."""
        from kubeflow_tpu.tools.tpuctl import main

        f = self._job_yaml(tmp_path, "llama-tiny", "v5e-8")
        rc = main(["plan", "-f", f, "--aot", "-o", "json"])
        out = capsys.readouterr().out
        assert rc == 0
        reports = json.loads(out.strip().splitlines()[-1])
        assert reports[0]["method"] == "aot"
        assert reports[0]["num_chips"] == 8
        assert reports[0]["activations"] > 0    # XLA temp, per device
        assert "FITS" in out

    def test_plan_json_output(self, tmp_path, capsys):
        from kubeflow_tpu.tools.tpuctl import main

        f = self._job_yaml(tmp_path, "llama3-8b", "v5e-16")
        rc = main(["plan", "-f", f, "-o", "json"])
        out = capsys.readouterr().out
        reports = json.loads(out.strip().splitlines()[-1])
        assert reports[0]["model"] == "llama3-8b"
        assert reports[0]["num_chips"] == 16
        assert rc == 0


class TestAdmissionGate:
    def _world(self, **ctl_kw):
        from kubeflow_tpu.controlplane.controllers import TpuJobController
        from kubeflow_tpu.controlplane.controllers.podrunner import (
            FakeKubelet,
        )
        from kubeflow_tpu.controlplane.runtime import (
            ControllerManager,
            InMemoryApiServer,
        )
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(TpuJobController(api, reg, **ctl_kw))
        mgr.register(FakeKubelet(api, reg))
        return api, mgr

    def _job(self, model, slice_type, env=None, name="j"):
        from kubeflow_tpu.controlplane.api import (
            ObjectMeta,
            TpuJob,
            TpuJobSpec,
        )
        from kubeflow_tpu.controlplane.api.core import EnvVar
        from kubeflow_tpu.controlplane.api.types import MeshAxesSpec

        return TpuJob(
            metadata=ObjectMeta(name=name, namespace="team-a"),
            spec=TpuJobSpec(
                slice_type=slice_type, model=model,
                mesh=MeshAxesSpec(dp=1, fsdp=-1),
                env=[EnvVar(k, v) for k, v in (env or {}).items()],
            ),
        )

    def test_oversized_job_rejected_at_admission(self):
        api, mgr = self._world()
        api.create(self._job("llama3-70b", "v5e-16"))
        mgr.run_until_idle()
        job = api.get("TpuJob", "j", "team-a")
        assert job.status.phase == "Failed"
        cond = job.status.conditions[-1]
        assert cond.reason == "CapacityExceeded"
        assert "GiB/chip" in cond.message
        # no gang was created
        assert api.list("Pod", "team-a") == []

    def test_fitting_job_admitted(self):
        api, mgr = self._world()
        api.create(self._job(
            "llama3-8b", "v5e-16",
            env={"KFTPU_MODEL_KW": json.dumps(
                {"param_dtype": "bfloat16"})},
        ))
        mgr.run_until_idle()
        job = api.get("TpuJob", "j", "team-a")
        assert job.status.phase != "Failed"
        pods = api.list("Pod", "team-a")
        assert len(pods) == 4           # v5e-16: 4 hosts
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert "param_dtype" in env.get("KFTPU_MODEL_KW", "")

    def test_gate_can_be_disabled(self):
        api, mgr = self._world(hbm_check=False)
        api.create(self._job("llama3-70b", "v5e-16"))
        mgr.run_until_idle()
        job = api.get("TpuJob", "j", "team-a")
        assert job.status.phase != "Failed"
