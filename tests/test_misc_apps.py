"""Trivial platform services (echo / https-redirect / static-config), the
HTTP culling probe, and the CI gate."""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.webapps.misc import (
    echo_app,
    https_redirect_app,
    serve,
    static_config_app,
)


class TestMiscApps:
    def test_echo_reflects_identity(self):
        srv = serve(echo_app())
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/some/path?x=1",
                headers={"x-goog-authenticated-user-email": "alice@corp"},
            )
            out = json.load(urllib.request.urlopen(req))
            assert out["path"] == "/some/path"
            assert out["query"] == {"x": "1"}
            assert out["caller"] == "alice@corp"
        finally:
            srv.stop()

    def test_https_redirect_sets_location(self):
        srv = serve(https_redirect_app())
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/notebook/ns/x",
                headers={"Host": "kubeflow.example.com"},
            )
            # urllib follows redirects; https to a fake host will fail, so
            # inspect the raw 301 instead.
            class NoRedirect(urllib.request.HTTPRedirectHandler):
                def redirect_request(self, *a, **k):
                    return None

            opener = urllib.request.build_opener(NoRedirect)
            with pytest.raises(urllib.error.HTTPError) as e:
                opener.open(req)
            assert e.value.code == 301
            assert e.value.headers["Location"] == \
                "https://kubeflow.example.com/notebook/ns/x"
        finally:
            srv.stop()

    def test_static_config(self):
        srv = serve(static_config_app({"defaultSliceType": "v5e-16"}))
        try:
            out = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/config"
            ))
            assert out == {"defaultSliceType": "v5e-16"}
        finally:
            srv.stop()


class TestHttpActivityProbe:
    def _jupyter(self, last_activity):
        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path != "/api/status":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(
                    {"last_activity": last_activity, "kernels": 1}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_parses_jupyter_last_activity(self):
        from kubeflow_tpu.controlplane.api.core import Pod
        from kubeflow_tpu.controlplane.controllers import NotebookController

        srv = self._jupyter("2026-07-30T01:02:03.000000Z")
        try:
            probe = NotebookController.http_activity_probe(
                port=srv.server_address[1]
            )
            pod = Pod()
            pod.status.pod_ip = "127.0.0.1"
            ts = probe(pod)
            assert ts is not None
            # 2026-07-30T01:02:03Z as a unix timestamp.
            import datetime

            want = datetime.datetime(
                2026, 7, 30, 1, 2, 3, tzinfo=datetime.timezone.utc
            ).timestamp()
            assert ts == pytest.approx(want)
        finally:
            srv.shutdown()

    def test_unreachable_pod_returns_none(self):
        from kubeflow_tpu.controlplane.api.core import Pod
        from kubeflow_tpu.controlplane.controllers import NotebookController

        probe = NotebookController.http_activity_probe(port=1, timeout=0.2)
        pod = Pod()
        pod.status.pod_ip = "127.0.0.1"
        assert probe(pod) is None
        assert probe(Pod()) is None       # no IP yet

    def test_null_last_activity_returns_none(self):
        from kubeflow_tpu.controlplane.api.core import Pod
        from kubeflow_tpu.controlplane.controllers import NotebookController

        srv = self._jupyter(None)
        try:
            probe = NotebookController.http_activity_probe(
                port=srv.server_address[1]
            )
            pod = Pod()
            pod.status.pod_ip = "127.0.0.1"
            assert probe(pod) is None
        finally:
            srv.shutdown()


class TestCiGate:
    def test_gate_passes_end_to_end(self, tmp_path):
        from kubeflow_tpu.tools.ci import main as ci

        bench = tmp_path / "bench.jsonl"
        bench.write_text(json.dumps(
            {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.05}
        ) + "\n")
        assert ci(["gate", "--bench-json", str(bench)]) == 0

    def test_gate_fails_on_bench_regression(self, tmp_path):
        from kubeflow_tpu.tools.ci import main as ci

        bench = tmp_path / "bench.jsonl"
        bench.write_text(json.dumps(
            {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 0.5}
        ) + "\n")
        assert ci(["gate", "--skip-smoke",
                   "--bench-json", str(bench)]) == 1


class TestRelease:
    def test_manifest_pins_all_images_to_one_tag(self):
        from kubeflow_tpu.tools.release import build_manifest

        m = build_manifest("v1.2.3")
        assert m["version"] == "v1.2.3"
        assert all(img.endswith(":v1.2.3") for img in m["images"].values())
        assert {"runtime", "serving", "controlplane", "jupyter"} <= set(
            m["images"]
        )

    def test_dockerfiles_cover_every_release_image(self, tmp_path):
        """The image-build half of the release story (reference
        components/image-releaser/): one Dockerfile per release image,
        entrypoints matching the env contracts the controllers inject."""
        from kubeflow_tpu.tools.release import (
            IMAGES,
            write_dockerfiles,
        )

        paths = write_dockerfiles(str(tmp_path))
        emitted = {p.split("/")[-2] for p in paths}
        assert emitted == set(IMAGES)
        text = {p.split("/")[-2]: open(p).read() for p in paths}
        assert "kubeflow_tpu.train.runner" in text["runtime"]
        assert "kubeflow_tpu.serving.server" in text["serving"]
        assert "kubeflow_tpu.controlplane.main" in text["controlplane"]
        # framework images ship the native loader source for on-host build
        for name in ("runtime", "serving", "controlplane"):
            assert "COPY native/ native/" in text[name]
        # idempotent re-emit (release pipelines re-run)
        assert write_dockerfiles(str(tmp_path)) == paths

    def test_bump_levels(self, tmp_path):
        from kubeflow_tpu.tools.release import bump_version

        vf = tmp_path / "version.py"
        vf.write_text('__version__ = "1.2.3"\n')
        assert bump_version("patch", str(vf)) == "1.2.4"
        assert bump_version("minor", str(vf)) == "1.3.0"
        assert bump_version("major", str(vf)) == "2.0.0"
        assert vf.read_text() == '__version__ = "2.0.0"\n'


class TestK8sManifests:
    def test_manifests_cover_controlplane_and_hub(self):
        from kubeflow_tpu.tools.release import build_k8s_manifests

        docs = build_k8s_manifests("v9.9.9")
        kinds = [d["kind"] for d in docs]
        assert kinds.count("Deployment") == 2
        assert kinds.count("ServiceAccount") == 2
        deps = {d["metadata"]["name"]: d for d in docs
                if d["kind"] == "Deployment"}
        cp = deps["controlplane"]["spec"]["template"]["spec"]["containers"][0]
        assert cp["image"].endswith(":v9.9.9")
        assert "kubeflow_tpu.controlplane.main" in cp["command"]

    def test_hub_is_behind_gatekeeper_sidecar(self):
        """The hub must not be reachable except through the auth proxy:
        the Service targets the gatekeeper port, and the hub container
        binds localhost (a direct hub Service would make the spoofable
        identity header full authentication)."""
        from kubeflow_tpu.tools.release import build_k8s_manifests

        docs = build_k8s_manifests("v1.0.0")
        hub = next(d for d in docs if d["kind"] == "Deployment"
                   and d["metadata"]["name"] == "hub")
        containers = {c["name"]: c
                      for c in hub["spec"]["template"]["spec"]["containers"]}
        assert set(containers) == {"gatekeeper", "hub"}
        assert "127.0.0.1" in containers["hub"]["command"]
        svc = next(d for d in docs if d["kind"] == "Service"
                   and d["metadata"]["name"] == "hub")
        assert svc["spec"]["ports"][0]["targetPort"] == 8081  # gatekeeper

    def test_no_cluster_admin_and_scoped_roles(self):
        from kubeflow_tpu.tools.release import build_k8s_manifests

        docs = build_k8s_manifests("v1.0.0")
        import json as _json

        assert "cluster-admin" not in _json.dumps(docs)
        roles = {d["metadata"]["name"]: d for d in docs
                 if d["kind"] == "ClusterRole"}
        assert {"kubeflow-tpu-controlplane",
                "kubeflow-tpu-hub"} <= set(roles)
        hub_verbs = {v for rule in roles["kubeflow-tpu-hub"]["rules"]
                     for v in rule["verbs"]}
        assert "*" not in hub_verbs
        # Hub SA differs from controller SA.
        deps = {d["metadata"]["name"]: d for d in docs
                if d["kind"] == "Deployment"}
        assert deps["hub"]["spec"]["template"]["spec"][
            "serviceAccountName"] == "kubeflow-tpu-hub"

    def test_cli_emits_yaml(self, capsys):
        from kubeflow_tpu.tools.release import main as release

        assert release(["manifest", "--k8s", "--tag", "v1.0.0"]) == 0
        out = capsys.readouterr().out
        assert "kind: Deployment" in out and ":v1.0.0" in out

    def test_fresh_cluster_completeness(self):
        """Everything a clean-cluster apply needs: CRDs for all kinds, the
        user roles Profile bindings reference, the bind verb that RBAC
        escalation prevention demands, and the gatekeeper secret (with a
        session key and a refused-by-default placeholder password)."""
        import json as _json

        from kubeflow_tpu.tools.release import build_k8s_manifests

        docs = build_k8s_manifests("v1.0.0")
        crds = [d for d in docs if d["kind"] == "CustomResourceDefinition"]
        assert len(crds) == 8
        assert {c["spec"]["names"]["kind"] for c in crds} >= {
            "TpuJob", "Profile", "Serving", "StudyJob"}
        roles = {d["metadata"]["name"] for d in docs
                 if d["kind"] == "ClusterRole"}
        assert {"kubeflow-admin", "kubeflow-edit", "kubeflow-view"} <= roles
        blob = _json.dumps(docs)
        assert '"bind"' in blob
        secrets = [d for d in docs if d["kind"] == "Secret"]
        assert len(secrets) == 1
        assert "session-key" in secrets[0]["stringData"]
        hub = next(d for d in docs if d["kind"] == "Deployment"
                   and d["metadata"]["name"] == "hub")
        gk = next(c for c in hub["spec"]["template"]["spec"]["containers"]
                  if c["name"] == "gatekeeper")
        assert "--session-secret-file" in gk["command"]
