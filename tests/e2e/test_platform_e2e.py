"""E2E tier: the platform driven end-to-end with REAL worker processes.

Mirrors the reference's deploy-then-assert backbone
(testing/kfctl/kf_is_ready_test.py:76-185 readiness list, Argo E2E DAGs
testing/workflows/components/workflows.libsonnet:98-165) without a
cluster: tpuctl apply brings the platform up, a TpuJob's gang runs as
actual ``train.runner`` subprocesses joined via jax.distributed on CPU
(Gloo collectives over a virtual 8-device mesh), a worker is SIGKILLed
mid-run to prove gang restart, and a second job resumes from the first's
checkpoints to prove the auto-resume contract.
"""

import json
import socket
import time
from pathlib import Path

import pytest
import yaml

from kubeflow_tpu.controlplane.api import ObjectMeta, TpuJob, TpuJobSpec
from kubeflow_tpu.controlplane.api.core import EnvVar
from kubeflow_tpu.controlplane.api.types import MeshAxesSpec
from kubeflow_tpu.controlplane.controllers import TpuJobController
from kubeflow_tpu.controlplane.controllers.podrunner import ProcessKubelet
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.tools.tpuctl import main as tpuctl
from kubeflow_tpu.utils.monitoring import MetricsRegistry

E2E_TIMEOUT = 420  # generous: 2 jax imports + distributed init per attempt


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestPlatformReadiness:
    """tpuctl apply -> assert the platform readiness list (the
    kf_is_ready_test analogue: every expected component reports applied)."""

    EXPECTED = [
        "tpujob-controller", "studyjob-controller", "notebook-controller",
        "profile-controller", "tensorboard-controller", "serving-controller",
        "poddefault-webhook", "kfam", "jupyter-web-app", "centraldashboard",
        "fake-kubelet", "availability-prober",
    ]

    def test_apply_then_ready_list(self, tmp_path):
        cfg = tmp_path / "platform.yaml"
        cfg.write_text(yaml.safe_dump({
            "kind": "PlatformConfig",
            "metadata": {"name": "kubeflow-tpu"},
            "spec": {},
        }))
        state = str(tmp_path / "state")
        assert tpuctl(["--state-dir", state, "apply", "-f", str(cfg)]) == 0

        from kubeflow_tpu.controlplane.platform import Platform

        platform = Platform.load(state)
        pc = platform.api.get("PlatformConfig", "kubeflow-tpu")
        assert pc.status.phase == "Ready"
        missing = [c for c in self.EXPECTED
                   if c not in pc.status.applied_components]
        assert not missing, f"components not ready: {missing}"

        # Second apply: full idempotency (the reference's CI contract).
        before = {
            (o.kind, o.metadata.name): o.metadata.resource_version
            for o in platform.api._objects.values()
        }
        assert tpuctl(["--state-dir", state, "apply", "-f", str(cfg)]) == 0
        platform2 = Platform.load(state)
        after = {
            (o.kind, o.metadata.name): o.metadata.resource_version
            for o in platform2.api._objects.values()
        }
        assert before == after, "second apply mutated resources"


class TestGangE2E:
    """Real multi-process gang: 2 runner.py workers, jax.distributed on
    CPU, kill-one-worker gang restart, checkpoint auto-resume."""

    def _world(self, tmp_path):
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(TpuJobController(api, reg))
        port = _free_port()

        def overrides(pod):
            return {
                "KFTPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "KFTPU_PLATFORM": "cpu",
                # 4 hosts x 2 virtual chips = the 8-device global mesh.
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "",
            }

        kubelet = ProcessKubelet(
            api, reg, env_overrides=overrides,
            log_dir=str(tmp_path / "podlogs"),
        )
        mgr.register(kubelet)
        return api, mgr, kubelet

    def _job(self, name, ckpt_dir, steps):
        return TpuJob(
            metadata=ObjectMeta(name=name, namespace="team-a"),
            spec=TpuJobSpec(
                slice_type="v5e-16",           # 4 hosts -> 4 worker procs
                model="llama-tiny",
                mesh=MeshAxesSpec(dp=-1),
                checkpoint_dir=ckpt_dir,
                max_restarts=2,
                backoff_seconds=0.2,
                env=[
                    EnvVar("KFTPU_TRAIN_STEPS", str(steps)),
                    EnvVar("KFTPU_BATCH_PER_HOST", "2"),
                    EnvVar("KFTPU_SEQ_LEN", "16"),
                    EnvVar("KFTPU_CHECKPOINT_EVERY", "2"),
                ],
            ),
        )

    def _drive(self, api, mgr, kubelet, name, *, until, timeout=E2E_TIMEOUT,
               on_tick=None):
        t0 = time.time()
        while time.time() - t0 < timeout:
            mgr.run_until_idle(include_timers_within=1.0)
            kubelet.sync()
            mgr.run_until_idle(include_timers_within=1.0)
            job = api.get("TpuJob", name, "team-a")
            if on_tick is not None:
                on_tick(job)
            if until(job):
                return job
            time.sleep(0.3)
        job = api.get("TpuJob", name, "team-a")
        logs = {
            p.name: p.read_text()[-2000:]
            for p in Path(kubelet.log_dir).glob("*.log")
        }
        pytest.fail(
            f"timeout: job phase={job.status.phase} "
            f"restarts={job.status.restarts}\nlogs: {json.dumps(logs)[:4000]}"
        )

    def test_gang_restart_and_checkpoint_resume(self, tmp_path):
        api, mgr, kubelet = self._world(tmp_path)
        ckpt = str(tmp_path / "ckpt")

        # ---- phase 1: run a gang, SIGKILL worker-1 early, expect gang
        # restart and a clean finish on generation 1.
        api.create(self._job("train", ckpt, steps=6))
        killed = {"done": False}

        def maybe_kill(job):
            if killed["done"] or job.status.phase != "Running":
                return
            # Kill as soon as the worker process exists (mid-startup or
            # mid-train; either way the gang must restart).
            if kubelet.kill_pod("train-worker-1", "team-a"):
                killed["done"] = True

        job = self._drive(
            api, mgr, kubelet, "train",
            until=lambda j: j.status.phase in ("Succeeded", "Failed")
            and killed["done"],
            on_tick=maybe_kill,
        )
        assert killed["done"], "never got to kill a worker"
        assert job.status.phase == "Succeeded", job.status
        assert job.status.restarts >= 1
        assert job.status.metrics.get("loss", 0) > 0  # termination-msg flow
        # Checkpoints exist for the resume phase.
        assert any(Path(ckpt).iterdir()), "no checkpoint written"

        # ---- phase 2: a new job on the same checkpoint dir must
        # auto-resume past the finished steps instead of starting over.
        api.create(self._job("train2", ckpt, steps=12))
        job2 = self._drive(
            api, mgr, kubelet, "train2",
            until=lambda j: j.status.phase in ("Succeeded", "Failed"),
        )
        assert job2.status.phase == "Succeeded", job2.status
        w0_log = (
            Path(kubelet.log_dir) / "team-a__train2-worker-0.log"
        ).read_text()
        assert "auto-resumed" in w0_log, w0_log[-2000:]
        assert job2.status.metrics.get("steps") == 12
        kubelet.shutdown()


class TestHpoE2E:
    """StudyJob whose trials are REAL single-process runner gangs: the full
    HPO platform path (suggest -> TpuJob -> process -> termination metrics
    -> objective aggregation) with actual training."""

    def test_study_with_real_trials(self, tmp_path):
        from kubeflow_tpu.controlplane.api.types import (
            StudyJob,
            StudyJobSpec,
            TpuJobSpec,
        )
        from kubeflow_tpu.controlplane.controllers import StudyJobController
        from kubeflow_tpu.hpo.space import ParameterSpec

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(TpuJobController(api, reg))
        mgr.register(StudyJobController(api, reg))
        kubelet = ProcessKubelet(
            api, reg,
            env_overrides=lambda pod: {
                "KFTPU_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "",
            },
            log_dir=str(tmp_path / "podlogs"),
        )
        mgr.register(kubelet)

        api.create(StudyJob(
            metadata=ObjectMeta(name="sweep", namespace="team-a"),
            spec=StudyJobSpec(
                objective="loss", direction="minimize",
                algorithm="random", max_trials=2, parallel_trials=2,
                parameters=[ParameterSpec(
                    name="learning_rate", min=1e-4, max=1e-2,
                    log_scale=True,
                )],
                trial=TpuJobSpec(
                    slice_type="v5e-8",       # single host -> one process
                    model="llama-tiny",
                    mesh=MeshAxesSpec(dp=-1),
                    max_restarts=0,
                    env=[
                        EnvVar("KFTPU_TRAIN_STEPS", "2"),
                        EnvVar("KFTPU_BATCH_PER_HOST", "2"),
                        EnvVar("KFTPU_SEQ_LEN", "16"),
                    ],
                ),
            ),
        ))

        t0 = time.time()
        while time.time() - t0 < E2E_TIMEOUT:
            mgr.run_until_idle(include_timers_within=1.0)
            kubelet.sync()
            mgr.run_until_idle(include_timers_within=1.0)
            study = api.get("StudyJob", "sweep", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
            time.sleep(0.3)
        kubelet.shutdown()
        assert study.status.condition == "Completed", study.status
        assert study.status.trials_completed == 2
        # Real losses flowed back as objectives.
        assert study.status.best_objective is not None
        assert study.status.best_objective > 0
        assert "learning_rate" in study.status.best_parameters


class TestServingE2E:
    """Serving CR whose pod is a REAL serving.server process: deploy ->
    wait ready -> query generate over HTTP -> delete (the reference's
    test_tf_serving.py lifecycle with an actual server)."""

    def test_deploy_query_real_server(self, tmp_path):
        import urllib.request

        from kubeflow_tpu.controlplane.api import Serving, ServingSpec
        from kubeflow_tpu.controlplane.controllers import ServingController

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ServingController(api, reg))
        port = _free_port()
        kubelet = ProcessKubelet(
            api, reg,
            env_overrides=lambda pod: {
                "KFTPU_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "JAX_PLATFORMS": "",
                "KFTPU_SERVING_HOST": "127.0.0.1",
            },
            log_dir=str(tmp_path / "podlogs"),
        )
        mgr.register(kubelet)

        api.create(Serving(
            metadata=ObjectMeta(name="llm", namespace="team-a"),
            spec=ServingSpec(
                model="llama-tiny", slice_type="v5e-8",
                max_batch=2, max_len=64, decode_chunk=2, port=port,
            ),
        ))
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert sv.status.ready  # pod Running (process spawned)

        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + E2E_TIMEOUT
        health = None
        while time.time() < deadline:
            kubelet.sync()
            try:
                health = json.load(urllib.request.urlopen(
                    f"{base}/healthz", timeout=2))
                break
            except OSError:
                time.sleep(0.5)
        assert health and health["ok"], health

        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"tokens": [3, 5, 7],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.load(urllib.request.urlopen(req, timeout=60))
        assert len(out["tokens"]) == 4
        kubelet.shutdown()


class TestServingReplicasE2E:
    """Two REAL serving replicas behind one LB endpoint: least-loaded
    dispatch, kill one replica mid-stream, the other absorbs new requests,
    and the controller heals the gang back to 2 (the reference's
    TF-Serving-Deployment-with-replicas semantics, test_tf_serving.py:60-100,
    upgraded with L7 load awareness)."""

    def test_two_replicas_kill_one_failover(self, tmp_path):
        import urllib.request

        from kubeflow_tpu.controlplane.api import Serving, ServingSpec
        from kubeflow_tpu.controlplane.controllers import ServingController
        from kubeflow_tpu.serving.lb import (
            ServingLBServer,
            ServingLoadBalancer,
        )

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ServingController(api, reg, drain_grace_s=0.2))
        kubelet = ProcessKubelet(
            api, reg,
            env_overrides=lambda pod: {
                "KFTPU_PLATFORM": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "JAX_PLATFORMS": "",
                "KFTPU_SERVING_HOST": "127.0.0.1",
            },
            log_dir=str(tmp_path / "podlogs"),
        )
        mgr.register(kubelet)

        # Two consecutive free ports (ordinal offset on a flat host net).
        base = None
        for _ in range(50):
            cand = _free_port()
            try:
                s = socket.socket()
                s.bind(("127.0.0.1", cand + 1))
                s.close()
                base = cand
                break
            except OSError:
                continue
        assert base is not None

        api.create(Serving(
            metadata=ObjectMeta(name="llm", namespace="team-a"),
            spec=ServingSpec(
                model="llama-tiny", slice_type="v5e-8", replicas=2,
                max_batch=2, max_len=128, decode_chunk=2, port=base,
            ),
        ))
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert sv.status.replicas == 2

        def wait_healthy(port, deadline):
            while time.time() < deadline:
                kubelet.sync()
                mgr.run_until_idle()
                try:
                    h = json.load(urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=2))
                    if h.get("ok"):
                        return True
                except OSError:
                    time.sleep(0.5)
            return False

        deadline = time.time() + E2E_TIMEOUT
        assert wait_healthy(base, deadline)
        assert wait_healthy(base + 1, deadline)
        mgr.run_until_idle()

        lb = ServingLoadBalancer()
        front = ServingLBServer(lb, api=api, namespace="team-a", name="llm")
        front.tick()
        assert len(lb.backends()) == 2
        front.start()
        lb_url = f"http://127.0.0.1:{front.port}/v1/generate"

        try:
            # open a stream through the LB
            req = urllib.request.Request(
                lb_url,
                data=json.dumps({"tokens": [3, 5, 7], "stream": True,
                                 "max_new_tokens": 512}).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = urllib.request.urlopen(req, timeout=60)
            first = json.loads(resp.readline())
            assert first.get("tokens"), first

            # find which replica holds the stream and SIGKILL it
            busy = [b for b in lb.backends() if b["in_flight"] == 1]
            assert len(busy) == 1
            busy_port = int(busy[0]["addr"].rsplit(":", 1)[1])
            ordinal = busy_port - base
            assert kubelet.kill_pod(f"llm-serving-{ordinal}", "team-a")

            # the stream dies (error chunk or truncation — never a hang)
            tail = [json.loads(l) for l in resp if l.strip()]
            assert not tail or "error" in tail[-1] or "done" not in tail[-1]

            # new requests go to the surviving replica
            out = json.load(_post_json(
                lb_url, {"tokens": [3, 5, 7], "max_new_tokens": 4}))
            assert len(out["tokens"]) == 4
            snap = {b["addr"]: b for b in lb.backends()}
            assert snap[busy[0]["addr"]]["healthy"] is False

            # controller heals: Failed pod recreated, back to 2 ready
            deadline = time.time() + E2E_TIMEOUT
            assert wait_healthy(busy_port, deadline)
            mgr.run_until_idle()
            sv = api.get("Serving", "llm", "team-a")
            assert sv.status.ready_replicas == 2
            front.tick()
            assert sum(b["healthy"] for b in lb.backends()) == 2
        finally:
            front.stop()
            kubelet.shutdown()


def _post_json(url, body, timeout=60):
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=timeout)
