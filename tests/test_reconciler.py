import threading
import time

import pytest

from kubeflow_tpu.controlplane.api import (
    ObjectMeta,
    Pod,
    Service,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.api.core import ServicePort, ServiceSpec
from kubeflow_tpu.controlplane.api.meta import OwnerReference
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    ControllerManager,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


class EchoServiceController(Controller):
    """Toy controller: every TpuJob gets a Service named <job>-svc."""

    NAME = "echo"
    WATCH_KINDS = ("TpuJob", "Service")

    def reconcile(self, namespace, name):
        job = self.api.try_get("TpuJob", name, namespace)
        if job is None:
            return Result()
        svc = Service(
            metadata=ObjectMeta(
                name=f"{name}-svc", namespace=namespace,
                owner_references=[OwnerReference(
                    kind="TpuJob", name=name, uid=job.metadata.uid)],
            ),
            spec=ServiceSpec(
                selector={"job": name},
                ports=[ServicePort(name="http", port=80, target_port=8888)],
            ),
        )
        create_or_update(self.api, svc)
        return Result()


def _mk(api=None):
    api = api or InMemoryApiServer()
    mgr = ControllerManager(api)
    ctl = EchoServiceController(api, registry=MetricsRegistry())
    mgr.register(ctl)
    return api, mgr, ctl


def _job(name="j1", ns="u"):
    return TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
                  spec=TpuJobSpec())


class TestReconcilerKernel:
    def test_creates_dependent(self):
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        assert api.get("Service", "j1-svc", "u").spec.selector == {"job": "j1"}

    def test_idempotent_second_pass(self):
        """The second-apply contract (testing/kfctl/kfctl_second_apply.py):
        reconciling an unchanged world must not produce new writes."""
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        rv = api.get("Service", "j1-svc", "u").metadata.resource_version
        mgr.run_until_idle()
        api_rv = api.get("Service", "j1-svc", "u").metadata.resource_version
        assert api_rv == rv

    def test_dependent_repair(self):
        """Deleting the dependent triggers re-creation via the secondary
        watch + map_to_primary (drift repair)."""
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        api.delete("Service", "j1-svc", "u")
        mgr.run_until_idle()
        assert api.try_get("Service", "j1-svc", "u") is not None

    def test_spec_drift_correction(self):
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        svc = api.get("Service", "j1-svc", "u")
        svc.spec.selector = {"job": "tampered"}
        api.update(svc)
        mgr.run_until_idle()
        assert api.get("Service", "j1-svc", "u").spec.selector == {"job": "j1"}

    def test_error_requeues_and_metrics(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)

        class Flaky(EchoServiceController):
            NAME = "flaky"
            fails = 2

            def reconcile(self, namespace, name):
                if Flaky.fails > 0:
                    Flaky.fails -= 1
                    raise RuntimeError("boom")
                return super().reconcile(namespace, name)

        ctl = Flaky(api, registry=MetricsRegistry())
        mgr.register(ctl)
        api.create(_job())
        mgr.run_until_idle(include_timers_within=2.0)
        assert ctl.metrics_reconcile.value(result="error") == 2
        assert api.try_get("Service", "j1-svc", "u") is not None

    def test_requeue_after(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)
        seen = []

        class Periodic(Controller):
            NAME = "periodic"
            WATCH_KINDS = ("TpuJob",)

            def reconcile(self, namespace, name):
                seen.append(name)
                if len(seen) < 3:
                    return Result(requeue_after=0.01)
                return Result()

        mgr.register(Periodic(api, registry=MetricsRegistry()))
        api.create(_job())
        mgr.run_until_idle(include_timers_within=1.0)
        assert len(seen) == 3

    def test_livelock_detection(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)

        class Hot(Controller):
            NAME = "hot"
            WATCH_KINDS = ("TpuJob",)

            def reconcile(self, namespace, name):
                # Unconditional write → generates MODIFIED → reconciles again.
                job = self.api.get("TpuJob", name, namespace)
                job.spec.max_restarts += 1
                self.api.update(job)
                return Result()

        mgr.register(Hot(api, registry=MetricsRegistry()))
        api.create(_job())
        with pytest.raises(RuntimeError, match="livelock"):
            mgr.run_until_idle(max_iterations=50)


class TestMonotonicTimers:
    """ISSUE 5 satellite: requeue/backoff timers key on time.monotonic().
    They used to mix wall-clock deadlines (_schedule/_due_timers) with
    monotonic queue-wait math — an NTP step fired or stalled every parked
    backoff timer."""

    def test_wall_clock_jump_does_not_fire_timers(self, monkeypatch):
        api, mgr, ctl = _mk()
        mgr._schedule(ctl, ("u", "j1"), after=30.0)
        # Jump the wall clock a year forward; the timer is 30 monotonic
        # seconds out and must stay parked.
        real_time = time.time
        monkeypatch.setattr(time, "time", lambda: real_time() + 3.15e7)
        mgr._due_timers()
        assert not mgr._pending
        assert len(mgr._timers) == 1

    def test_timers_fire_on_monotonic_deadline(self):
        api, mgr, ctl = _mk()
        mgr._schedule(ctl, ("u", "j1"), after=0.0)
        mgr._due_timers()
        assert len(mgr._pending) == 1
        assert not mgr._timers


class _Sentinel(Controller):
    """Reconcile body that records overlap of the SAME key with itself —
    the per-key serialization contract a worker pool must keep."""

    NAME = "sentinel"
    WATCH_KINDS = ("TpuJob",)

    def __init__(self, api, registry, dwell_s=0.0):
        super().__init__(api, registry=registry)
        self.dwell_s = dwell_s
        self.lock = threading.Lock()
        self.in_flight = {}
        self.overlaps = []
        self.counts = {}

    def reconcile(self, namespace, name):
        with self.lock:
            self.in_flight[name] = self.in_flight.get(name, 0) + 1
            if self.in_flight[name] > 1:
                self.overlaps.append(name)
            self.counts[name] = self.counts.get(name, 0) + 1
        if self.dwell_s:
            time.sleep(self.dwell_s)
        with self.lock:
            self.in_flight[name] -= 1
        return Result()


class TestWorkerPool:
    """ISSUE 5 tentpole: ControllerManager(workers=N) — client-go
    workqueue semantics under concurrent dispatch."""

    def test_workers_must_be_positive(self):
        with pytest.raises(ValueError, match="workers"):
            ControllerManager(InMemoryApiServer(), workers=0)

    def test_parallel_drain_converges_like_serial(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api, workers=4)
        ctl = EchoServiceController(api, registry=MetricsRegistry())
        mgr.register(ctl)
        for i in range(12):
            api.create(_job(f"j{i}"))
        mgr.run_until_idle()
        for i in range(12):
            assert api.try_get("Service", f"j{i}-svc", "u") is not None
        assert mgr.is_idle()
        mgr.close()

    def test_same_key_never_overlaps_itself(self):
        """Stress: a writer thread hammers updates into the watch stream
        while four workers drain — two reconciles of one key must never
        run concurrently (the in-flight set), and no update may be lost
        (the dirty set re-enqueues)."""
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api, reg, workers=4)
        ctl = _Sentinel(api, reg, dwell_s=0.001)
        mgr.register(ctl)
        names = [f"j{i}" for i in range(6)]
        for n in names:
            api.create(_job(n))

        done = threading.Event()

        def hammer():
            # Bounded: an open-ended writer would keep run_until_idle
            # legitimately busy forever.
            for i in range(300):
                name = names[i % len(names)]
                try:
                    live = api.get("TpuJob", name, "u")
                    live.status.phase = f"w{i}"
                    api.update_status(live)
                except Exception:
                    pass
            done.set()

        t = threading.Thread(target=hammer)
        t.start()
        try:
            while not done.is_set():
                mgr.run_until_idle(max_iterations=100000)
        finally:
            t.join()
        mgr.run_until_idle(max_iterations=100000)
        assert ctl.overlaps == []
        # No event lost: every key reconciled at least once and the
        # manager drained clean.
        assert set(ctl.counts) == set(names)
        assert mgr.is_idle()
        mgr.close()

    def test_dirty_while_in_flight_requeues_exactly_once(self):
        """Events arriving for an in-flight key coalesce into ONE
        follow-up reconcile — not zero (lost) and not one per event
        (duplicated)."""
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api, reg)
        seen = []

        class Dirtying(Controller):
            NAME = "dirtying"
            WATCH_KINDS = ("TpuJob",)

            def reconcile(self, namespace, name):
                seen.append(name)
                if len(seen) == 1:
                    # Simulate three watch deliveries for OUR OWN key
                    # landing mid-reconcile: the key is in flight, so all
                    # three must collapse into exactly one dirty requeue.
                    for _ in range(3):
                        mgr._enqueue(self, (namespace, name))
                return Result()

        ctl = Dirtying(api, registry=reg)
        mgr.register(ctl)
        api.create(_job())
        mgr.run_until_idle()
        assert seen == ["j1", "j1"]
        mgr.close()

    def test_inflight_gauge_registered(self):
        reg = MetricsRegistry()
        mgr = ControllerManager(InMemoryApiServer(), reg, workers=2)
        g = reg.get("kftpu_workqueue_inflight")
        assert g is not None and g.value() == 0.0
        mgr.close()
