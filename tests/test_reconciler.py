import pytest

from kubeflow_tpu.controlplane.api import (
    ObjectMeta,
    Pod,
    Service,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.api.core import ServicePort, ServiceSpec
from kubeflow_tpu.controlplane.api.meta import OwnerReference
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    ControllerManager,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


class EchoServiceController(Controller):
    """Toy controller: every TpuJob gets a Service named <job>-svc."""

    NAME = "echo"
    WATCH_KINDS = ("TpuJob", "Service")

    def reconcile(self, namespace, name):
        job = self.api.try_get("TpuJob", name, namespace)
        if job is None:
            return Result()
        svc = Service(
            metadata=ObjectMeta(
                name=f"{name}-svc", namespace=namespace,
                owner_references=[OwnerReference(
                    kind="TpuJob", name=name, uid=job.metadata.uid)],
            ),
            spec=ServiceSpec(
                selector={"job": name},
                ports=[ServicePort(name="http", port=80, target_port=8888)],
            ),
        )
        create_or_update(self.api, svc)
        return Result()


def _mk(api=None):
    api = api or InMemoryApiServer()
    mgr = ControllerManager(api)
    ctl = EchoServiceController(api, registry=MetricsRegistry())
    mgr.register(ctl)
    return api, mgr, ctl


def _job(name="j1", ns="u"):
    return TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
                  spec=TpuJobSpec())


class TestReconcilerKernel:
    def test_creates_dependent(self):
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        assert api.get("Service", "j1-svc", "u").spec.selector == {"job": "j1"}

    def test_idempotent_second_pass(self):
        """The second-apply contract (testing/kfctl/kfctl_second_apply.py):
        reconciling an unchanged world must not produce new writes."""
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        rv = api.get("Service", "j1-svc", "u").metadata.resource_version
        mgr.run_until_idle()
        api_rv = api.get("Service", "j1-svc", "u").metadata.resource_version
        assert api_rv == rv

    def test_dependent_repair(self):
        """Deleting the dependent triggers re-creation via the secondary
        watch + map_to_primary (drift repair)."""
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        api.delete("Service", "j1-svc", "u")
        mgr.run_until_idle()
        assert api.try_get("Service", "j1-svc", "u") is not None

    def test_spec_drift_correction(self):
        api, mgr, _ = _mk()
        api.create(_job())
        mgr.run_until_idle()
        svc = api.get("Service", "j1-svc", "u")
        svc.spec.selector = {"job": "tampered"}
        api.update(svc)
        mgr.run_until_idle()
        assert api.get("Service", "j1-svc", "u").spec.selector == {"job": "j1"}

    def test_error_requeues_and_metrics(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)

        class Flaky(EchoServiceController):
            NAME = "flaky"
            fails = 2

            def reconcile(self, namespace, name):
                if Flaky.fails > 0:
                    Flaky.fails -= 1
                    raise RuntimeError("boom")
                return super().reconcile(namespace, name)

        ctl = Flaky(api, registry=MetricsRegistry())
        mgr.register(ctl)
        api.create(_job())
        mgr.run_until_idle(include_timers_within=2.0)
        assert ctl.metrics_reconcile.value(result="error") == 2
        assert api.try_get("Service", "j1-svc", "u") is not None

    def test_requeue_after(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)
        seen = []

        class Periodic(Controller):
            NAME = "periodic"
            WATCH_KINDS = ("TpuJob",)

            def reconcile(self, namespace, name):
                seen.append(name)
                if len(seen) < 3:
                    return Result(requeue_after=0.01)
                return Result()

        mgr.register(Periodic(api, registry=MetricsRegistry()))
        api.create(_job())
        mgr.run_until_idle(include_timers_within=1.0)
        assert len(seen) == 3

    def test_livelock_detection(self):
        api = InMemoryApiServer()
        mgr = ControllerManager(api)

        class Hot(Controller):
            NAME = "hot"
            WATCH_KINDS = ("TpuJob",)

            def reconcile(self, namespace, name):
                # Unconditional write → generates MODIFIED → reconciles again.
                job = self.api.get("TpuJob", name, namespace)
                job.spec.max_restarts += 1
                self.api.update(job)
                return Result()

        mgr.register(Hot(api, registry=MetricsRegistry()))
        api.create(_job())
        with pytest.raises(RuntimeError, match="livelock"):
            mgr.run_until_idle(max_iterations=50)
