"""WAL crash-replay + atomic snapshot semantics (ISSUE 6 satellites).

Two durability layers under test:

- ``Platform.save`` is crash-safe on its own: the snapshot is written to
  a temp file and ``os.replace``d in, so a kill mid-save can never leave
  a truncated ``state.yaml`` (the next load reads the OLD snapshot).
- the WAL closes the between-saves window: every committed write is an
  fsync'd record, replay reconstructs the exact pre-crash store (gated
  on ``state_fingerprint`` equality), and a truncated final record — the
  expected shape of a crash mid-append — stops replay cleanly instead of
  poisoning it.
"""

import os

import pytest
import yaml

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import TpuJob, TpuJobSpec
from kubeflow_tpu.controlplane.benchmark import state_fingerprint
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.controlplane.runtime import InMemoryApiServer
from kubeflow_tpu.controlplane.wal import WriteAheadLog, wal_path


def _job(name, ns="team"):
    return TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
                  spec=TpuJobSpec(slice_type="v5e-16"))


class TestWalReplay:
    def test_replay_reconstructs_exact_state(self, tmp_path):
        api = InMemoryApiServer()
        wal = WriteAheadLog(wal_path(str(tmp_path)))
        wal.attach(api)
        api.create(_job("a"))
        api.create(_job("b"))
        obj = api.get("TpuJob", "a", "team")
        obj.status.phase = "Running"
        api.update_status(obj)
        spec = api.get("TpuJob", "b", "team")
        spec.spec.max_restarts = 9
        api.update(spec)
        api.create(_job("c"))
        api.delete("TpuJob", "c", "team")

        crashed = InMemoryApiServer()
        replayed = WriteAheadLog(wal_path(str(tmp_path))).replay(crashed)
        assert replayed == wal.appended == 6
        assert state_fingerprint(crashed.list_all()) == \
            state_fingerprint(api.list_all())
        # The rv counter survives too: post-replay writes cannot reuse
        # versions from before the crash.
        assert crashed._rv == api._rv
        assert crashed.get("TpuJob", "b", "team").spec.max_restarts == 9
        assert crashed.try_get("TpuJob", "c", "team") is None

    def test_truncated_tail_is_tolerated(self, tmp_path):
        api = InMemoryApiServer()
        wal = WriteAheadLog(wal_path(str(tmp_path)))
        wal.attach(api)
        api.create(_job("a"))
        api.create(_job("b"))
        # Crash mid-append: the final record is half a line.
        with open(wal.path, "a") as f:
            f.write('{"rv": 99, "op": "put", "obj": {"kind": "Tpu')
        crashed = InMemoryApiServer()
        assert WriteAheadLog(wal.path).replay(crashed) == 2
        assert crashed.try_get("TpuJob", "a", "team") is not None
        assert crashed._rv == api._rv

    def test_journal_records_are_ordered_and_fsynced_per_write(self, tmp_path):
        api = InMemoryApiServer()
        wal = WriteAheadLog(wal_path(str(tmp_path)))
        wal.attach(api)
        for i in range(5):
            api.create(_job(f"j{i}"))
        rvs = [r["rv"] for r in wal.records()]
        assert rvs == sorted(rvs) and len(set(rvs)) == 5


class TestPlatformIntegration:
    def _platform_with_job(self, tmp_path):
        platform = Platform()
        platform.attach_wal(str(tmp_path))
        platform.api.create(_job("train"))
        return platform

    def test_load_prefers_wal_replay_over_snapshot(self, tmp_path):
        platform = self._platform_with_job(tmp_path)
        platform.save(str(tmp_path))
        # Post-save writes land only in the WAL — the crash window.
        job = platform.api.get("TpuJob", "train", "team")
        job.status.phase = "Running"
        platform.api.update_status(job)
        platform.api.create(_job("late"))

        restored = Platform.load(str(tmp_path))
        assert restored.api.get("TpuJob", "train", "team",
                                copy=False).status.phase == "Running"
        assert restored.api.try_get("TpuJob", "late", "team") is not None
        assert state_fingerprint(restored.api.list_all()) == \
            state_fingerprint(platform.api.list_all())
        # load() re-attached the journal: the restored platform keeps
        # journaling without any caller opt-in.
        assert restored.wal is not None

    def test_save_compacts_the_wal(self, tmp_path):
        platform = self._platform_with_job(tmp_path)
        assert platform.wal.records()
        platform.save(str(tmp_path))
        assert platform.wal.records() == []
        # ... and the snapshot alone still restores everything.
        restored = Platform.load(str(tmp_path))
        assert restored.api.try_get("TpuJob", "train", "team") is not None

    def test_save_is_atomic_under_mid_dump_crash(self, tmp_path, monkeypatch):
        platform = Platform()
        platform.api.create(_job("precious"))
        platform.save(str(tmp_path))

        def exploding_dump(docs, stream, **kw):
            stream.write("kind: PlatformState\n---\n")   # partial garbage
            raise RuntimeError("kill -9 mid-dump")

        platform.api.create(_job("doomed"))
        monkeypatch.setattr(yaml, "safe_dump_all", exploding_dump)
        with pytest.raises(RuntimeError):
            platform.save(str(tmp_path))
        monkeypatch.undo()
        # The interrupted save must not have touched the real snapshot:
        # the OLD state loads intact (pre-fix, state.yaml was truncated
        # in place and the whole platform came back empty).
        restored = Platform.load(str(tmp_path))
        assert restored.api.try_get("TpuJob", "precious", "team") is not None

    def test_wal_survives_where_snapshot_alone_would_lose_writes(self, tmp_path):
        """The headline: kill after N un-saved writes; snapshot-only would
        resurrect the stale world, WAL replay resurrects the true one."""
        platform = self._platform_with_job(tmp_path)
        platform.save(str(tmp_path))
        for i in range(7):
            platform.api.create(_job(f"unsaved-{i}"))
        want = state_fingerprint(platform.api.list_all())
        # No save() — the process "dies" here.
        restored = Platform.load(str(tmp_path))
        assert state_fingerprint(restored.api.list_all()) == want
