"""Cross-shard admission ledger (ISSUE 8, PR-6 follow-up): the
capacity authority behind the leader lease. In-process tests drive the
real pipe transport (client ↔ service directly, or through the parent
relay that the sharded plane uses)."""

import multiprocessing

from kubeflow_tpu.controlplane.ledger import (
    CapacityLedger,
    LedgerClient,
    LedgerRelay,
    LedgerService,
    ledger_journal_path,
)


class TestCapacityLedger:
    def test_reserve_release_accounting(self):
        led = CapacityLedger({"v5e-16": 2})
        assert led.try_reserve("a", "v5e-16", 1) is None
        assert led.try_reserve("b", "v5e-16", 1) is None
        verdict = led.try_reserve("c", "v5e-16", 1)
        assert "2/2" in verdict
        assert led.release("a") is True
        assert led.release("a") is False          # idempotent
        assert led.try_reserve("c", "v5e-16", 1) is None

    def test_re_reserve_same_uid_is_idempotent(self):
        led = CapacityLedger({"v5e-16": 1})
        assert led.try_reserve("a", "v5e-16", 1) is None
        # The same gang re-admitting must not double-count itself.
        assert led.try_reserve("a", "v5e-16", 1) is None
        assert led.snapshot()["reservations"] == 1

    def test_denial_drops_stale_hold(self):
        led = CapacityLedger({"v5e-16": 2})
        assert led.try_reserve("a", "v5e-16", 1) is None
        assert led.try_reserve("b", "v5e-16", 1) is None
        # "a" grows to 2 slices: denied — and its old 1-slice hold must
        # drop (a parked gang cannot keep capacity it admitted for).
        assert led.try_reserve("a", "v5e-16", 2) is not None
        assert led.snapshot()["in_use"] == {"v5e-16": 1}

    def test_unknown_slice_type_has_zero_capacity(self):
        led = CapacityLedger({"v5e-16": 1})
        assert led.try_reserve("a", "v5p-8", 1) is not None


def _direct(capacity, journal=""):
    """Client wired straight to the service (one pipe, no relay)."""
    client_end, serve_end = multiprocessing.Pipe()
    svc = LedgerService(capacity, serve_end, journal_path=journal,
                        fsync=False).start()
    return svc, LedgerClient(client_end, timeout_s=5.0)


class TestLedgerServiceClient:
    def test_reserve_release_roundtrip(self):
        svc, cli = _direct({"v5e-16": 1})
        try:
            assert cli.try_reserve("a", "v5e-16", 1) is None
            verdict = cli.try_reserve("b", "v5e-16", 1)
            assert "1/1" in verdict
            cli.release("a")
            assert cli.try_reserve("b", "v5e-16", 1) is None
            assert cli.snapshot()["reservations"] == 1
        finally:
            svc.stop()

    def test_unreachable_ledger_fails_closed(self):
        client_end, _serve_end = multiprocessing.Pipe()  # nobody serving
        cli = LedgerClient(client_end, timeout_s=0.1)
        verdict = cli.try_reserve("a", "v5e-16", 1)
        assert verdict == LedgerClient.UNAVAILABLE
        cli.release("a")    # must not raise

    def test_failover_replays_journal(self, tmp_path):
        journal = ledger_journal_path(str(tmp_path))
        svc, cli = _direct({"v5e-16": 2}, journal=journal)
        try:
            assert cli.try_reserve("a", "v5e-16", 1) is None
            assert cli.try_reserve("b", "v5e-16", 1) is None
            cli.release("b")
        finally:
            svc.stop()      # the old leader dies
        # The NEXT leader replays the journal: "a" still holds, "b" was
        # released — failover must not reopen the double-admit window.
        svc2, cli2 = _direct({"v5e-16": 2}, journal=journal)
        try:
            snap = cli2.snapshot()
            assert snap["in_use"] == {"v5e-16": 1}
            assert cli2.try_reserve("c", "v5e-16", 1) is None
            assert cli2.try_reserve("d", "v5e-16", 1) is not None
            # Idempotent re-reserve of the replayed holder still works.
            assert cli2.try_reserve("a", "v5e-16", 1) is None
        finally:
            svc2.stop()

    def test_torn_journal_tail_is_ignored(self, tmp_path):
        journal = ledger_journal_path(str(tmp_path))
        svc, cli = _direct({"v5e-16": 2}, journal=journal)
        try:
            assert cli.try_reserve("a", "v5e-16", 1) is None
        finally:
            svc.stop()
        with open(journal, "a") as f:
            f.write('{"op": "reserve", "uid": "half')   # crash mid-append
        svc2, cli2 = _direct({"v5e-16": 2}, journal=journal)
        try:
            assert cli2.snapshot()["in_use"] == {"v5e-16": 1}
        finally:
            svc2.stop()


class TestLedgerRelay:
    def _mesh(self, capacity, leader_holder):
        """Two client pipes + two serve pipes + the relay, with the
        LedgerService on whichever id ``leader_holder`` names."""
        client_parent, client_child = {}, {}
        serve_parent, serve_child = {}, {}
        for i in (0, 1):
            client_parent[i], client_child[i] = multiprocessing.Pipe()
            serve_parent[i], serve_child[i] = multiprocessing.Pipe()
        relay = LedgerRelay(client_parent, serve_parent,
                            leader_of=lambda: leader_holder["id"]).start()
        services = {
            i: LedgerService(capacity, serve_child[i]).start()
            for i in (0, 1)
        }
        clients = {i: LedgerClient(client_child[i], timeout_s=5.0)
                   for i in (0, 1)}
        return relay, services, clients

    def test_routes_to_current_leader_and_redirects_on_election(self):
        leader = {"id": 0}
        relay, services, clients = self._mesh({"v5e-16": 1}, leader)
        try:
            # Shard 1's request lands on shard 0's ledger.
            assert clients[1].try_reserve("a", "v5e-16", 1) is None
            assert "1/1" in clients[0].try_reserve("b", "v5e-16", 1)
            assert services[0].served > 0 and services[1].served == 0
            # Election moves the lease: traffic redirects immediately.
            # (Shard 1's ledger is fresh — this test only checks
            # ROUTING; state continuity is the journal's job.)
            leader["id"] = 1
            assert clients[0].try_reserve("c", "v5e-16", 1) is None
            assert services[1].served > 0
        finally:
            relay.stop()
            for s in services.values():
                s.stop()

    def test_no_leader_fails_closed(self):
        leader = {"id": None}
        relay, services, clients = self._mesh({"v5e-16": 1}, leader)
        try:
            assert clients[0].try_reserve("a", "v5e-16", 1) \
                == LedgerClient.UNAVAILABLE
        finally:
            relay.stop()
            for s in services.values():
                s.stop()


class TestReviewHardening:
    def test_steady_state_re_reserve_does_not_grow_journal(self, tmp_path):
        journal = ledger_journal_path(str(tmp_path))
        svc, cli = _direct({"v5e-16": 2}, journal=journal)
        try:
            assert cli.try_reserve("a", "v5e-16", 1) is None
            size1 = __import__("os").path.getsize(journal)
            # The idempotent re-reserve every reconcile performs must
            # not append (one fsync per reconcile per job otherwise).
            for _ in range(5):
                assert cli.try_reserve("a", "v5e-16", 1) is None
            assert __import__("os").path.getsize(journal) == size1
            # A real change DOES journal.
            assert cli.try_reserve("a", "v5e-16", 2) is None
            assert __import__("os").path.getsize(journal) > size1
        finally:
            svc.stop()

    def test_start_compacts_journal_to_live_reservations(self, tmp_path):
        journal = ledger_journal_path(str(tmp_path))
        svc, cli = _direct({"v5e-16": 4}, journal=journal)
        try:
            for i in range(4):
                assert cli.try_reserve(f"u{i}", "v5e-16", 1) is None
            for i in range(3):
                cli.release(f"u{i}")
        finally:
            svc.stop()
        with open(journal) as f:
            assert len(f.readlines()) == 7      # full history
        svc2, cli2 = _direct({"v5e-16": 4}, journal=journal)
        try:
            assert cli2.snapshot()["in_use"] == {"v5e-16": 1}
            # Replay rewrote the log down to the one live reservation.
            with open(journal) as f:
                lines = f.readlines()
            assert len(lines) == 1 and '"uid": "u3"' in lines[0]
        finally:
            svc2.stop()

    def test_prune_drops_orphan_reservations(self, tmp_path):
        journal = ledger_journal_path(str(tmp_path))
        svc, cli = _direct({"v5e-16": 4}, journal=journal)
        try:
            assert cli.try_reserve("live", "v5e-16", 1) is None
            assert cli.try_reserve("orphan", "v5e-16", 1) is None
            dropped = svc.handle("prune", (["live"],))
            assert dropped == ["orphan"]
            assert svc.handle("prune", (["live"],)) == []   # idempotent
        finally:
            svc.stop()
        # The prune is journaled: a failover does not resurrect orphans.
        svc2, cli2 = _direct({"v5e-16": 4}, journal=journal)
        try:
            assert cli2.snapshot()["in_use"] == {"v5e-16": 1}
        finally:
            svc2.stop()

    def test_relay_drops_mismatched_replies(self):
        """A reply left over from an earlier (timed-out) forward —
        possibly for a DIFFERENT client whose own req_id collides — must
        never be delivered as the current request's verdict."""
        import threading

        client_parent, client_child = multiprocessing.Pipe()
        serve_parent, serve_child = multiprocessing.Pipe()
        relay = LedgerRelay({0: client_parent}, {0: serve_parent},
                            leader_of=lambda: 0)
        # Stale reply sitting in the serve pipe (id no forward used).
        serve_child.send((999, None))

        def leader():
            fwd_id, op, args, _ctx = serve_child.recv()
            assert op == "reserve"
            serve_child.send((fwd_id, "1/1 v5e-16 slices reserved "
                                      "cluster-wide"))
        t = threading.Thread(target=leader, daemon=True)
        t.start()
        relay._forward(0, (1, "reserve", ("uid", "v5e-16", 1)))
        t.join(timeout=5)
        req_id, payload = client_child.recv()
        assert req_id == 1
        assert payload is not None and "1/1" in payload     # NOT the stale None


class TestTraceStitching:
    """Cross-shard trace stitching (ISSUE 10): the ledger pipe-RPC
    carries the caller's (trace_id, span_id), and the leader-side
    service records each operation as a span IN the caller's trace —
    one trace id end to end, so `tpuctl trace` includes the reserve
    round-trip instead of an orphan span on the lease-holding shard."""

    def test_one_trace_id_client_to_relay_to_service(self):
        from kubeflow_tpu.utils.tracing import Tracer

        client_parent, client_child = multiprocessing.Pipe()
        serve_parent, serve_child = multiprocessing.Pipe()
        relay = LedgerRelay({0: client_parent}, {0: serve_parent},
                            leader_of=lambda: 0).start()
        leader_tracer = Tracer()
        svc = LedgerService({"v5e-16": 1}, serve_child,
                            tracer=leader_tracer).start()
        caller_tracer = Tracer()
        cli = LedgerClient(client_child, timeout_s=5.0)
        try:
            with caller_tracer.span("reconcile") as caller_span:
                assert cli.try_reserve("gang-a", "v5e-16", 1) is None
            spans = leader_tracer.spans("ledger.reserve")
            assert len(spans) == 1
            served = spans[0]
            # Same trace id end to end + a causal link back to the
            # calling span.
            assert served.trace_id == caller_span.trace_id
            assert tuple(served.links[0]) == caller_span.context
            assert served.attrs["uid"] == "gang-a"
            assert served.attrs["verdict"] == "reserved"
        finally:
            relay.stop()
            svc.stop()

    def test_denied_reserve_span_carries_verdict(self):
        from kubeflow_tpu.utils.tracing import Tracer

        tracer = Tracer()
        client_end, serve_end = multiprocessing.Pipe()
        svc = LedgerService({"v5e-16": 1}, serve_end,
                            tracer=tracer).start()
        cli = LedgerClient(client_end, timeout_s=5.0)
        try:
            caller = Tracer()
            with caller.span("reconcile"):
                assert cli.try_reserve("a", "v5e-16", 1) is None
                assert cli.try_reserve("b", "v5e-16", 1) is not None
            verdicts = [s.attrs["verdict"]
                        for s in tracer.spans("ledger.reserve")]
            assert verdicts[0] == "reserved" and "1/1" in verdicts[1]
        finally:
            svc.stop()

    def test_spanless_caller_and_legacy_3_tuple_still_serve(self):
        from kubeflow_tpu.utils.tracing import Tracer

        tracer = Tracer()
        client_end, serve_end = multiprocessing.Pipe()
        svc = LedgerService({"v5e-16": 1}, serve_end,
                            tracer=tracer).start()
        cli = LedgerClient(client_end, timeout_s=5.0)
        try:
            # No span open on the caller: ctx=None, no span recorded.
            assert cli.try_reserve("a", "v5e-16", 1) is None
            assert tracer.spans("ledger.reserve") == []
            # A pre-stitching peer sends 3-tuples: still answered.
            client_end.send((99, "snapshot", ()))
            assert client_end.poll(5)
            req_id, payload = client_end.recv()
            assert req_id == 99 and payload["reservations"] == 1
        finally:
            svc.stop()
