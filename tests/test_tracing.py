"""ISSUE 4 tentpole: the in-process tracer, its threading through the
apiserver + reconciler kernel (write-RV → reconcile span links, queue-wait
and watch-lag histograms), cross-thread propagation under
``ControllerManager.start()``, and log↔trace correlation."""

import json
import threading
import time

from kubeflow_tpu.controlplane.api import (
    ObjectMeta,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    ControllerManager,
    InMemoryApiServer,
    Result,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer, assemble_trace


class StatusTouchController(Controller):
    """Minimal controller: stamp a phase so reconciles write back."""

    NAME = "touch"
    WATCH_KINDS = ("TpuJob",)

    def reconcile(self, namespace, name):
        job = self.api.try_get("TpuJob", name, namespace)
        if job is None:
            return Result()
        if job.status.phase != "Touched":
            job.status.phase = "Touched"
            self.api.update_status(job)
        return Result()


def _world():
    tracer = Tracer()
    registry = MetricsRegistry()
    api = InMemoryApiServer(registry=registry, tracer=tracer)
    mgr = ControllerManager(api, registry, tracer=tracer)
    ctl = StatusTouchController(api, registry=MetricsRegistry())
    mgr.register(ctl)
    return tracer, registry, api, mgr


def _job(name="j1", ns="t"):
    return TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
                  spec=TpuJobSpec())


class TestTracerCore:
    def test_nesting_shares_trace_and_parent(self):
        tr = Tracer()
        with tr.span("outer") as outer:
            assert tr.current() is outer
            with tr.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        assert tr.current() is None
        names = [s.name for s in tr.spans()]
        assert names == ["inner", "outer"]      # recorded at close
        assert all(s.duration_s >= 0 for s in tr.spans())

    def test_sibling_traces_are_distinct(self):
        tr = Tracer()
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        a, b = tr.spans()
        assert a.trace_id != b.trace_id

    def test_explicit_trace_id_adoption(self):
        tr = Tracer()
        with tr.span("write") as w:
            pass
        with tr.span("reconcile", links=[w.context],
                     trace_id=w.trace_id) as r:
            pass
        assert r.trace_id == w.trace_id
        assert r.links == [w.context]

    def test_ring_buffer_bounds_memory(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        spans = tr.spans()
        assert len(spans) == 8
        assert spans[0].name == "s12"           # oldest evicted

    def test_attr_filtering(self):
        tr = Tracer()
        with tr.span("x", attrs={"kind": "TpuJob", "name": "a"}):
            pass
        with tr.span("x", attrs={"kind": "Pod", "name": "a"}):
            pass
        assert len(tr.spans("x", kind="TpuJob")) == 1

    def test_jsonl_round_trip(self, tmp_path):
        tr = Tracer()
        with tr.span("w", attrs={"rv": 3}) as w:
            pass
        with tr.span("r", links=[w.context]):
            pass
        p = str(tmp_path / "t.jsonl")
        assert tr.export_jsonl(p) == 2
        loaded = Tracer.load_jsonl(p)
        assert [s.name for s in loaded] == ["w", "r"]
        assert loaded[0].attrs["rv"] == 3
        assert loaded[1].links == [w.context]

    def test_export_new_never_duplicates(self, tmp_path):
        """Platform.save calls this once per tpuctl subcommand — repeated
        exports of an unchanged ring must append nothing."""
        tr = Tracer()
        p = str(tmp_path / "t.jsonl")
        with tr.span("a"):
            pass
        assert tr.export_new_jsonl(p) == 1
        assert tr.export_new_jsonl(p) == 0
        with tr.span("b"):
            pass
        assert tr.export_new_jsonl(p) == 1
        assert [s.name for s in Tracer.load_jsonl(p)] == ["a", "b"]


class TestKernelInstrumentation:
    def test_write_rv_link_reaches_reconcile_span(self):
        """The tentpole contract: the reconcile span triggered by a write's
        watch event links back to that write's span context and ADOPTS its
        trace id — one trace covers write → watch → reconcile → status
        update."""
        tracer, registry, api, mgr = _world()
        created = api.create(_job())
        create_span = tracer.spans("apiserver.create", kind="TpuJob")[-1]
        assert create_span.attrs["rv"] == created.metadata.resource_version
        mgr.run_until_idle()
        recons = tracer.spans("reconcile", controller="touch")
        assert recons, "no reconcile spans recorded"
        first = recons[0]
        assert create_span.context in first.links
        assert first.trace_id == create_span.trace_id
        assert first.attrs["outcome"] == "ok"
        # The status update the reconcile made nested under it: same
        # trace, parented to the reconcile span.
        status_spans = [
            s for s in tracer.spans("apiserver.update_status")
            if s.parent_id == first.span_id
        ]
        assert status_spans
        assert status_spans[0].trace_id == create_span.trace_id
        mgr.close()

    def test_latency_histograms_observe_each_reconcile(self):
        tracer, registry, api, mgr = _world()
        for i in range(3):
            api.create(_job(f"j{i}"))
        n = mgr.run_until_idle()
        hist = registry.get("kftpu_reconcile_duration_seconds")
        assert hist.count(controller="touch", result="ok") == n
        qwait = registry.get("kftpu_workqueue_wait_seconds")
        assert qwait.count(controller="touch") == n
        wlag = registry.get("kftpu_watch_delivery_lag_seconds")
        assert wlag.count(controller="touch") > 0
        # Per-verb apiserver latency histograms saw the writes too.
        verb = registry.get("kftpu_apiserver_request_duration_seconds")
        assert verb.count(verb="create") == 3
        assert verb.quantile(0.5, verb="create") is not None
        mgr.close()

    def test_propagation_across_manager_thread(self):
        """Satellite: spans recorded by the background start() thread still
        carry the main-thread write's span context — propagation is
        explicit (event stamps), not contextvar inheritance, so a fresh
        thread context must not sever the causal chain."""
        tracer, registry, api, mgr = _world()
        main_thread = threading.get_ident()
        with tracer.span("client.apply"):
            api.create(_job("bg"))
            create_span = tracer.spans("apiserver.create", kind="TpuJob")[-1]
        mgr.start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if tracer.spans("reconcile", controller="touch"):
                    job = api.get("TpuJob", "bg", "t")
                    if job.status.phase == "Touched":
                        break
                time.sleep(0.01)
        finally:
            mgr.stop()
        recons = tracer.spans("reconcile", controller="touch")
        assert recons, "background thread recorded no reconcile spans"
        assert create_span.context in recons[0].links
        assert recons[0].trace_id == create_span.trace_id
        # And the client-side root span really was on another thread than
        # the reconcile (start() pumps in its own thread).
        assert threading.get_ident() == main_thread
        mgr.close()

    def test_assemble_trace_covers_causal_chain(self):
        tracer, registry, api, mgr = _world()
        api.create(_job("asm"))
        mgr.run_until_idle()
        chain = assemble_trace(tracer.spans(), "TpuJob", "asm", "t")
        names = {s.name for s in chain}
        assert "apiserver.create" in names
        assert "reconcile" in names
        assert "apiserver.update_status" in names
        # Chronological by wall clock.
        starts = [s.start_unix for s in chain]
        assert starts == sorted(starts)
        mgr.close()


class TestJsonLogging:
    def test_json_format_carries_trace_ids(self, monkeypatch, capsys):
        """Satellite: KFTPU_LOG_FORMAT=json emits one JSON object per line
        with the active span's trace_id/span_id attached and kv pairs as
        structured fields."""
        from kubeflow_tpu.utils import logging as kflog

        monkeypatch.setenv("KFTPU_LOG_FORMAT", "json")
        kflog.configure(force=True)
        try:
            log = kflog.get_logger("tracetest")
            # A PRIVATE tracer, not the global one: Platform/benches run
            # their own, and correlation must still work (regression —
            # the formatter once read only global_tracer's context).
            with Tracer().span("op") as sp:
                log.info("hello", kv={"job": "j1", "n": 3})
            err = capsys.readouterr().err.strip().splitlines()
            rec = json.loads(err[-1])
            assert rec["msg"] == "hello"
            assert rec["job"] == "j1" and rec["n"] == 3
            assert rec["trace_id"] == sp.trace_id
            assert rec["span_id"] == sp.span_id
            assert rec["logger"] == "kubeflow_tpu.tracetest"
            # Outside any span: no trace fields, still valid JSON.
            log.info("bye")
            rec2 = json.loads(
                capsys.readouterr().err.strip().splitlines()[-1])
            assert "trace_id" not in rec2
        finally:
            monkeypatch.setenv("KFTPU_LOG_FORMAT", "text")
            kflog.configure(force=True)

    def test_text_format_unchanged(self, monkeypatch, capsys):
        from kubeflow_tpu.utils import logging as kflog

        monkeypatch.setenv("KFTPU_LOG_FORMAT", "text")
        kflog.configure(force=True)
        log = kflog.get_logger("texttest", component="x")
        log.info("plain", kv={"a": 1})
        err = capsys.readouterr().err
        assert "plain component=x a=1" in err
