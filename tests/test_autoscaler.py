"""ServingAutoscaler (ISSUE 7): latency-driven replica scaling with
hysteresis — scale-up fast on queue-wait pressure, scale-down only after
an uninterrupted stabilization window, bounds always clamped, every
decision traced and counted.

The scrape is injected (addr -> ServingEngine.load()-shaped dict) so the
control law is tested deterministically; the HTTP scrape path and the
closed loop against live replicas are covered by the serve bench
(tools/loadtest.run_serve_bench) and the CI serve-bench-smoke stage.
"""

import time

from kubeflow_tpu.controlplane.api import (
    AutoscaleSpec,
    ObjectMeta,
    Serving,
    ServingSpec,
)
from kubeflow_tpu.controlplane.controllers import (
    ServingAutoscaler,
    ServingController,
)
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer


def make_world(*, autoscale=None, replicas=1, endpoints=("e0:80",),
               stabilization_s=3600.0, scrape=None):
    """Api + autoscaler with an injected scrape. Default stabilization is
    effectively infinite so scale-down tests opt in explicitly."""
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    tracer = Tracer()
    loads = {}

    def default_scrape(addr):
        return loads.get(addr, {})

    asc = ServingAutoscaler(
        api, reg, tracer=tracer, interval_s=5.0,
        scale_down_stabilization_s=stabilization_s,
        scrape=scrape or default_scrape,
    )
    api.create(Serving(
        metadata=ObjectMeta(name="llm", namespace="team-a"),
        spec=ServingSpec(model="llama-tiny", replicas=replicas,
                         autoscale=autoscale),
    ))
    sv = api.get("Serving", "llm", "team-a")
    sv.status.endpoints = list(endpoints)
    api.update_status(sv)
    return api, asc, tracer, loads


def busy(p95):
    return {"queued": 3, "p95_queue_wait_s": p95, "p50_queue_wait_s": p95}


QUIET = {"queued": 0, "p95_queue_wait_s": 0.0, "p50_queue_wait_s": 0.0}


class TestScaleUp:
    def test_proportional_scale_up_over_target(self):
        api, asc, tracer, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1))
        loads["e0:80"] = busy(0.4)
        res = asc.reconcile("team-a", "llm")
        sv = api.get("Serving", "llm", "team-a")
        assert sv.spec.replicas == 4            # ceil(1 * 0.4 / 0.1)
        assert res.requeue_after == asc.interval_s
        assert asc.metrics_decisions.value(
            reason="queue-wait-above-target") == 3.0

    def test_scale_up_at_least_one_step(self):
        """Barely over target still adds a replica — overload must never
        round down to a no-op."""
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1),
            replicas=2, endpoints=("e0:80", "e1:80"))
        loads["e0:80"] = busy(0.11)
        loads["e1:80"] = QUIET                  # WORST replica drives
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 3

    def test_scale_up_clamps_to_max(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=3,
                                    target_queue_wait_s=0.05))
        loads["e0:80"] = busy(5.0)              # 100x over target
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 3

    def test_no_signal_no_action(self):
        """Unreachable replicas contribute no signal: replicas hold."""
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1),
            replicas=2, endpoints=("e0:80",))
        # scrape returns {} (default) -> no loads at all
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2

    def test_no_autoscale_spec_is_inert(self):
        api, asc, _, loads = make_world(autoscale=None, replicas=2)
        loads["e0:80"] = busy(9.0)
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2


class TestScaleDownHysteresis:
    def test_scale_down_waits_out_stabilization_window(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1),
            replicas=3, stabilization_s=0.2)
        loads["e0:80"] = dict(QUIET)
        asc.reconcile("team-a", "llm")          # clock starts
        assert api.get("Serving", "llm", "team-a").spec.replicas == 3
        time.sleep(0.25)
        asc.reconcile("team-a", "llm")          # window elapsed: ONE step
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2
        asc.reconcile("team-a", "llm")          # window restarted: hold
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2
        assert asc.metrics_decisions.value(
            reason="queue-wait-below-target") == 1.0

    def test_busy_scrape_resets_the_window(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1),
            replicas=2, stabilization_s=0.2)
        loads["e0:80"] = dict(QUIET)
        asc.reconcile("team-a", "llm")          # clock starts
        time.sleep(0.12)
        loads["e0:80"] = {"queued": 1, "p95_queue_wait_s": 0.06,
                          "p50_queue_wait_s": 0.06}   # in-band: reset
        asc.reconcile("team-a", "llm")
        loads["e0:80"] = dict(QUIET)
        time.sleep(0.12)                        # 0.24s since FIRST quiet,
        asc.reconcile("team-a", "llm")          # but only 0.12 since reset
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2

    def test_scale_down_stops_at_min(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=2, max_replicas=8,
                                    target_queue_wait_s=0.1),
            replicas=2, stabilization_s=0.0)
        loads["e0:80"] = dict(QUIET)
        asc.reconcile("team-a", "llm")
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2


class TestBounds:
    def test_below_min_clamps_up_even_when_quiet(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=3, max_replicas=8,
                                    target_queue_wait_s=0.1))
        loads["e0:80"] = dict(QUIET)
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 3
        assert asc.metrics_decisions.value(reason="min-replicas") == 2.0

    def test_above_max_clamps_down(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=2,
                                    target_queue_wait_s=0.1),
            replicas=5)
        asc.reconcile("team-a", "llm")
        assert api.get("Serving", "llm", "team-a").spec.replicas == 2
        assert asc.metrics_decisions.value(reason="max-replicas") == 3.0


class TestObservability:
    def test_decision_span_links_to_scrape_span(self):
        """One autoscale.decision span per scale step, LINKED to the
        autoscale.scrape span that triggered it — the same causal-link
        pattern as write->reconcile edges, renderable by tpuctl trace."""
        api, asc, tracer, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=8,
                                    target_queue_wait_s=0.1))
        loads["e0:80"] = busy(0.3)
        asc.reconcile("team-a", "llm")
        scrapes = tracer.spans("autoscale.scrape")
        decisions = tracer.spans("autoscale.decision")
        assert len(scrapes) == 1 and len(decisions) == 1
        assert decisions[0].links == [scrapes[0].context]
        assert decisions[0].attrs["reason"] == "queue-wait-above-target"
        assert decisions[0].attrs["from"] == 1
        assert decisions[0].attrs["to"] == 3
        # no-op reconciles emit a scrape span but no decision span
        loads["e0:80"] = {"queued": 0, "p95_queue_wait_s": 0.08,
                          "p50_queue_wait_s": 0.08}   # in-band
        asc.reconcile("team-a", "llm")
        assert len(tracer.spans("autoscale.decision")) == 1
        assert len(tracer.spans("autoscale.scrape")) == 2

    def test_scaled_event_recorded(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=4,
                                    target_queue_wait_s=0.1))
        loads["e0:80"] = busy(0.3)
        asc.reconcile("team-a", "llm")
        evs = [e for e in api.list("Event", namespace="team-a")
               if e.reason == "Scaled"]
        assert len(evs) == 1
        assert "1 -> 3" in evs[0].message


class TestClosedLoopWithServingController:
    def test_autoscaler_drives_pod_creation(self):
        """End to end through the manager: pressure -> autoscaler rewrites
        spec.replicas -> ServingController creates the pods -> endpoints
        grow. The observe->actuate loop the PR-4 layer was missing."""
        from kubeflow_tpu.controlplane.controllers import FakeKubelet

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(FakeKubelet(api, reg, outcome="running"))
        mgr.register(ServingController(api, reg))
        loads = {}
        asc = ServingAutoscaler(api, reg, tracer=Tracer(),
                                scrape=lambda a: dict(loads))
        mgr.register(asc)
        api.create(Serving(
            metadata=ObjectMeta(name="llm", namespace="team-a"),
            spec=ServingSpec(
                model="llama-tiny", replicas=1,
                autoscale=AutoscaleSpec(min_replicas=1, max_replicas=3,
                                        target_queue_wait_s=0.1)),
        ))
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert len(sv.status.endpoints) == 1
        loads.update(busy(0.35))                # every endpoint overloaded
        asc.reconcile("team-a", "llm")
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert sv.spec.replicas == 3
        assert len(sv.status.endpoints) == 3
        pods = api.list("Pod", namespace="team-a")
        assert len(pods) == 3
        mgr.close()

    def test_deleted_serving_clears_hysteresis_state(self):
        api, asc, _, loads = make_world(
            autoscale=AutoscaleSpec(min_replicas=1, max_replicas=4,
                                    target_queue_wait_s=0.1),
            replicas=2, stabilization_s=0.2)
        loads["e0:80"] = dict(QUIET)
        asc.reconcile("team-a", "llm")
        assert ("team-a", "llm") in asc._below_since
        api.delete("Serving", "llm", "team-a")
        asc.reconcile("team-a", "llm")
        assert ("team-a", "llm") not in asc._below_since
