"""Flash attention kernel vs the full-softmax reference.

Runs the pallas kernel in interpret mode on CPU (auto-selected), mirroring
the reference's envtest philosophy (suite_test.go:50-72): real kernel
semantics, no hardware. Forward AND backward are pinned against
ops.attention.mha_reference, including GQA head grouping, bf16 inputs, and
the (o, lse) blockwise-merge path that ring attention composes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_lse,
    merge_attention_blocks,
)

B, S, H, HKV, D = 2, 512, 4, 2, 64
BQ = BKV = 128


def _qkv(key, dtype=jnp.float32, s=S, hkv=HKV):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, s, H, D), dtype)
    k = jax.random.normal(kk, (B, s, hkv, D), dtype)
    v = jax.random.normal(kv, (B, s, hkv, D), dtype)
    return q, k, v


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("hkv", [H, HKV])
    def test_matches_reference_f32(self, causal, hkv):
        q, k, v = _qkv(jax.random.PRNGKey(0), hkv=hkv)
        got = flash_attention(q, k, v, causal=causal, block_q=BQ, block_kv=BKV)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def test_matches_reference_bf16(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True, block_q=BQ, block_kv=BKV)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            got.astype(np.float32), want.astype(np.float32),
            atol=2e-2, rtol=2e-2,
        )

    def test_uneven_blocks_fall_back(self):
        # S=96 doesn't block by 128 -> wrapper must fall back to reference.
        q, k, v = _qkv(jax.random.PRNGKey(2), s=96)
        got = flash_attention(q, k, v, causal=True, block_q=BQ, block_kv=BKV)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("hkv", [H, HKV])
    def test_grads_match_reference(self, causal, hkv):
        q, k, v = _qkv(jax.random.PRNGKey(3), hkv=hkv)
        co = jax.random.normal(jax.random.PRNGKey(4), (B, S, H, D))

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal,
                                block_q=BQ, block_kv=BKV) * co
            )

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) * co)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                got, want, atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch (causal={causal}, hkv={hkv})",
            )


class TestBlockwiseMerge:
    """The ring-attention composition: split kv in halves, attend per half
    with absolute offsets, merge with lse weights."""

    def _merged(self, q, k, v, causal):
        half = S // 2
        o1, lse1 = flash_attention_lse(
            q, k[:, :half], v[:, :half], causal=causal,
            q_offset=0, kv_offset=0, block_q=BQ, block_kv=BKV,
        )
        o2, lse2 = flash_attention_lse(
            q, k[:, half:], v[:, half:], causal=causal,
            q_offset=0, kv_offset=half, block_q=BQ, block_kv=BKV,
        )
        o, _ = merge_attention_blocks(o1, lse1, o2, lse2)
        return o

    @pytest.mark.parametrize("causal", [False, True])
    def test_merge_matches_full(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(5))
        got = self._merged(q, k, v, causal)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def test_merge_grads_match_full(self):
        q, k, v = _qkv(jax.random.PRNGKey(6))
        co = jax.random.normal(jax.random.PRNGKey(7), (B, S, H, D))

        g_merge = jax.grad(
            lambda q, k, v: jnp.sum(self._merged(q, k, v, True) * co),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) * co),
            argnums=(0, 1, 2),
        )(q, k, v)
        for got, want, name in zip(g_merge, g_ref, "qkv"):
            np.testing.assert_allclose(
                got, want, atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch through merge",
            )

    def test_fully_masked_block_is_neutral(self):
        # A kv block strictly after every q position (causal) must contribute
        # nothing and produce no NaNs — the ring sees this every rotation.
        q, k, v = _qkv(jax.random.PRNGKey(8))
        res = flash_attention_lse(
            q, k, v, causal=True, q_offset=0, kv_offset=S,
            block_q=BQ, block_kv=BKV,
        )
        o, lse = res
        assert not np.any(np.isnan(o))
        np.testing.assert_array_equal(np.asarray(o), 0.0)
        # Merging the dead block into a live one is an identity.
        live, lse_live = flash_attention_lse(
            q, k, v, causal=True, block_q=BQ, block_kv=BKV,
        )
        merged, _ = merge_attention_blocks(live, lse_live, o, lse)
        np.testing.assert_allclose(merged, live, atol=1e-6, rtol=1e-6)


class TestChunkedBackward:
    """Long query ranges chunk the fused backward (dq_all VMEM budget,
    _bwd_impl): shrinking the module budget forces the chunked path at
    test shapes; gradients must match the single-call kernel exactly
    (same math, different partitioning)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_single_call(self, causal, monkeypatch):
        from kubeflow_tpu.ops import flash_attention as fa_mod

        q, k, v = _qkv(jax.random.PRNGKey(11), hkv=HKV)
        co = jax.random.normal(jax.random.PRNGKey(12), (B, S, H, D))

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal,
                                block_q=BQ, block_kv=BKV) * co
            )

        g_single = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        # G=2, D=64: 64 KiB -> 128 q rows per chunk -> 4 chunks at S=512.
        monkeypatch.setattr(fa_mod, "_DQ_VMEM_BUDGET", 64 * 1024)
        g_chunked = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_single, g_chunked, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
                err_msg=f"d{name} chunked mismatch (causal={causal})",
            )
