"""KubectlApiServer integration: controllers run UNMODIFIED against a
kubectl backend (here the fake_kubectl test double — real exec + JSON
serialization + apiserver error semantics at a process boundary).

This is the acceptance for the real-backend seam: the substitution claim
in runtime/apiserver.py is code, not a comment.
"""

import subprocess
import sys
from pathlib import Path

import pytest

from kubeflow_tpu.controlplane.api import (
    Notebook,
    NotebookSpec,
    ObjectMeta,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.runtime.apiserver import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from kubeflow_tpu.controlplane.runtime.kubectl import (
    KubectlApiServer,
    resource_for,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry

FAKE = Path(__file__).parent / "fake_kubectl.py"


@pytest.fixture()
def api(tmp_path, monkeypatch):
    monkeypatch.setenv("FAKE_KUBECTL_DIR", str(tmp_path / "store"))
    # Invoke the double through the same interpreter (no +x / shebang
    # needs). -S skips site initialisation: the double is stdlib-only and
    # this host's sitecustomize costs ~1.8s per interpreter start — paid
    # on EVERY kubectl call otherwise.
    wrapper = tmp_path / "kubectl"
    wrapper.write_text(
        f"#!/bin/sh\nexec {sys.executable} -S {FAKE} \"$@\"\n"
    )
    wrapper.chmod(0o755)
    return KubectlApiServer(kubectl=str(wrapper))


def _job(name="train", ns="team-a"):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny"),
    )


class TestKubectlCrud:
    def test_create_get_roundtrip(self, api):
        created = api.create(_job())
        assert created.metadata.uid
        assert created.metadata.resource_version > 0
        got = api.get("TpuJob", "train", "team-a")
        assert got.spec.model == "llama-tiny"
        assert got.metadata.uid == created.metadata.uid

    def test_already_exists_and_not_found(self, api):
        api.create(_job())
        with pytest.raises(AlreadyExistsError):
            api.create(_job())
        with pytest.raises(NotFoundError):
            api.get("TpuJob", "nope", "team-a")
        assert api.try_get("TpuJob", "nope", "team-a") is None

    def test_update_conflict_on_stale_rv(self, api):
        api.create(_job())
        a = api.get("TpuJob", "train", "team-a")
        b = api.get("TpuJob", "train", "team-a")
        a.spec.max_restarts = 7
        api.update(a)
        b.spec.max_restarts = 9
        with pytest.raises(ConflictError):
            api.update(b)

    def test_update_status_preserves_live_spec(self, api):
        api.create(_job())
        stale = api.get("TpuJob", "train", "team-a")
        live = api.get("TpuJob", "train", "team-a")
        live.spec.max_restarts = 5
        api.update(live)
        stale.status.phase = "Running"
        api.update_status(stale)
        got = api.get("TpuJob", "train", "team-a")
        assert got.status.phase == "Running"
        assert got.spec.max_restarts == 5      # concurrent spec write won

    def test_update_status_retries_past_racing_writer(self, api,
                                                      monkeypatch):
        """A writer landing between update_status's read and replace must
        not surface a Conflict — the in-memory backend's status write
        always succeeds against a live object, and the adapter keeps that
        contract by rereading (controller-runtime's RetryOnConflict)."""
        api.create(_job())
        stale = api.get("TpuJob", "train", "team-a")
        real_get = KubectlApiServer.get
        raced = {"n": 0}

        def racing_get(self_, kind, name, namespace=""):
            out = real_get(self_, kind, name, namespace)
            if raced["n"] == 0:
                raced["n"] += 1
                live = real_get(self_, kind, name, namespace)
                live.spec.max_restarts = 9
                self_.update(live)      # concurrent spec write wins the rv
            return out

        monkeypatch.setattr(KubectlApiServer, "get", racing_get)
        stale.status.phase = "Running"
        api.update_status(stale)
        monkeypatch.setattr(KubectlApiServer, "get", real_get)
        got = api.get("TpuJob", "train", "team-a")
        assert got.status.phase == "Running"
        assert got.spec.max_restarts == 9      # the racer's spec survived
        assert raced["n"] == 1                 # exactly one retry needed

    def test_update_status_conflict_retries_are_bounded(self, api,
                                                        monkeypatch):
        api.create(_job())
        stale = api.get("TpuJob", "train", "team-a")
        real_get = KubectlApiServer.get
        raced = {"n": 0}

        def always_racing_get(self_, kind, name, namespace=""):
            out = real_get(self_, kind, name, namespace)
            raced["n"] += 1
            live = real_get(self_, kind, name, namespace)
            live.spec.max_restarts = raced["n"]
            self_.update(live)
            return out

        monkeypatch.setattr(KubectlApiServer, "get", always_racing_get)
        stale.status.phase = "Running"
        with pytest.raises(ConflictError):
            api.update_status(stale)
        assert raced["n"] == KubectlApiServer.STATUS_CONFLICT_RETRIES

    def test_list_with_selector_and_namespace(self, api):
        j1 = _job("a", "team-a")
        j1.metadata.labels["tier"] = "prod"
        j2 = _job("b", "team-a")
        j3 = _job("c", "team-b")
        for j in (j1, j2, j3):
            api.create(j)
        assert {j.metadata.name for j in api.list("TpuJob")} == {"a", "b", "c"}
        assert [j.metadata.name
                for j in api.list("TpuJob", namespace="team-b")] == ["c"]
        assert [j.metadata.name
                for j in api.list("TpuJob", namespace="team-a",
                                  label_selector={"tier": "prod"})] == ["a"]

    def test_delete_cascades_owner_references(self, api):
        from kubeflow_tpu.controlplane.api.meta import OwnerReference
        from kubeflow_tpu.controlplane.api import Pod
        from kubeflow_tpu.controlplane.api.core import PodSpec

        owner = api.create(_job())
        pod = Pod(metadata=ObjectMeta(
            name="train-w0", namespace="team-a",
            owner_references=[OwnerReference(
                kind="TpuJob", name="train", uid=owner.metadata.uid)],
        ), spec=PodSpec())
        api.create(pod)
        api.delete("TpuJob", "train", "team-a")
        assert api.try_get("Pod", "train-w0", "team-a") is None

    def test_resource_names(self):
        assert resource_for("TpuJob") == "tpujobs.tpu.kubeflow.org"
        assert resource_for("Pod") == "pods"
        assert resource_for("VirtualService") == \
            "virtualservices.networking.istio.io"


class TestKubectlWatch:
    def test_poll_diffs_into_events(self, api):
        q = api.watch("TpuJob")
        api.create(_job())
        assert api.poll_now() >= 1
        ev = q.get_nowait()
        assert ev.type == "ADDED" and ev.object.metadata.name == "train"

        live = api.get("TpuJob", "train", "team-a")
        live.spec.max_restarts = 2
        api.update(live)
        api.poll_now()
        assert q.get_nowait().type == "MODIFIED"

        api.delete("TpuJob", "train", "team-a")
        api.poll_now()
        ev = q.get_nowait()
        assert ev.type == "DELETED" and ev.object.metadata.name == "train"


class TestControllersOnKubectl:
    def test_notebook_controller_unmodified(self, api):
        """The seam's point: NotebookController (written against the
        in-memory store) reconciles through kubectl untouched."""
        from kubeflow_tpu.controlplane.controllers import NotebookController
        from kubeflow_tpu.controlplane.runtime import ControllerManager

        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(NotebookController(api, reg))

        api.create(Notebook(
            metadata=ObjectMeta(name="nb", namespace="team-a"),
            spec=NotebookSpec(image="jupyter:latest"),
        ))
        api.poll_now()
        mgr.run_until_idle()

        pod = api.get("Pod", "nb-0", "team-a")
        assert pod.spec.containers[0].image == "jupyter:latest"
        svc = api.get("Service", "nb", "team-a")
        assert svc.spec.ports[0].target_port == 8888
        vs = api.get("VirtualService", "notebook-nb", "team-a")
        assert vs.http[0].prefix == "/notebook/team-a/nb/"

        # Pod phase flip -> status mirrored on next poll+drain, exactly as
        # on the in-memory backend.
        pod.status.phase = "Running"
        api.update(pod)
        api.poll_now()
        mgr.run_until_idle()
        nb = api.get("Notebook", "nb", "team-a")
        assert nb.status.ready_replicas == 1
        assert nb.status.container_state == "Running"


class TestControllerUnderConcurrentWriters:
    def test_reconcile_loop_converges_with_racing_spec_writes(self, api):
        """One controller reconcile loop through the kubectl backend while
        an external writer keeps editing the CR spec: every status write
        races a spec write, and the loop must converge on the LAST spec
        with no Conflict surfacing (the optimistic-concurrency story the
        in-memory backend proves, held through the adapter). Uses the
        Serving controller because it replaces pods on spec drift — the
        converging observable."""
        from kubeflow_tpu.controlplane.api import Serving, ServingSpec
        from kubeflow_tpu.controlplane.controllers import ServingController
        from kubeflow_tpu.controlplane.runtime import ControllerManager

        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ServingController(api, reg))

        api.create(Serving(
            metadata=ObjectMeta(name="llm", namespace="team-a"),
            spec=ServingSpec(model="llama-tiny", slice_type="v5e-8",
                             image="serving:v0"),
        ))
        api.poll_now()
        mgr.run_until_idle()

        def write_spec(image):
            # external writers retry their own conflicts, like any client
            for _ in range(10):
                live = api.get("Serving", "llm", "team-a")
                live.spec.image = image
                try:
                    api.update(live)
                    return
                except ConflictError:
                    continue
            raise AssertionError("writer starved")

        for i in range(1, 6):
            write_spec(f"serving:v{i}")
            # interleave: poll (controller sees the new spec), reconcile
            # (controller rewrites pod + status), then ANOTHER spec write
            # lands before the next poll — the reread-retry window.
            api.poll_now()
            mgr.run_until_idle()
        api.poll_now()
        mgr.run_until_idle()

        pod = api.get("Pod", "llm-serving-0", "team-a")
        assert pod.spec.containers[0].image == "serving:v5"
        sv = api.get("Serving", "llm", "team-a")
        assert sv.spec.image == "serving:v5"
        # status writes kept landing throughout (none lost to Conflicts)
        assert sv.status.replicas == 1


class TestKubectlWatchReplay:
    def test_late_subscriber_gets_existing_objects(self, api):
        """A watch registered after the kind was already polled must replay
        current state as ADDED (the informer contract controllers rely on)."""
        q1 = api.watch("TpuJob")
        api.create(_job("a"))
        api.poll_now()
        q1.get_nowait()                      # q1 saw the ADDED

        q2 = api.watch("TpuJob")             # late subscriber
        ev = q2.get_nowait()
        assert ev.type == "ADDED" and ev.object.metadata.name == "a"
        # And the replay must not duplicate into the next poll for q2
        # beyond at most one benign MODIFIED.
        api.poll_now()
        assert q2.qsize() <= 1

    def test_unscoped_watch_rejected(self, api):
        from kubeflow_tpu.controlplane.runtime.apiserver import ApiError

        with pytest.raises(ApiError, match="kind-scoped"):
            api.watch(None)


class TestTpuctlKubectlBackend:
    def test_apply_get_delete_against_cluster(self, api, tmp_path):
        """tpuctl --backend kubectl targets the (fake) cluster: apply is
        create-or-update, get lists live objects, delete removes them."""
        from kubeflow_tpu.tools.tpuctl import main as tpuctl

        manifest = tmp_path / "job.yaml"
        manifest.write_text(
            "kind: TpuJob\n"
            "metadata: {name: train, namespace: team-a}\n"
            "spec: {sliceType: v5e-16, model: llama-tiny}\n"
        )
        flags = ["--backend", "kubectl", "--kubectl-bin", api.kubectl]
        assert tpuctl(flags + ["apply", "-f", str(manifest)]) == 0
        got = api.get("TpuJob", "train", "team-a")
        assert got.spec.model == "llama-tiny"

        # Second apply with identical spec: no-op (resourceVersion stable).
        rv1 = got.metadata.resource_version
        assert tpuctl(flags + ["apply", "-f", str(manifest)]) == 0
        assert api.get("TpuJob", "train", "team-a"
                       ).metadata.resource_version == rv1

        # Spec change: update flows through.
        manifest.write_text(
            "kind: TpuJob\n"
            "metadata: {name: train, namespace: team-a}\n"
            "spec: {sliceType: v5e-16, model: llama-tiny, maxRestarts: 9}\n"
        )
        assert tpuctl(flags + ["apply", "-f", str(manifest)]) == 0
        assert api.get("TpuJob", "train", "team-a").spec.max_restarts == 9

        assert tpuctl(flags + ["delete", "--kind", "TpuJob",
                               "--name", "train", "-n", "team-a"]) == 0
        assert api.try_get("TpuJob", "train", "team-a") is None

    def test_deleted_tombstone_carries_owner_refs(self, api):
        """DELETED events must carry the full last-seen object so
        secondary-kind deletions map back to the owning primary."""
        from kubeflow_tpu.controlplane.api import Pod
        from kubeflow_tpu.controlplane.api.core import PodSpec
        from kubeflow_tpu.controlplane.api.meta import OwnerReference

        owner = api.create(_job())
        q = api.watch("Pod")
        api.create(Pod(metadata=ObjectMeta(
            name="train-w0", namespace="team-a",
            owner_references=[OwnerReference(
                kind="TpuJob", name="train", uid=owner.metadata.uid)],
        ), spec=PodSpec()))
        api.poll_now()
        assert q.get_nowait().type == "ADDED"
        api.delete("Pod", "train-w0", "team-a")
        api.poll_now()
        ev = q.get_nowait()
        assert ev.type == "DELETED"
        assert ev.object.metadata.owner_references[0].name == "train"


class TestControlPlaneMain:
    def test_build_and_reconcile_against_kubectl(self, api):
        """The in-cluster entrypoint wires every controller against the
        kubectl backend; a Notebook reconciles through real exec."""
        from kubeflow_tpu.controlplane.main import build, build_parser

        args = build_parser().parse_args([
            "--backend", "kubectl", "--kubectl-bin", api.kubectl,
            "--metrics-port", "-1",
        ])
        k_api, manager, prober, registry = build(args)
        assert len(manager.controllers) == 6

        k_api.create(Notebook(
            metadata=ObjectMeta(name="nb", namespace="team-a"),
            spec=NotebookSpec(image="jupyter:latest"),
        ))
        k_api.poll_now()
        manager.run_until_idle()
        assert k_api.get("Pod", "nb-0", "team-a") is not None
        assert prober.probe() is True
        assert "kftpu_availability 1" in registry.render()

    def test_unknown_component_exits(self, api):
        from kubeflow_tpu.controlplane.main import build, build_parser

        args = build_parser().parse_args([
            "--backend", "memory", "--components", "tpujob,nope",
        ])
        with pytest.raises(SystemExit):
            build(args)


class TestKubectlPodLogs:
    def test_pod_logs_and_notfound(self, api):
        from kubeflow_tpu.controlplane.api.core import Container, Pod, PodSpec
        from kubeflow_tpu.controlplane.runtime.apiserver import NotFoundError

        api.create(Pod(
            metadata=ObjectMeta(name="w0", namespace="team-a"),
            spec=PodSpec(containers=[Container(name="main")]),
        ))
        out = api.pod_logs("w0", namespace="team-a")
        assert "log line from w0" in out
        with pytest.raises(NotFoundError):
            api.pod_logs("missing", namespace="team-a")
