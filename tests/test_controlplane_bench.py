"""Control-plane bench driver + latency soak profile (ISSUE 3): the
tier-1 wiring for ``bench.py controlplane``, the CI ``cp-bench-smoke``
copy-counter gate, and the chaos layer's latency_s injection."""

import pytest

from kubeflow_tpu.chaos import run_soak
from kubeflow_tpu.controlplane.benchmark import run_controlplane_sweep
from kubeflow_tpu.tools.ci import GateFailure, run_cp_bench_smoke


class TestControlPlaneSweep:
    def test_sweep_converges_and_counts(self):
        rep = run_controlplane_sweep(num_jobs=24, num_namespaces=4)
        assert rep.all_succeeded, rep.phases
        assert rep.pods == 24 * 4                 # v5e-16: 4-host gangs
        assert rep.reconciles > 0
        assert rep.wall_s > 0

    def test_list_copies_scale_with_matches_not_store(self):
        """The acceptance assertion at small N: the probe list's deepcopy
        count equals its matches and stays far under the store size."""
        rep = run_controlplane_sweep(num_jobs=24, num_namespaces=4)
        assert rep.list_matches == 6              # 24 jobs / 4 namespaces
        assert rep.copies_scale_with_matches, (
            rep.list_copies, rep.list_matches)
        # Store: 24 jobs + 96 pods + 24 services + events >> 6 matches.
        assert rep.store_objects > 10 * rep.list_copies

    def test_copy_counts_are_deterministic(self):
        """Count-based gating only works if the tally is a pure function of
        the (single-threaded) drive sequence — same run, same numbers."""
        a = run_controlplane_sweep(num_jobs=8, num_namespaces=2)
        b = run_controlplane_sweep(num_jobs=8, num_namespaces=2)
        assert a.copied_during_sweep == b.copied_during_sweep
        assert (a.list_matches, a.list_copies) == \
            (b.list_matches, b.list_copies)
        assert a.reconciles == b.reconciles

    def test_ci_cp_bench_smoke_stage(self):
        run_cp_bench_smoke(num_jobs=20, num_namespaces=4)

    def test_sweep_reports_latency_percentiles(self):
        """ISSUE 4 acceptance: `bench.py controlplane` JSON carries
        reconcile-latency and queue-wait p50/p95/p99 — latency
        decomposition next to throughput."""
        rep = run_controlplane_sweep(num_jobs=12, num_namespaces=3)
        summary = rep.summary()
        for key in ("reconcile_latency_s", "queue_wait_s"):
            pcts = summary[key]
            assert {"p50", "p95", "p99"} <= set(pcts), (key, pcts)
            assert 0 <= pcts["p50"] <= pcts["p95"] <= pcts["p99"]
        # One reconcile span per reconcile executed (count-based).
        assert summary["reconcile_spans"] == rep.reconciles > 0

    def test_ci_obs_smoke_stage(self):
        """The new CI stage: live scrape parses and span/histogram counts
        match reconciles exactly."""
        from kubeflow_tpu.tools.ci import run_obs_smoke

        run_obs_smoke(num_jobs=8, num_namespaces=2)

    def test_ci_gate_raises_on_unconverged(self, monkeypatch):
        import kubeflow_tpu.tools.ci as ci

        def broken(**kw):
            rep = run_controlplane_sweep(num_jobs=4, num_namespaces=2)
            rep.all_succeeded = False
            return rep

        monkeypatch.setattr(
            "kubeflow_tpu.controlplane.benchmark.run_controlplane_sweep",
            broken)
        with pytest.raises(GateFailure, match="converge"):
            ci.run_cp_bench_smoke(num_jobs=4, num_namespaces=2)


class TestWorkerPoolSweep:
    """ISSUE 5: the ``--workers`` scaling sweep's correctness half —
    worker-pool and serial dispatch must converge to the IDENTICAL world
    (count-based state signature), with the O(matches) copy contract
    intact under concurrency."""

    def test_final_state_identical_across_worker_counts(self):
        serial = run_controlplane_sweep(num_jobs=20, num_namespaces=4)
        for workers in (2, 4):
            par = run_controlplane_sweep(num_jobs=20, num_namespaces=4,
                                         workers=workers)
            assert par.all_succeeded, par.phases
            assert par.workers == workers
            assert par.state_signature == serial.state_signature, (
                par.final_state, serial.final_state)
            assert par.copies_scale_with_matches

    def test_signature_detects_divergence(self):
        """The gate actually discriminates: a different fleet produces a
        different signature."""
        a = run_controlplane_sweep(num_jobs=8, num_namespaces=2)
        b = run_controlplane_sweep(num_jobs=9, num_namespaces=2)
        assert a.state_signature != b.state_signature

    def test_rtt_profile_converges_with_workers(self):
        """The scaling sweep's measurement profile (modeled per-verb API
        RTT) through the pool: semantics unchanged, state identical to
        the zero-RTT serial world."""
        base = run_controlplane_sweep(num_jobs=8, num_namespaces=2)
        rep = run_controlplane_sweep(num_jobs=8, num_namespaces=2,
                                     workers=4, rtt_s=0.0002)
        assert rep.all_succeeded, rep.phases
        assert rep.state_signature == base.state_signature

    def test_ci_cp_bench_smoke_includes_workers_gate(self, monkeypatch):
        from kubeflow_tpu.tools import ci

        real = run_controlplane_sweep

        def diverging(**kw):
            rep = real(**kw)
            if kw.get("workers", 1) > 1:
                rep.state_signature = "deadbeef"
            return rep

        monkeypatch.setattr(
            "kubeflow_tpu.controlplane.benchmark.run_controlplane_sweep",
            diverging)
        with pytest.raises(GateFailure, match="DIFFERENT world"):
            ci.run_cp_bench_smoke(num_jobs=6, num_namespaces=2, workers=2)


class TestLatencySoakProfile:
    def test_latency_soak_converges(self):
        """The ROADMAP follow-up made tier-1: per-verb injected latency —
        a slow apiserver — must not deadlock the backoff timers or the
        cached read path; the fleet still fully converges."""
        rep = run_soak(num_jobs=2, seed=5, conflict_rate=0.2,
                       transient_rate=0.05, latency_s=0.002,
                       fault_rounds=6, max_rounds=40)
        assert rep.converged, rep.stuck_jobs()
        assert rep.all_succeeded, rep.phases
        assert rep.availability == 1.0

    def test_ci_latency_smoke_variant(self):
        from kubeflow_tpu.tools.ci import run_chaos_smoke

        run_chaos_smoke(seed=20260803, latency_s=0.001)
