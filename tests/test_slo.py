"""Fleet SLO engine + flight recorder (ISSUE 15): burn-rate windows,
alert-state-machine hysteresis edges, exemplar capture bounds and
resolution, alert-journal replay byte-identity (torn tail and rotation
included), flight-ring overflow/ordering, and cross-shard stitching."""

import json
import os

from kubeflow_tpu.obs.flight import FlightRecorder, flight_paths, stitch
from kubeflow_tpu.obs.slo import (
    ALERTS_JOURNAL,
    Objective,
    SLOEngine,
    Windows,
    interruption_delta_source,
    soak_objectives,
)
from kubeflow_tpu.utils.monitoring import (
    EXEMPLAR_LABELSET_CAP,
    MetricsRegistry,
)
from kubeflow_tpu.utils.tracing import Tracer

#: Tiny deterministic windows: fast pair (2, 4), slow pair (6, 12).
W = Windows(fast_short=2, fast_long=4, slow_short=6, slow_long=12)


def _engine(reg, *, threshold=0.25, slo=0.9, page_burn=2.0,
            warn_burn=1.0, clear_after=2, **kw):
    return SLOEngine(reg, objectives=[Objective(
        name="lat", metric="lat", threshold_s=threshold, slo=slo,
        page_burn=page_burn, warn_burn=warn_burn, windows=W,
        clear_after=clear_after)], **kw)


class TestObjectiveValidation:
    def test_exactly_one_source(self):
        import pytest

        with pytest.raises(ValueError):
            Objective(name="x")
        with pytest.raises(ValueError):
            Objective(name="x", metric="m", gauge="g")
        with pytest.raises(ValueError):
            Objective(name="x", metric="m", slo=1.0)
        with pytest.raises(ValueError):
            Objective(name="x", value_fn=lambda: 0.0, group_by="t")

    def test_duplicate_names_rejected(self):
        import pytest

        reg = MetricsRegistry()
        objs = [Objective(name="a", metric="m"),
                Objective(name="a", metric="m2")]
        with pytest.raises(ValueError):
            SLOEngine(reg, objectives=objs)


class TestStateMachine:
    """Hysteresis edges: flap across the threshold, window restart."""

    def _feed(self, h, eng, t, value):
        h.observe(value)
        return eng.evaluate(t)

    def test_escalates_immediately_and_pages_once(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg)
        eng.evaluate(0)                       # baseline
        for t in range(1, 5):
            self._feed(h, eng, t, 2.0)        # all bad
        assert eng.states()["lat"] == "page"
        assert eng.pages_by_objective() == {"lat": 1}
        # Still burning: no second page, no transition churn.
        for t in range(5, 8):
            self._feed(h, eng, t, 2.0)
        assert eng.pages_by_objective() == {"lat": 1}

    def test_flap_across_threshold_holds_state(self):
        """Alternating good/bad samples around a burn that keeps the
        page condition true must NOT flap: one page transition."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg)                    # budget 0.1, page at 2.0
        eng.evaluate(0)
        # 50% bad = burn 5.0 >= 2.0: alternating samples keep paging.
        for t in range(1, 12):
            self._feed(h, eng, t, 2.0 if t % 2 else 0.01)
        assert eng.states()["lat"] == "page"
        assert eng.pages_by_objective() == {"lat": 1}
        snap = eng.snapshot()["series"]["lat"]
        assert snap["transitions"] == 1

    def test_deescalation_needs_consecutive_quiet_evals(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg, clear_after=3)
        eng.evaluate(0)
        for t in range(1, 4):
            self._feed(h, eng, t, 2.0)
        assert eng.states()["lat"] == "page"
        # One quiet eval, then bad again: calm resets, still paged.
        self._feed(h, eng, 4, 0.01)
        self._feed(h, eng, 5, 0.01)
        self._feed(h, eng, 6, 2.0)            # burn back over page
        assert eng.states()["lat"] == "page"
        # Now a long quiet run: windows drain, clear_after=3 quiet
        # evals step the state down (page -> warn -> ok as the slow
        # windows dilute).
        for t in range(7, 40):
            self._feed(h, eng, t, 0.01)
        assert eng.states()["lat"] == "ok"
        assert eng.pages_by_objective() == {"lat": 1}

    def test_window_restart_no_data_deescalates(self):
        """A source that stops producing events entirely: burns go
        None, the state machine still walks back to ok."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg)
        eng.evaluate(0)
        for t in range(1, 4):
            self._feed(h, eng, t, 2.0)
        assert eng.states()["lat"] == "page"
        for t in range(4, 20):                # no observations at all
            eng.evaluate(t)
        assert eng.states()["lat"] == "ok"
        burns = eng.snapshot()["series"]["lat"]["burn"]
        assert all(b is None for b in burns.values())

    def test_fast_pair_must_both_burn(self):
        """One bad sample inside fast_short but diluted over fast_long
        must not page (the multi-window guard against blips)."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg, page_burn=4.0, warn_burn=10.0)
        eng.evaluate(0)
        for t in range(1, 4):
            self._feed(h, eng, t, 0.01)       # good history
        self._feed(h, eng, 4, 2.0)            # one blip
        # fast_short (2): 1 bad / 1 -> burn 10; fast_long (4): 1/4 ->
        # 2.5 < 4.0 -> NO page.
        assert eng.states()["lat"] == "ok"

    def test_value_objective_bounds(self):
        reg = MetricsRegistry()
        vals = {"v": 0.0}
        eng = SLOEngine(reg, objectives=[Objective(
            name="ratio", value_fn=lambda: vals["v"], min_value=0.5,
            slo=0.5, page_burn=1.5, warn_burn=1.0, windows=W,
            clear_after=2)])
        for t in range(1, 4):
            vals["v"] = 0.1                   # bad ticks
            eng.evaluate(t)
        assert eng.states()["ratio"] == "page"

    def test_gauge_group_by_series(self):
        reg = MetricsRegistry()
        g = reg.gauge("ratio", "t", labels=("tenant",))
        eng = SLOEngine(reg, objectives=[Objective(
            name="tenant-goodput", gauge="ratio", group_by="tenant",
            min_value=0.5, slo=0.5, page_burn=1.5, warn_burn=1.0,
            windows=W, clear_after=2)])
        g.set(0.9, tenant="acme")
        g.set(0.1, tenant="startup")
        for t in range(1, 4):
            eng.evaluate(t)
        states = eng.states()
        assert states["tenant-goodput[tenant=acme]"] == "ok"
        assert states["tenant-goodput[tenant=startup]"] == "page"
        assert eng.pages_by_objective() == {"tenant-goodput": 1}

    def test_interruption_delta_source_baselines_at_creation(self):
        class Acc:
            interruptions = {"preempt": 3}

        acc = Acc()
        fn = interruption_delta_source(acc)
        assert fn() == 0.0                    # pre-existing history clean
        acc.interruptions = {"preempt": 4}
        assert fn() == 1.0
        assert fn() == 0.0


class TestExemplars:
    def test_latest_wins_per_band_and_over_threshold(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        h.observe(2.0, exemplar="old")
        h.observe(3.0, exemplar="new")
        h.observe(0.1, exemplar="good")
        ex = h.exemplar_over(0.25)
        assert ex["trace_id"] == "new" and ex["value"] == 3.0
        # Under-threshold exemplar exists but is not "over".
        assert {e["trace_id"] for e in h.exemplars()} == {"new", "good"}

    def test_current_span_auto_capture(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        tr = Tracer()
        with tr.span("write") as s:
            h.observe(2.0)
        assert h.exemplar_over(0.25)["trace_id"] == s.trace_id
        # No span, no explicit exemplar: nothing captured.
        h2 = reg.histogram("lat2", "t", buckets=(0.25,))
        h2.observe(2.0)
        assert h2.exemplars() == []

    def test_labelset_cap_bounds_the_store(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", labels=("k",), buckets=(0.25,))
        for i in range(EXEMPLAR_LABELSET_CAP + 50):
            h.observe(2.0, exemplar=f"e{i}", k=str(i))
        # Counts are unbounded; the exemplar store is capped.
        assert h.count() == EXEMPLAR_LABELSET_CAP + 50
        assert len(h.exemplars()) <= EXEMPLAR_LABELSET_CAP

    def test_count_and_sum_aggregate_label_subsets(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", labels=("k",), buckets=(0.25,))
        h.observe(0.1, k="a")
        h.observe(0.2, k="b")
        assert h.count() == 2
        assert abs(h.sum() - 0.3) < 1e-9
        assert h.count(k="a") == 1
        pairs = h.cumulative()
        assert pairs[-1] == (float("inf"), 2.0)

    def test_grouped_alert_exemplar_scoped_to_its_group(self):
        """A grouped objective's alert must carry a trace from ITS
        label group — never a sibling group's blip."""
        reg = MetricsRegistry()
        h = reg.histogram("age", "t", labels=("priority",),
                          buckets=(0.25, 1.0))
        eng = SLOEngine(reg, objectives=[Objective(
            name="queue-age", metric="age", threshold_s=0.25,
            group_by="priority", slo=0.9, page_burn=2.0, warn_burn=1.0,
            windows=W, clear_after=2)])
        eng.evaluate(0)
        # priority=0 burns (and will page); priority=10 has ONE newer
        # over-threshold blip whose exemplar must NOT be borrowed.
        for t in range(1, 5):
            h.observe(2.0, exemplar=f"p0-{t}", priority="0")
            if t == 4:
                h.observe(3.0, exemplar="p10-blip", priority="10")
            eng.evaluate(t)
        series = eng.snapshot()["series"]
        paged = series["queue-age[priority=0]"]
        assert paged["state"] == "page"
        assert paged["exemplar"].startswith("p0-")

    def test_alert_carries_resolvable_exemplar(self, tmp_path):
        """The paged objective's exemplar is a trace id whose spans the
        tpuctl trace --id path resolves from the recorded jsonl."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        tr = Tracer()
        eng = _engine(reg)
        eng.evaluate(0)
        with tr.span("apiserver.update",
                     attrs={"kind": "TpuJob", "name": "train1",
                            "namespace": "ml"}) as s:
            h.observe(2.0)
        for t in range(1, 4):
            h.observe(2.0, exemplar=s.trace_id)
            eng.evaluate(t)
        snap = eng.snapshot()["series"]["lat"]
        assert snap["state"] == "page"
        assert snap["exemplar"] == s.trace_id
        # Resolve through the CLI: trace --id renders that trace.
        trace_file = tmp_path / "trace.jsonl"
        tr.export_jsonl(str(trace_file))
        from kubeflow_tpu.tools.tpuctl import main as tpuctl_main

        rc = tpuctl_main(["--state-dir", str(tmp_path), "trace",
                          "--id", snap["exemplar"]])
        assert rc == 0


class TestJournal:
    def _page(self, tmp_path, fname=ALERTS_JOURNAL, rotate=4 << 20):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        eng = _engine(reg, journal_path=str(tmp_path / fname),
                      rotate_bytes=rotate)
        eng.evaluate(0)
        for t in range(1, 5):
            h.observe(2.0)
            eng.evaluate(t)
        for t in range(5, 30):                # walk back down to ok
            h.observe(0.01)
            eng.evaluate(t)
        return eng

    def test_replay_byte_identity(self, tmp_path):
        eng = self._page(tmp_path)
        assert eng.transitions_total() >= 2   # up and back down
        fresh = SLOEngine(MetricsRegistry(),
                          objectives=soak_objectives(None))
        n = fresh.replay_from(str(tmp_path / ALERTS_JOURNAL))
        assert n == eng.transitions_total()
        assert fresh.fingerprint() == eng.fingerprint()
        assert fresh.states()["lat"] == "ok"

    def test_torn_tail_tolerated(self, tmp_path):
        eng = self._page(tmp_path)
        path = tmp_path / ALERTS_JOURNAL
        raw = path.read_bytes()
        # Crash mid-append: truncate inside the last record.
        path.write_bytes(raw[:-7])
        lines = [ln for ln in raw.decode().splitlines() if ln]
        fresh = SLOEngine(MetricsRegistry(),
                          objectives=soak_objectives(None))
        n = fresh.replay_from(str(path))
        assert n == len(lines) - 1            # the torn record dropped
        # The complete prefix applied; last full transition's state.
        prefix = [json.loads(ln) for ln in lines[:-1]]
        assert fresh.states()["lat"] == prefix[-1]["to"]

    def test_rotation_keeps_replay_identical(self, tmp_path):
        # Tiny rotate threshold: every transition rolls the journal.
        eng = self._page(tmp_path, rotate=64)
        assert os.path.exists(str(tmp_path / (ALERTS_JOURNAL + ".1")))
        fresh = SLOEngine(MetricsRegistry(),
                          objectives=soak_objectives(None))
        fresh.replay_from(str(tmp_path / ALERTS_JOURNAL))
        assert fresh.fingerprint() == eng.fingerprint()

    def test_rotated_current_generation_is_self_contained(self, tmp_path):
        """After rotation the CURRENT file opens with a state record —
        deleting the .1 generation must not change the replayed state
        (the discipline that makes repeated rollover safe)."""
        eng = self._page(tmp_path, rotate=64)
        os.remove(str(tmp_path / (ALERTS_JOURNAL + ".1")))
        fresh = SLOEngine(MetricsRegistry(),
                          objectives=soak_objectives(None))
        fresh.replay_from(str(tmp_path / ALERTS_JOURNAL))
        assert fresh.fingerprint() == eng.fingerprint()

    def test_own_journal_replay_compacts(self, tmp_path):
        eng = self._page(tmp_path, rotate=64)
        fp = eng.fingerprint()
        eng.close()
        path = str(tmp_path / ALERTS_JOURNAL)
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))  # noqa: F841
        eng2 = _engine(reg, journal_path=path)
        eng2.replay_from(path)
        assert eng2.fingerprint() == fp
        # Compacted: one state record, no stale .1 generation left.
        recs = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert len(recs) == 1 and recs[0]["op"] == "state"
        assert not os.path.exists(path + ".1")


class TestGoodputJournalRotation:
    def test_goodput_rotation_replays_both_generations(self, tmp_path):
        from kubeflow_tpu.obs.goodput import GoodputAccountant

        path = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity(
            {"v5e-16": 2}, journal_path=path, fsync=False,
            rotate_bytes=256)
        for t in range(1, 60):
            acc.tick(t)
        fp = acc.fingerprint()
        assert os.path.exists(path + ".1")    # rotation happened
        twin = GoodputAccountant.from_capacity({"v5e-16": 2})
        twin.replay_from(path)
        assert twin.fingerprint() == fp
        assert twin.conservation()["exact"]

    def test_goodput_rotated_head_is_state_record(self, tmp_path):
        from kubeflow_tpu.obs.goodput import GoodputAccountant

        path = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity(
            {"v5e-16": 2}, journal_path=path, fsync=False,
            rotate_bytes=256)
        for t in range(1, 60):
            acc.tick(t)
        first = json.loads(open(path).readline())
        assert first["op"] == "state"
        # Current generation alone already replays to the full state.
        os.remove(path + ".1")
        twin = GoodputAccountant.from_capacity({"v5e-16": 2})
        twin.replay_from(path)
        assert twin.fingerprint() == acc.fingerprint()


class TestFlightRecorder:
    def test_ring_overflow_keeps_newest_in_order(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("event", {"i": i})
        entries = list(rec._ring)
        assert len(entries) == 8
        assert [e["data"]["i"] for e in entries] == list(range(12, 20))
        # seq stays globally monotone (causal order survives eviction).
        assert [e["seq"] for e in entries] == list(range(13, 21))

    def test_dump_and_load_roundtrip(self, tmp_path):
        rec = FlightRecorder(capacity=8, shard="sh00")
        rec.record("event", {"i": 1}, t=10.0)
        rec.record("alert", {"objective": "lat"}, t=11.0,
                   trace_id="tid")
        path = rec.dump(str(tmp_path), reason="test")
        recs = FlightRecorder.load(path)
        assert recs[0]["kind"] == "flight"
        assert recs[0]["reason"] == "test"
        kinds = [r["kind"] for r in recs[1:]]
        assert kinds == ["event", "alert"]
        assert recs[2]["trace_id"] == "tid"

    def test_guards_latch_one_dump(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        state = {"ok": True}
        guards = {"conservation": lambda: state["ok"]}
        assert rec.check_guards(guards, str(tmp_path)) == []
        state["ok"] = False
        assert rec.check_guards(guards, str(tmp_path)) == \
            ["conservation"]
        # Latched: still broken, but no second dump.
        assert rec.check_guards(guards, str(tmp_path)) == []
        assert len(rec.dumps) == 1

    def test_metric_deltas_record_movement_only(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_test_total", "t")
        rec = FlightRecorder(registry=reg)
        assert rec.record_metric_deltas() == 0   # baseline
        c.inc(3)
        assert rec.record_metric_deltas() == 1
        assert rec.record_metric_deltas() == 0   # no movement
        entry = [e for e in rec._ring if e["kind"] == "metrics"][-1]
        assert entry["data"]["deltas"]["kftpu_test_total"] == 3

    def test_cross_shard_stitch_ordering_and_dedup(self, tmp_path):
        a = FlightRecorder(capacity=8, shard="sh00")
        b = FlightRecorder(capacity=8, shard="sh01")
        a.record("event", {"i": "a1"}, t=1.0)
        b.record("event", {"i": "b1"}, t=2.0)
        a.record("event", {"i": "a2"}, t=3.0)
        # Same-shard causal order beats a skewed wall clock: a3 records
        # with an EARLIER t than a2 but a later seq.
        a.record("event", {"i": "a3"}, t=3.0)
        da1 = a.dump(str(tmp_path / "shard-00"))
        db = b.dump(str(tmp_path / "shard-01"))
        # Overlapping second dump of shard a: entries must dedup.
        da2 = a.dump(str(tmp_path / "shard-00"))
        merged = stitch([da1, db, da2])
        seq = [(r.get("shard"), r["data"]["i"]) for r in merged
               if r["kind"] == "event"]
        assert seq == [("sh00", "a1"), ("sh01", "b1"), ("sh00", "a2"),
                       ("sh00", "a3")]
        paths = flight_paths(str(tmp_path))
        assert set(paths) == {da1, da2, db}

    def test_engine_pages_dump_the_ring(self, tmp_path):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "t", buckets=(0.25, 1.0))
        rec = FlightRecorder(capacity=16)
        eng = _engine(reg, recorder=rec, dump_dir=str(tmp_path))
        eng.evaluate(0)
        for t in range(1, 5):
            h.observe(2.0)
            eng.evaluate(t)
        assert eng.states()["lat"] == "page"
        assert len(rec.dumps) == 1
        recs = FlightRecorder.load(rec.dumps[0])
        assert recs[0]["reason"] == "alert-page:lat"
        assert any(r["kind"] == "alert" for r in recs)


class TestSoakIntegration:
    """The slo-smoke substrate at tier-1 scale: the seeded soak carries
    an slo section; clean soak quiet, fault soak pages (the full CI
    gates run in slo-smoke)."""

    def test_clean_soak_fires_nothing(self):
        from kubeflow_tpu.chaos import run_soak

        rep = run_soak(num_jobs=2, seed=7, preempt_every=0,
                       fault_rounds=5, max_rounds=30)
        assert rep.converged
        assert rep.slo["transitions"] == 0
        assert rep.flight_dumps == []

    def test_fault_soak_pages_and_dumps(self, tmp_path):
        from kubeflow_tpu.chaos import run_soak

        rep = run_soak(num_jobs=4, seed=20260803, preempt_every=3,
                       fault_rounds=9, max_rounds=40,
                       state_dir=str(tmp_path))
        assert rep.converged
        pages = rep.slo["pages"]
        assert pages.get("goodput-interruptions", 0) == 1
        assert rep.flight_dumps
        assert os.path.exists(str(tmp_path / ALERTS_JOURNAL))
        # Journal replays byte-identically into a fresh engine.
        fresh = SLOEngine(MetricsRegistry(),
                          objectives=soak_objectives(None))
        fresh.replay_from(str(tmp_path / ALERTS_JOURNAL))
        assert fresh.fingerprint() == rep.slo["fingerprint"]


class TestStormIntegration:
    def test_storm_reports_starvation_slo(self):
        from kubeflow_tpu.scheduler.benchmark import run_schedule_storm

        rep = run_schedule_storm(num_jobs=12, policy="priority", seed=1,
                                 fleet_capacity={"v5e-16": 4},
                                 pool_size=4, max_ticks=120,
                                 starvation_bound_ticks=5)
        assert "series" in rep.slo
        keys = set(rep.slo["series"])
        # One series per priority class that ever queued.
        assert any(k.startswith("queue-age[priority=") for k in keys)


class TestPlatformIntegration:
    def test_platform_wires_engine_and_journal(self, tmp_path):
        import yaml

        from kubeflow_tpu.tools.tpuctl import main as tpuctl_main

        state = tmp_path / "st"
        cfg = {
            "kind": "PlatformConfig",
            "metadata": {"name": "kubeflow-tpu"},
            "spec": {"components": [
                {"name": "tpujob-controller", "enabled": True,
                 "params": {"capacity": "v5e-16=2"}},
                {"name": "fake-kubelet", "enabled": True},
            ]},
        }
        f = tmp_path / "platform.yaml"
        f.write_text(yaml.safe_dump(cfg))
        assert tpuctl_main(["--state-dir", str(state), "apply",
                            "-f", str(f)]) == 0
        # The scoreboard renders (quiet fleet: rc 0, nothing paging).
        assert tpuctl_main(["--state-dir", str(state), "slo"]) == 0
        assert tpuctl_main(["--state-dir", str(state), "slo",
                            "-o", "json"]) == 0
        # flight dump + show round-trip.
        assert tpuctl_main(["--state-dir", str(state), "flight",
                            "dump"]) == 0
        assert flight_paths(str(state))
        assert tpuctl_main(["--state-dir", str(state), "flight",
                            "show"]) == 0
        assert tpuctl_main(["--state-dir", str(state), "flight",
                            "ls"]) == 0

    def test_restored_interruption_history_reads_clean(self, tmp_path):
        """Platform.load restores the goodput ledger AFTER the SLO
        engine's delta source baselined — rebaseline_sources() must
        keep persisted interruption history from reading as one fresh
        burst on every tpuctl invocation."""
        import yaml

        from kubeflow_tpu.controlplane.platform import Platform

        state = str(tmp_path / "st")
        cfg = {
            "kind": "PlatformConfig",
            "metadata": {"name": "kubeflow-tpu"},
            "spec": {"components": [
                {"name": "tpujob-controller", "enabled": True,
                 "params": {"capacity": "v5e-16=2"}},
            ]},
        }
        p = Platform.load(state)
        from kubeflow_tpu.controlplane.api import object_from_dict

        p.apply_config(object_from_dict(cfg))
        # Fake persisted interruption history on the live accountant
        # and save WITHOUT evaluating (the history predates this
        # engine): the fresh process's first evaluations must read
        # delta 0, not 3.
        p.goodput.interruptions["preempt"] = 3
        p.save(state)
        p2 = Platform.load(state)
        assert p2.goodput.interruptions["preempt"] == 3
        for _ in range(4):
            p2.reconcile()
        series = p2.slo.snapshot()["series"].get(
            "goodput-interruptions", {})
        assert series.get("state", "ok") == "ok"
        assert p2.slo.transitions_total() == 0
        _ = yaml  # silence unused-import lint in minimal envs

    def test_platform_reconcile_evaluates(self):
        from kubeflow_tpu.controlplane.api.meta import ObjectMeta
        from kubeflow_tpu.controlplane.api.types import PlatformConfig
        from kubeflow_tpu.controlplane.platform import Platform

        p = Platform()
        p.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        p.reconcile()
        assert p.slo is not None and p.flight is not None
        snap = p.slo.snapshot()
        assert "admission-latency" in snap["objectives"]
        assert "queue-age" in snap["objectives"]
        assert not snap["paging"]
