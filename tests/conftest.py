"""Test harness configuration.

All tests run on a virtual 8-device CPU backend
(``xla_force_host_platform_device_count``) so multi-chip sharding is
exercised without TPU hardware — the analogue of the reference's envtest
(in-memory etcd+apiserver, reference: components/profile-controller/
controllers/suite_test.go:50-72): a fake backend with real semantics.
"""

import os

# Must be set before jax is imported anywhere. Force CPU even if the shell
# has a TPU platform configured — tests never touch real hardware.
os.environ["JAX_PLATFORMS"] = "cpu"
# Keep native-loader build artifacts + corpus-validation markers out of the
# developer's ~/.cache. Per-uid path: a world-shared fixed /tmp dir would
# collide across users on shared hosts; _cache_dir() additionally enforces
# 0700 + ownership before anything is dlopened from it. getuid (not
# getpass.getuser) so unmapped-UID containers don't KeyError at import.
import tempfile  # noqa: E402

_uid = os.getuid() if hasattr(os, "getuid") else "win"
os.environ.setdefault(
    "KFTPU_NATIVE_CACHE",
    os.path.join(tempfile.gettempdir(), f"kftpu-test-native-cache-{_uid}"),
)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Some environments register a TPU PJRT plugin via sitecustomize and make it
# the default regardless of JAX_PLATFORMS; the config update wins either way.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second integration benches excluded from tier-1 "
        "(-m 'not slow'); CI smoke stages cover their invariants",
    )


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules.

    The full suite compiles many hundreds of XLA CPU programs in one
    process; with all of them kept alive, the CPU backend segfaulted
    (reproducibly, ~78% through the suite, inside
    backend_compile_and_load on a fresh compile — not an OOM: 120 GB
    free) while the same tests pass in module-sized runs. Bounding the
    live-executable count per module avoids whatever compiler-state
    limit that crash lives in, and caps suite RSS. Costs only
    cross-module cache reuse, which module-scoped fixtures don't rely
    on."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(autouse=True)
def _no_leaked_threads(request):
    """Leaked-thread/executor detector (ISSUE 16): every test that
    starts a manager, service thread or worker pool must close it.

    A non-daemon thread (ThreadPoolExecutor workers are non-daemon, so
    this covers leaked executors) that appeared during the test and is
    still alive after a short grace join fails the test that leaked it
    — at the leak site, instead of as a suite-teardown hang or a
    cross-test lock-order artifact in the locktrace soaks."""
    import threading

    before = {t.ident for t in threading.enumerate()}
    yield
    candidates = [
        t for t in threading.enumerate()
        if t.ident not in before and t.is_alive() and not t.daemon
    ]
    # Grace period: close() paths that were just invoked may still be
    # joining their workers.
    for t in candidates:
        t.join(timeout=2.0)
    leaked = [t for t in candidates if t.is_alive()]
    assert not leaked, (
        "test leaked non-daemon threads: "
        + ", ".join(sorted(t.name for t in leaked))
    )


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
