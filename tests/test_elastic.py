"""Elastic TpuJobs (ISSUE 11): resize the gang instead of restarting it.

Covers the resize lifecycle verb across every layer: spec validation,
shrink-on-preemption (the zero-downtime branch of the preemption path),
grow-on-freed-capacity (ElasticController + fair-placement rule),
shrink-to-fit placement, the scheduler's partial release/grow, defrag's
shrink-vs-migrate policy, WAL-replay adoption of a RESIZED assignment,
the goodput ledger's recompute-only resize attribution, the checkpoint
catalog's torn-save hardening, the capacity-oscillation soak, and the
tpuctl surfaces."""

import json
import os

import pytest

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    ComponentConfig,
    ElasticSpec,
    MeshAxesSpec,
    PlatformConfig,
    PlatformConfigSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.elastic import ElasticController
from kubeflow_tpu.scheduler import (
    DefragController,
    Fleet,
    GangScheduler,
    parse_assignment,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer


def make_elastic_job(name, *, ns="ml", n=2, min_slices=1, max_slices=None,
                     prio=0, ckpt_dir="", policy="restart"):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(
            slice_type="v5e-16", num_slices=n,
            mesh=MeshAxesSpec(dp=-1), priority=prio,
            backoff_seconds=0.0, preemption_policy=policy,
            checkpoint_dir=ckpt_dir,
            elastic=ElasticSpec(min_slices=min_slices,
                                max_slices=max_slices or n),
        ),
    )


class Rig:
    """api + manager + TpuJobController(scheduler) [+ ElasticController]
    + FakeKubelet — the test_scheduler rig grown an elastic half."""

    def __init__(self, fleet_cap, *, pool_size=4, elastic_ctl=False,
                 outcome=None, warmup_ticks=0):
        self.registry = MetricsRegistry()
        self.tracer = Tracer()
        self.api = InMemoryApiServer(registry=self.registry,
                                     tracer=self.tracer)
        self.mgr = ControllerManager(self.api, self.registry,
                                     tracer=self.tracer)
        self.fleet = Fleet.from_capacity(fleet_cap, pool_size=pool_size)
        self.scheduler = GangScheduler(self.fleet, policy="priority",
                                       registry=self.registry,
                                       tracer=self.tracer)
        self.ctl = TpuJobController(self.api, self.registry,
                                    hbm_check=False,
                                    scheduler=self.scheduler,
                                    requeue_pending_s=3600.0)
        self.mgr.register(self.ctl)
        self.elastic = None
        if elastic_ctl:
            self.elastic = ElasticController(
                self.api, self.registry, scheduler=self.scheduler,
                tracer=self.tracer, interval_s=0.0)
            self.mgr.register(self.elastic)
        self.kubelet = FakeKubelet(self.api, self.registry,
                                   outcome=outcome or (lambda name: None),
                                   warmup_ticks=warmup_ticks)
        self.mgr.register(self.kubelet)

    def drain(self):
        self.mgr.kick_timers(2 * 3600.0)
        self.mgr.run_until_idle(max_iterations=100000)
        self.kubelet.tick()
        self.mgr.run_until_idle(max_iterations=100000)

    def job(self, name, ns="ml"):
        return self.api.get("TpuJob", name, ns)

    def close(self):
        self.mgr.close()


# --------------------------------------------------------------------------
# Spec validation
# --------------------------------------------------------------------------


class TestElasticSpecValidation:
    @pytest.mark.parametrize("n,mn,mx", [
        (2, 0, 2),      # min below 1
        (2, 3, 4),      # min above num_slices
        (4, 1, 3),      # num_slices above max
    ])
    def test_bad_bounds_fail_admission(self, n, mn, mx):
        rig = Rig({"v5e-16": 8})
        rig.api.create(make_elastic_job("bad", n=n, min_slices=mn,
                                        max_slices=mx))
        rig.drain()
        job = rig.job("bad")
        assert job.status.phase == "Failed"
        reasons = {c.reason for c in job.status.conditions}
        assert "InvalidElasticSpec" in reasons
        rig.close()

    def test_elastic_requires_restart_policy(self):
        rig = Rig({"v5e-16": 8})
        rig.api.create(make_elastic_job("pinned", policy="fail"))
        rig.drain()
        assert rig.job("pinned").status.phase == "Failed"
        rig.close()


# --------------------------------------------------------------------------
# Shrink on preemption (the resize branch)
# --------------------------------------------------------------------------


class TestShrink:
    def test_partial_preemption_shrinks_not_restarts(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4})
        rig.api.create(make_elastic_job("a", n=2))
        rig.drain()
        job = rig.job("a")
        before = parse_assignment(job.status.slice_assignment)
        assert len(before) == 2 and job.status.phase == "Running"
        # Preempt slice group 1: group index maps to assignment index.
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=1) > 0
        rig.drain()
        job = rig.job("a")
        # A resize, never a restart: budget and preemption count
        # untouched, world republished at width 1 on the SURVIVOR.
        assert job.status.resizes == 1
        assert job.status.preemptions == 0 and job.status.restarts == 0
        assert job.status.current_slices == 1
        after = parse_assignment(job.status.slice_assignment)
        assert after == [before[0]]      # survivor kept byte-identically
        assert job.status.phase == "Running"
        # The lost unit is free again; the survivor still held.
        assert rig.fleet.unit(before[1]).free
        assert rig.fleet.assignment(job.metadata.uid) == after
        # Zero-downtime: no backoff hold — the gang is already whole.
        assert sorted(p.status.phase for p in
                      rig.api.list("Pod", namespace="ml")) == ["Running"] * 4
        events = [e.reason for e in rig.api.list("Event", namespace="ml")]
        assert "ElasticShrink" in events
        assert rig.registry.get("kftpu_tpujob_gang_resizes_total").value(
            direction="shrink") == 1
        rig.close()

    def test_losing_group_zero_renumbers_survivors(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4})
        rig.api.create(make_elastic_job("a", n=2))
        rig.drain()
        job = rig.job("a")
        before = parse_assignment(job.status.slice_assignment)
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=0) > 0
        rig.drain()
        job = rig.job("a")
        assert job.status.resizes == 1
        assert parse_assignment(job.status.slice_assignment) == [before[1]]
        # The renumbered world is 4 pods, worker-0..3, all Running.
        pods = rig.api.list("Pod", namespace="ml")
        assert sorted(p.metadata.name for p in pods) == [
            f"a-worker-{i}" for i in range(4)]
        assert all(p.status.phase == "Running" for p in pods)
        rig.close()

    def test_below_min_slices_falls_back_to_restart(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4})
        rig.api.create(make_elastic_job("a", n=2, min_slices=2,
                                        max_slices=4))
        rig.drain()
        job = rig.job("a")
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=1) > 0
        rig.drain()
        rig.drain()
        job = rig.job("a")
        # Survivors (1) < min_slices (2): the ordinary preemption path.
        assert job.status.resizes == 0
        assert job.status.preemptions == 1 and job.status.restarts == 0
        rig.close()

    def test_genuine_crash_still_consumes_restart_budget(self):
        rig = Rig({"v5e-16": 4})
        rig.api.create(make_elastic_job("a", n=2))
        rig.drain()
        # A worker crash WITHOUT the preemption marker.
        pod = self_pod = rig.api.get("Pod", "a-worker-0", "ml")
        pod.status.phase = "Failed"
        pod.status.message = "OOM"
        rig.api.update_status(pod)
        rig.drain()
        rig.drain()
        job = rig.job("a")
        assert job.status.restarts == 1 and job.status.resizes == 0
        rig.close()

    def test_shrink_without_scheduler_capacity_mode(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        registry = MetricsRegistry()
        api = InMemoryApiServer(registry=registry)
        mgr = ControllerManager(api, registry)
        mgr.register(TpuJobController(api, registry, hbm_check=False,
                                      capacity={"v5e-16": 2}))
        kubelet = FakeKubelet(api, registry, outcome=lambda name: None)
        mgr.register(kubelet)
        api.create(make_elastic_job("a", n=2))
        for _ in range(3):
            mgr.run_until_idle(max_iterations=100000,
                               include_timers_within=120.0)
            kubelet.tick()
        mgr.run_until_idle(max_iterations=100000,
                           include_timers_within=120.0)
        job = api.get("TpuJob", "a", "ml")
        assert job.status.phase == "Running"
        assert SlicePreemptor(api, seed=0).preempt(job, slice_id=0) > 0
        for _ in range(3):
            mgr.run_until_idle(max_iterations=100000,
                               include_timers_within=120.0)
            kubelet.tick()
        mgr.run_until_idle(max_iterations=100000,
                           include_timers_within=120.0)
        job = api.get("TpuJob", "a", "ml")
        assert job.status.resizes == 1 and job.status.preemptions == 0
        assert job.status.current_slices == 1
        assert job.status.slice_assignment == "v5e-16x1"
        mgr.close()


# --------------------------------------------------------------------------
# Grow (ElasticController + fairness)
# --------------------------------------------------------------------------


class TestGrow:
    def test_shrunk_gang_grows_back_to_max(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4}, elastic_ctl=True)
        rig.api.create(make_elastic_job("a", n=2))
        rig.drain()
        job = rig.job("a")
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=1) > 0
        rig.drain()
        rig.drain()
        job = rig.job("a")
        # Shrink (resize 1) then grow back (resize 2): no queue blocks.
        assert job.status.resizes == 2
        assert job.status.current_slices == 2
        assert len(parse_assignment(job.status.slice_assignment)) == 2
        assert job.status.phase == "Running"
        assert job.status.restarts == 0 and job.status.preemptions == 0
        events = [e.reason for e in rig.api.list("Event", namespace="ml")]
        assert "ElasticGrow" in events
        assert rig.registry.get("kftpu_elastic_grows_total").value() == 1
        rig.close()

    def test_growth_never_outruns_equal_priority_queue(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4}, elastic_ctl=True)
        rig.api.create(make_elastic_job("a", n=2, prio=0))
        rig.api.create(make_elastic_job("b", n=2, prio=0))
        rig.drain()
        # Fleet full (2+2). A third same-priority gang queues.
        rig.api.create(make_elastic_job("c", n=2, prio=0))
        rig.drain()
        assert rig.job("c").status.phase == "Pending"
        job = rig.job("a")
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=1) > 0
        rig.drain()
        rig.drain()
        # The freed unit belongs to the QUEUE's claim, not the grower's
        # — "a" stays shrunk while "c" waits (c needs 2, only 1 free, so
        # c still queues; growth must STILL not take the unit).
        job = rig.job("a")
        assert job.status.resizes == 1
        assert job.status.current_slices == 1
        rig.close()

    def test_growth_passes_strictly_lower_priority_queue(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 4}, elastic_ctl=True)
        rig.api.create(make_elastic_job("hi", n=2, prio=10))
        rig.api.create(make_elastic_job("mid", n=2, prio=5))
        rig.drain()
        rig.api.create(make_elastic_job("batch", n=2, prio=0))
        rig.drain()
        assert rig.job("batch").status.phase == "Pending"
        job = rig.job("hi")
        assert SlicePreemptor(rig.api, seed=0).preempt(job, slice_id=1) > 0
        rig.drain()
        rig.drain()
        # The priority-10 grower may pass the priority-0 queue —
        # consistent with the eviction order.
        job = rig.job("hi")
        assert job.status.resizes == 2
        assert job.status.current_slices == 2
        rig.close()

    def test_shrink_to_fit_initial_placement(self):
        rig = Rig({"v5e-16": 4}, elastic_ctl=True)
        rig.api.create(make_elastic_job("wide", n=4, max_slices=4))
        rig.drain()
        assert rig.job("wide").status.current_slices == 4
        done = set()
        rig2 = Rig({"v5e-16": 4},
                   outcome=lambda name: "Succeeded"
                   if name.rsplit("-worker-", 1)[0] in done else None)
        rig2.api.create(make_elastic_job("filler", n=3, min_slices=3))
        rig2.drain()
        # Only 1 unit free: an elastic x4 gang places AT width 1 instead
        # of queueing (shrink-to-fit; no preemption at reduced widths).
        rig2.api.create(make_elastic_job("flex", n=4, max_slices=4))
        rig2.drain()
        flex = rig2.job("flex")
        assert flex.status.phase == "Running"
        assert flex.status.current_slices == 1
        assert len(parse_assignment(flex.status.slice_assignment)) == 1
        rig.close()
        rig2.close()


# --------------------------------------------------------------------------
# Scheduler partial ops
# --------------------------------------------------------------------------


class TestFleetPartialOps:
    def test_release_units_partial_and_full(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        units = [u.uid for u in fleet.free("v5e-16")[:3]]
        fleet.allocate("j", units)
        assert fleet.release_units("j", [units[1]]) == [units[1]]
        assert fleet.assignment("j") == [units[0], units[2]]
        assert fleet.unit(units[1]).free
        # Releasing the rest degrades to a full release.
        assert sorted(fleet.release_units("j", [units[0], units[2]])) \
            == sorted([units[0], units[2]])
        assert fleet.assignment("j") is None

    def test_extend_appends_and_rejects_taken(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        free = [u.uid for u in fleet.free("v5e-16")]
        fleet.allocate("a", free[:1])
        fleet.allocate("b", free[1:2])
        fleet.extend("a", free[2:3])
        assert fleet.assignment("a") == [free[0], free[2]]
        with pytest.raises(ValueError):
            fleet.extend("a", free[1:2])      # held by b
        with pytest.raises(ValueError):
            fleet.extend("ghost", free[3:4])  # nothing to extend


# --------------------------------------------------------------------------
# Defrag: shrink beats migrate
# --------------------------------------------------------------------------


class TestDefragShrink:
    def test_elastic_gang_shrunk_not_migrated(self):
        done = set()
        rig = Rig({"v5e-16": 8}, pool_size=4,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        defrag = DefragController(
            rig.api, rig.registry, scheduler=rig.scheduler,
            tracer=rig.tracer, threshold=0.4, interval_s=0.0)
        defrag.reader = rig.api
        # One elastic x2 gang + x1 fillers; finish a checkerboard so
        # free units are scattered holes above the threshold.
        rig.api.create(make_elastic_job("el", n=2))
        for i in range(6):
            rig.api.create(TpuJob(
                metadata=ObjectMeta(name=f"f{i}", namespace="ml"),
                spec=TpuJobSpec(slice_type="v5e-16", num_slices=1,
                                mesh=MeshAxesSpec(dp=-1),
                                backoff_seconds=0.0),
            ))
        rig.drain()
        by_unit = {}
        for i in range(6):
            job = rig.job(f"f{i}")
            units = rig.fleet.assignment(job.metadata.uid)
            by_unit[units[0]] = f"f{i}"
        for pool in rig.fleet.pools:
            for u in pool.units:
                if u.coord in ((0, 0), (1, 1)) and u.uid in by_unit:
                    done.add(by_unit[u.uid])
        rig.drain()
        frag = rig.fleet.fragmentation("v5e-16")
        assert frag > 0.4
        moved = defrag.sweep()
        assert moved == 1
        # The cheap move won: a shrink, through the same eviction seam.
        assert rig.scheduler.defrag_log[-1]["reason"] == "shrink"
        assert rig.registry.get(
            "kftpu_scheduler_defrag_shrinks_total").value() == 1
        rig.drain()
        el = rig.job("el")
        assert el.status.resizes == 1
        assert el.status.preemptions == 0
        assert el.status.current_slices == 1
        events = [e.reason for e in rig.api.list("Event", namespace="ml")]
        assert "DefragShrink" in events and "DefragMigration" not in events
        assert rig.fleet.fragmentation("v5e-16") < frag
        rig.close()


class TestResizeRaces:
    def test_fresh_preemption_during_resizing_is_classified(self):
        """An eviction racing the Resizing republish must not be
        swallowed by the idempotent re-entry: the doomed ledger tells
        the resize's own stale pods from a fresh event."""
        from kubeflow_tpu.scheduler import preempt_slice_group

        rig = Rig({"v5e-16": 4})
        rig.api.create(make_elastic_job("a", n=3, max_slices=3))
        rig.drain()
        job = rig.job("a")
        # First shrink: take the LAST group so survivors keep indices.
        preempt_slice_group(rig.api, job, "a-2")
        rig.mgr.run_until_idle(max_iterations=1000)
        job = rig.job("a")
        assert job.status.resizes == 1
        # Fresh preemption of a SURVIVOR group while the resize is
        # still republishing (phase may be Resizing mid-drain): it must
        # become a SECOND resize, not vanish.
        preempt_slice_group(rig.api, rig.job("a"), "a-0")
        rig.drain()
        job = rig.job("a")
        assert job.status.resizes == 2
        assert job.status.current_slices == 1
        assert job.status.preemptions == 0 and job.status.restarts == 0
        assert job.status.resize_doomed == []
        assert job.status.phase == "Running"
        rig.close()

    def test_defrag_shrink_is_not_undone_by_growth(self):
        """The defrag<->grow coordination: a defrag shrink caps the
        gang's growth until a simulated regrow stays under the
        threshold — no shrink/grow thrash, no stuck in-flight marker."""
        done = set()
        rig = Rig({"v5e-16": 8}, pool_size=4, elastic_ctl=True,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        defrag = DefragController(
            rig.api, rig.registry, scheduler=rig.scheduler,
            tracer=rig.tracer, threshold=0.4, interval_s=0.0)
        defrag.reader = rig.api
        rig.api.create(make_elastic_job("el", n=2))
        for i in range(6):
            rig.api.create(TpuJob(
                metadata=ObjectMeta(name=f"f{i}", namespace="ml"),
                spec=TpuJobSpec(slice_type="v5e-16", num_slices=1,
                                mesh=MeshAxesSpec(dp=-1),
                                backoff_seconds=0.0),
            ))
        rig.drain()
        by_unit = {}
        for i in range(6):
            units = rig.fleet.assignment(rig.job(f"f{i}").metadata.uid)
            by_unit[units[0]] = f"f{i}"
        for pool in rig.fleet.pools:
            for u in pool.units:
                if u.coord in ((0, 0), (1, 1)) and u.uid in by_unit:
                    done.add(by_unit[u.uid])
        rig.drain()
        assert rig.fleet.fragmentation("v5e-16") > 0.4
        assert defrag.sweep() == 1
        assert rig.scheduler.defrag_log[-1]["reason"] == "shrink"
        rig.drain()
        rig.drain()
        el = rig.job("el")
        # The growth cap held: still shrunk, exactly ONE resize — the
        # ElasticController did not undo the heal.
        assert el.status.resizes == 1
        assert el.status.current_slices == 1
        uid = el.metadata.uid
        assert rig.scheduler.growth_cap(uid) == 1
        # A second sweep settles the shrink's in-flight marker (the
        # shrunk width landed — no deadlock) and never re-shrinks the
        # capped gang; it MAY legitimately migrate someone else.
        shrinks_before = rig.registry.get(
            "kftpu_scheduler_defrag_shrinks_total").value()
        defrag.sweep()
        assert uid not in defrag._migrating
        assert rig.registry.get(
            "kftpu_scheduler_defrag_shrinks_total").value() \
            == shrinks_before
        # Pressure clears (everything else finishes) -> the cap lifts
        # and the gang grows back to spec.
        for i in range(6):
            done.add(f"f{i}")
        rig.drain()
        defrag.sweep()
        assert rig.scheduler.growth_cap(uid) is None
        # Event-driven growth rides on TpuJob churn; the quiesced test
        # world nudges the sweep directly (a storm never needs to).
        rig.elastic.sweep()
        rig.drain()
        el = rig.job("el")
        assert el.status.current_slices == 2
        assert el.status.resizes == 2
        rig.close()


# --------------------------------------------------------------------------
# WAL-replay adoption of a RESIZED assignment (satellite 3)
# --------------------------------------------------------------------------


class TestResizedAssignmentAcrossRestart:
    def test_shrink_then_grow_round_trips_wal_replay(self, tmp_path):
        from kubeflow_tpu.chaos import SlicePreemptor
        from kubeflow_tpu.controlplane.platform import Platform

        state = str(tmp_path / "state")
        cfg = PlatformConfig(
            metadata=ObjectMeta(name="kf"),
            spec=PlatformConfigSpec(components=[
                ComponentConfig(name="tpujob-controller",
                                params={"fleet": "v5e-16=4",
                                        "poolSize": "4",
                                        "elasticIntervalSeconds": "0",
                                        "defrag": "false"}),
                ComponentConfig(name="fake-kubelet"),
            ]),
        )
        platform = Platform()
        platform.attach_wal(state)
        platform.apply_config(cfg)
        platform.api.create(make_elastic_job("a", n=2))
        platform.reconcile()
        job = platform.api.get("TpuJob", "a", "ml")
        full = parse_assignment(job.status.slice_assignment)
        assert len(full) == 2 and job.status.phase == "Running"

        # Shrink: preempt group 1, then let the elastic controller grow
        # back — TWO resizes whose final assignment may differ from the
        # original unit set.
        SlicePreemptor(platform.api, seed=1).preempt(job, slice_id=1)
        platform.reconcile()
        platform.reconcile()
        job = platform.api.get("TpuJob", "a", "ml")
        assert job.status.resizes >= 1
        resized = parse_assignment(job.status.slice_assignment)
        assert resized is not None
        platform.save(state)

        # A fresh process loads the WAL-backed state: adopt() must
        # re-pin the RESIZED assignment byte-identically — never the
        # original placement, never a migration.
        reloaded = Platform.load(state)
        reloaded.reconcile()
        job2 = reloaded.api.get("TpuJob", "a", "ml")
        assert parse_assignment(job2.status.slice_assignment) == resized
        assert reloaded.scheduler.assignment_of(job2.metadata.uid) \
            == resized
        assert job2.status.current_slices == job.status.current_slices
        assert job2.status.resizes == job.status.resizes


# --------------------------------------------------------------------------
# Goodput: resize attributes as recompute only (+ counterfactual)
# --------------------------------------------------------------------------


class TestGoodputResize:
    def _mk(self, **kw):
        from kubeflow_tpu.obs.goodput import GoodputAccountant

        return GoodputAccountant.from_capacity({"v5e-16": 2}, **kw)

    def _job(self, *, resizes=0, current=0, phase="Running"):
        j = make_elastic_job("a", ns="obs", n=2)
        j.metadata.uid = "uid-a"
        j.status.phase = phase
        j.status.resizes = resizes
        j.status.current_slices = current
        return j

    def test_resize_moves_recompute_without_window(self):
        from kubeflow_tpu.controlplane.runtime.apiserver import WatchEvent

        acc = self._mk()
        acc.apply_event(WatchEvent("ADDED", self._job()))
        acc.tick(3)      # 3 ticks x 2 units productive, all unsaved
        acc.apply_event(WatchEvent(
            "MODIFIED", self._job(resizes=1, current=1)))
        acc.tick(4)
        snap = acc.snapshot()
        assert snap["interruptions"]["resize"] == 1
        # Recompute moved (6 unsaved ticks), NO interruption window: the
        # tick after the resize is productive again (1 unit now).
        assert snap["categories_ticks"]["restart_rollback"] == 6
        cons = acc.conservation()
        assert cons["exact"]
        acc.close()

    def test_degraded_productive_counts_the_counterfactual(self):
        from kubeflow_tpu.controlplane.runtime.apiserver import WatchEvent

        acc = self._mk()
        acc.apply_event(WatchEvent("ADDED", self._job()))
        acc.tick(2)
        # Shrunk to 1 of 2 desired: productive ticks now count as
        # degraded (the restart twin would have queued instead).
        acc.apply_event(WatchEvent(
            "MODIFIED", self._job(resizes=1, current=1)))
        acc.tick(5)
        snap = acc.snapshot()
        assert snap["degraded_productive_ticks"] == 3
        job = snap["jobs"]["obs/a"]
        assert job["resizes"] == 1
        assert job["degraded_productive_ticks"] == 3
        assert job["counterfactual_saved_s"] == 3.0
        assert acc.conservation()["exact"]
        acc.close()

    def test_resize_journal_replays_byte_identically(self, tmp_path):
        from kubeflow_tpu.controlplane.runtime.apiserver import WatchEvent
        from kubeflow_tpu.obs.goodput import GoodputAccountant

        path = str(tmp_path / "goodput.jsonl")
        acc = self._mk(journal_path=path, fsync=False)
        acc.apply_event(WatchEvent("ADDED", self._job()))
        acc.tick(3)
        acc.apply_event(WatchEvent(
            "MODIFIED", self._job(resizes=1, current=1)))
        acc.tick(5)
        fp = acc.fingerprint()
        acc.close()
        twin = GoodputAccountant.from_capacity({"v5e-16": 2})
        twin.replay_from(path)
        assert twin.fingerprint() == fp
        assert twin.conservation()["exact"]
        twin.close()


# --------------------------------------------------------------------------
# Checkpoint catalog: torn-save hardening (satellite 1)
# --------------------------------------------------------------------------


class TestTornSaveCatalog:
    def _dir_with_steps(self, tmp_path, steps, torn=()):
        d = tmp_path / "ckpt"
        for s in steps:
            (d / str(s)).mkdir(parents=True)
        for s in torn:
            # The torn-save fixture: a SIGKILL mid-commit left the orbax
            # in-progress marker INSIDE the renamed step directory.
            (d / str(s) / ".orbax-checkpoint-tmp-1718").mkdir(
                parents=True, exist_ok=True)
        return str(d)

    def test_torn_step_never_reported_complete(self, tmp_path):
        from kubeflow_tpu.controlplane.ckpt_catalog import (
            latest_complete_step,
        )

        d = self._dir_with_steps(tmp_path, [1, 2, 3], torn=[3])
        assert latest_complete_step(d) == 2
        d2 = self._dir_with_steps(tmp_path / "only-torn", [5], torn=[5])
        assert latest_complete_step(d2) is None

    def test_resolve_checkpoint_skips_torn_saves(self, tmp_path):
        from kubeflow_tpu.controlplane.ckpt_catalog import (
            list_checkpoints,
            resolve_checkpoint,
        )

        d = self._dir_with_steps(tmp_path, [1, 4], torn=[4])
        api = InMemoryApiServer()
        api.create(TpuJob(
            metadata=ObjectMeta(name="train", namespace="ml"),
            spec=TpuJobSpec(checkpoint_dir=d),
        ))
        entry = resolve_checkpoint(api, "ml", "train")
        assert entry is not None and entry["latestStep"] == 1
        assert list_checkpoints(api, "ml")[0]["latestStep"] == 1


# --------------------------------------------------------------------------
# The capacity-oscillation soak + elastic storm (satellites 4/5)
# --------------------------------------------------------------------------


class TestElasticSoak:
    def test_oscillation_soak_gates(self):
        from kubeflow_tpu.chaos import run_elastic_soak

        rep = run_elastic_soak(seed=7)
        assert rep.converged and rep.all_succeeded
        assert rep.bursts > 0 and rep.shrinks > 0 and rep.grows > 0
        assert rep.restarts_consumed == 0
        assert rep.preemption_restarts == 0
        assert rep.min_width_observed == 1
        assert rep.checkpoint_steps_monotone
        assert all(s > 0 for s in rep.final_steps.values())
        assert rep.goodput_conserved
        assert rep.goodput["interruptions"]["resize"] == rep.resizes

    def test_ci_elastic_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_elastic_smoke

        run_elastic_smoke()


class TestElasticStorm:
    def test_elastic_storm_converges_with_resizes_deterministically(self):
        from kubeflow_tpu.scheduler.benchmark import (
            check_storm_gates,
            run_schedule_storm,
        )

        common = dict(
            num_jobs=20, policy="priority", seed=3,
            fleet_capacity={"v5e-16": 8}, pool_size=4,
            chaos_at_tick=4, chaos_preempts=2, chaos_every=4,
            ckpt_every_ticks=2, elastic=True, width_scaled_work=True,
        )
        rep = run_schedule_storm(**common)
        check_storm_gates(rep)
        assert rep.converged and rep.succeeded == rep.submitted
        assert rep.resizes > 0 and rep.shrinks > 0
        assert rep.goodput["conserved"]
        assert rep.goodput["interruptions"]["resize"] == rep.resizes
        # Same seed, same storm: resize decisions replay byte-equal.
        again = run_schedule_storm(**common)
        assert again.summary() == rep.summary()

    def test_restart_only_storm_stays_byte_identical_to_pr8(self):
        """elastic=False + defaults must keep the PR-8/PR-10 storm
        contract: no resize machinery fires at all."""
        from kubeflow_tpu.scheduler.benchmark import run_schedule_storm

        rep = run_schedule_storm(num_jobs=12, policy="priority", seed=2,
                                 fleet_capacity={"v5e-16": 8},
                                 pool_size=4)
        assert rep.resizes == 0 and rep.shrinks == 0 and rep.grows == 0
        assert rep.goodput["interruptions"]["resize"] == 0
        assert rep.goodput["degraded_productive_ticks"] == 0


# --------------------------------------------------------------------------
# tpuctl surfaces
# --------------------------------------------------------------------------


class TestTpuctlJobs:
    def test_jobs_table_and_json_show_elastic_state(self, tmp_path,
                                                    capsys):
        from kubeflow_tpu.tools import tpuctl

        state = str(tmp_path / "state")
        cfg = {
            "kind": "PlatformConfig",
            "metadata": {"name": "kf"},
            "spec": {"components": [
                {"name": "tpujob-controller",
                 "params": {"fleet": "v5e-16=4", "poolSize": "4"}},
                {"name": "fake-kubelet"},
            ]},
        }
        job = {
            "kind": "TpuJob",
            "metadata": {"name": "train", "namespace": "ml"},
            "spec": {"sliceType": "v5e-16", "numSlices": 2,
                     "mesh": {"dp": -1},
                     "elastic": {"minSlices": 1, "maxSlices": 4}},
        }
        import yaml

        cfg_file = tmp_path / "cfg.yaml"
        cfg_file.write_text(yaml.safe_dump(cfg))
        job_file = tmp_path / "job.yaml"
        job_file.write_text(yaml.safe_dump(job))
        assert tpuctl.main(["--state-dir", state, "apply",
                            "-f", str(cfg_file)]) == 0
        assert tpuctl.main(["--state-dir", state, "apply",
                            "-f", str(job_file)]) == 0
        capsys.readouterr()
        assert tpuctl.main(["--state-dir", state, "jobs",
                            "-o", "json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        row = next(r for r in rows if r["name"] == "train")
        assert row["elastic"] == "1..4"
        assert row["slices"] == "2/2"
        assert row["resizes"] == 0
        assert tpuctl.main(["--state-dir", state, "jobs"]) == 0
        out = capsys.readouterr().out
        assert "ELASTIC" in out and "1..4" in out and "SAVED_S" in out
