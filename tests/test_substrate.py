"""SubstrateProvider seam (VERDICT r4 Missing #2): the Apply(PLATFORM)
half of kfctl — provision TPU slice/node pools before the k8s apply,
finalizer-guarded, delete reclaims everything with a leak check.

Mirrors the IAM plugin conformance pattern (tests/test_iam_plugins.py):
the provider contract is tested generically so a GCP/AWS implementation
drops into the same suite. Reference:
bootstrap/cmd/bootstrap/app/kfctlServer.go:219-296 (DM deployment before
k8s apply), testing/kfctl/kfctl_delete_test.py:44-71 (delete-leak check).
"""

import json
import time
import urllib.request

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    NodePoolSpec,
    PlatformConfig,
    PlatformConfigSpec,
    SlicePoolSpec,
    SubstrateSpec,
)
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.controlplane.substrate import (
    PROVIDERS,
    SUBSTRATE_FINALIZER,
    FakeSubstrateProvider,
    SubstrateError,
    SubstrateLeakError,
    get_provider,
    provision,
)


@pytest.fixture(autouse=True)
def fresh_fake():
    fake = PROVIDERS["fake"]
    fake.reset()
    yield fake
    fake.reset()


def _spec(**kw):
    kw.setdefault("provider", "fake")
    kw.setdefault("slice_pools", [
        SlicePoolSpec(name="train-pool", slice_type="v5e-16", num_slices=2),
        SlicePoolSpec(name="serve-pool", slice_type="v5e-4", num_slices=1),
    ])
    kw.setdefault("node_pools", [
        NodePoolSpec(name="cp-pool", machine_type="n2-standard-8", count=3),
    ])
    return SubstrateSpec(**kw)


# Parametrized like the IAM conformance suite: every registered provider
# must satisfy the same lifecycle contract. The gcloud impl runs against
# a recording executor (the production seam is subprocess.run).
@pytest.fixture(params=["fake", "gcloud"])
def provider(request, fresh_fake):
    if request.param == "gcloud":
        from kubeflow_tpu.controlplane.substrate import GcloudTpuProvider

        p = GcloudTpuProvider(runner=lambda argv: "", project="proj",
                              zone="us-east5-a")
        return p
    return get_provider(request.param)


class TestProviderConformance:
    def test_ensure_creates_all_pools(self, provider):
        names = provider.ensure_pools("dep-a", _spec())
        assert names == ["cp-pool", "serve-pool", "train-pool"]
        recs = provider.list_resources("dep-a")
        kinds = {r["name"]: r["kind"] for r in recs}
        assert kinds == {"train-pool": "SlicePool", "serve-pool": "SlicePool",
                        "cp-pool": "NodePool"}

    def test_ensure_is_idempotent(self, provider):
        provider.ensure_pools("dep-a", _spec())
        before = provider.list_resources("dep-a")
        provider.ensure_pools("dep-a", _spec())
        assert provider.list_resources("dep-a") == before

    def test_ensure_updates_changed_pool(self, provider):
        provider.ensure_pools("dep-a", _spec())
        changed = _spec(slice_pools=[
            SlicePoolSpec(name="train-pool", slice_type="v5e-16",
                          num_slices=4),
            SlicePoolSpec(name="serve-pool", slice_type="v5e-4",
                          num_slices=1),
        ])
        provider.ensure_pools("dep-a", changed)
        rec = {r["name"]: r for r in provider.list_resources("dep-a")}
        assert rec["train-pool"]["numSlices"] == 4

    def test_ensure_prunes_pools_dropped_from_spec(self, provider):
        provider.ensure_pools("dep-a", _spec())
        provider.ensure_pools("dep-a", _spec(
            slice_pools=[SlicePoolSpec(name="train-pool",
                                       slice_type="v5e-16", num_slices=2)],
            node_pools=[]))
        names = [r["name"] for r in provider.list_resources("dep-a")]
        assert names == ["train-pool"]

    def test_deployments_are_isolated(self, provider):
        provider.ensure_pools("dep-a", _spec())
        provider.ensure_pools("dep-b", _spec(
            slice_pools=[SlicePoolSpec(name="other",
                                       slice_type="v5e-8", num_slices=1)],
            node_pools=[]))
        provider.deprovision("dep-b")
        assert provider.list_resources("dep-b") == []
        assert len(provider.list_resources("dep-a")) == 3

    def test_deprovision_leaves_nothing(self, provider):
        provider.ensure_pools("dep-a", _spec())
        deleted = provider.deprovision("dep-a")
        assert deleted == ["cp-pool", "serve-pool", "train-pool"]
        assert provider.list_resources("dep-a") == []

    def test_unknown_slice_type_fails_loudly(self, provider):
        with pytest.raises(SubstrateError, match="slice_type"):
            provider.ensure_pools("dep-a", _spec(slice_pools=[
                SlicePoolSpec(name="x", slice_type="h100-pod")]))

    def test_nameless_pool_fails(self, provider):
        with pytest.raises(SubstrateError, match="name"):
            provider.ensure_pools("dep-a", _spec(slice_pools=[
                SlicePoolSpec(name="", slice_type="v5e-16")]))

    def test_duplicate_pool_name_across_kinds_fails(self, provider):
        with pytest.raises(SubstrateError, match="both"):
            provider.ensure_pools("dep-a", _spec(
                slice_pools=[SlicePoolSpec(name="p", slice_type="v5e-16")],
                node_pools=[NodePoolSpec(name="p")]))

    def test_unknown_provider_fails(self):
        with pytest.raises(SubstrateError, match="unknown substrate"):
            provision("dep-a", SubstrateSpec(provider="gcp-dm"))


class TestPlatformIntegration:
    def _config(self, name="kf-sub"):
        return PlatformConfig(
            metadata=ObjectMeta(name=name),
            spec=PlatformConfigSpec(substrate=_spec()))

    def test_apply_provisions_before_components_and_adds_finalizer(
            self, fresh_fake):
        pf = Platform()
        pf.apply_config(self._config())
        assert len(fresh_fake.list_resources("kf-sub")) == 3
        cfg = pf.api.get("PlatformConfig", "kf-sub")
        assert SUBSTRATE_FINALIZER in cfg.metadata.finalizers

    def test_second_apply_is_idempotent(self, fresh_fake):
        pf = Platform()
        pf.apply_config(self._config())
        before = fresh_fake.list_resources("kf-sub")
        pf.apply_config(self._config())
        assert fresh_fake.list_resources("kf-sub") == before
        cfg = pf.api.get("PlatformConfig", "kf-sub")
        assert cfg.metadata.finalizers.count(SUBSTRATE_FINALIZER) == 1

    def test_delete_config_reclaims_everything(self, fresh_fake):
        pf = Platform()
        pf.apply_config(self._config())
        deleted = pf.delete_config("kf-sub")
        assert deleted == ["cp-pool", "serve-pool", "train-pool"]
        assert fresh_fake.list_resources("kf-sub") == []
        assert pf.api.try_get("PlatformConfig", "kf-sub") is None

    def test_leak_raises_and_keeps_finalizer(self, fresh_fake,
                                             monkeypatch):
        pf = Platform()
        pf.apply_config(self._config())

        # A buggy provider that forgets one pool on deprovision.
        real = fresh_fake.deprovision

        def leaky(deployment):
            real(deployment)
            fresh_fake._pools[(deployment, "train-pool")] = {
                "kind": "SlicePool", "name": "train-pool",
                "sliceType": "v5e-16", "numSlices": 2}
            return []

        monkeypatch.setattr(fresh_fake, "deprovision", leaky)
        with pytest.raises(SubstrateLeakError, match="leaked"):
            pf.delete_config("kf-sub")
        # The config (and its finalizer) survive: nothing was silently
        # dropped while cloud resources are still alive.
        cfg = pf.api.get("PlatformConfig", "kf-sub")
        assert SUBSTRATE_FINALIZER in cfg.metadata.finalizers

    def test_no_substrate_section_is_a_noop(self, fresh_fake):
        pf = Platform()
        pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="plain")))
        assert fresh_fake.list_resources("plain") == []
        cfg = pf.api.get("PlatformConfig", "plain")
        assert SUBSTRATE_FINALIZER not in cfg.metadata.finalizers
        pf.delete_config("plain")
        assert pf.api.try_get("PlatformConfig", "plain") is None


class TestBootstrapE2E:
    """Provision-then-apply through the deployment REST plane — the
    kfctl-server flow with the substrate half attached."""

    @pytest.fixture()
    def server(self, tmp_path, fresh_fake):
        from kubeflow_tpu.controlplane.bootstrap import DeploymentServer

        srv = DeploymentServer(state_dir=str(tmp_path)).start()
        yield srv
        srv.stop()

    def _post(self, srv, path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return json.load(urllib.request.urlopen(req))

    def _wait_ready(self, srv, name, tries=100):
        for _ in range(tries):
            out = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/kfctl/apps/v1beta1/get/{name}"))
            if out["phase"] in ("Ready", "Failed"):
                return out
            time.sleep(0.05)
        raise AssertionError("deployment never settled")

    def test_create_provisions_then_applies_delete_reclaims(
            self, server, fresh_fake):
        self._post(server, "/kfctl/apps/v1beta1/create", {
            "name": "subdep",
            "spec": {
                "substrate": {
                    "provider": "fake",
                    "slicePools": [{"name": "train-pool",
                                    "sliceType": "v5e-16",
                                    "numSlices": 2}],
                    "nodePools": [{"name": "cp-pool", "count": 1}],
                },
            },
        })
        out = self._wait_ready(server, "subdep")
        assert out["phase"] == "Ready", out
        assert len(fresh_fake.list_resources("subdep")) == 2

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/kfctl/apps/v1beta1/delete/"
            "subdep", method="DELETE")
        out = json.load(urllib.request.urlopen(req))
        assert out["substratePools"] == ["cp-pool", "train-pool"]
        assert fresh_fake.list_resources("subdep") == []

    def test_substrate_inspection_endpoint(self, server, fresh_fake):
        self._post(server, "/kfctl/apps/v1beta1/create", {
            "name": "viewdep",
            "spec": {"substrate": {"provider": "fake",
                                   "slicePools": [{"name": "tp",
                                                   "sliceType": "v5e-16",
                                                   "numSlices": 2}]}},
        })
        self._wait_ready(server, "viewdep")
        out = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/kfctl/apps/v1beta1/"
            "substrate/viewdep"))
        assert out["provider"] == "fake"
        assert [r["name"] for r in out["resources"]] == ["tp"]
        assert out["resources"][0]["numSlices"] == 2

    def test_substrate_endpoint_shows_pools_of_failed_apply(
            self, server, fresh_fake):
        """A failed apply may have provisioned BEFORE its config reached
        the store — the inspection endpoint must still surface the pools
        (they are exactly the leak the operator needs to see)."""
        self._post(server, "/kfctl/apps/v1beta1/create", {
            "name": "faildep",
            "spec": {
                "substrate": {"provider": "fake",
                              "slicePools": [{"name": "tp",
                                              "sliceType": "v5e-16"}]},
                "components": [{"name": "bogus-component"}],
            },
        })
        out = self._wait_ready(server, "faildep")
        assert out["phase"] == "Failed"
        assert len(fresh_fake.list_resources("faildep")) == 1
        view = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/kfctl/apps/v1beta1/"
            "substrate/faildep"))
        assert view["provider"] == "fake"
        assert [r["name"] for r in view["resources"]] == ["tp"]
        # and delete still reclaims them (the fallback feeds delete too)
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/kfctl/apps/v1beta1/delete/"
            "faildep", method="DELETE")
        out = json.load(urllib.request.urlopen(req))
        assert out["substratePools"] == ["tp"]
        assert fresh_fake.list_resources("faildep") == []

    def test_bad_substrate_fails_the_deployment_loudly(self, server,
                                                       fresh_fake):
        self._post(server, "/kfctl/apps/v1beta1/create", {
            "name": "badsub",
            "spec": {"substrate": {"provider": "fake",
                                   "slicePools": [{"name": "x",
                                                   "sliceType": "gpu-a100"}]}},
        })
        out = self._wait_ready(server, "badsub")
        assert out["phase"] == "Failed"
        assert "slice_type" in out["error"]
        assert fresh_fake.list_resources("badsub") == []


class TestReviewRegressions:
    """Round-5 review findings, pinned."""

    def test_spec_dropping_substrate_reclaims_pools(self, fresh_fake):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kf-sub"),
            spec=PlatformConfigSpec(substrate=_spec())))
        assert len(fresh_fake.list_resources("kf-sub")) == 3
        # Re-apply WITHOUT the substrate section: the old pools must be
        # reclaimed (leak-checked), not silently orphaned.
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kf-sub"),
            spec=PlatformConfigSpec()))
        assert fresh_fake.list_resources("kf-sub") == []
        cfg = pf.api.get("PlatformConfig", "kf-sub")
        assert SUBSTRATE_FINALIZER not in cfg.metadata.finalizers

    def test_finalizer_persists_on_stored_config_after_reapply(
            self, fresh_fake):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kf-sub"),
            spec=PlatformConfigSpec()))
        # Substrate introduced on a RE-apply: the finalizer must land on
        # the STORED config, where a direct api.delete would consult it.
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kf-sub"),
            spec=PlatformConfigSpec(substrate=_spec())))
        stored = pf.api.get("PlatformConfig", "kf-sub")
        assert SUBSTRATE_FINALIZER in stored.metadata.finalizers

    def test_duplicate_slice_pool_names_fail(self, fresh_fake):
        with pytest.raises(SubstrateError, match="duplicate"):
            fresh_fake.ensure_pools("d", _spec(slice_pools=[
                SlicePoolSpec(name="train", slice_type="v5e-16"),
                SlicePoolSpec(name="train", slice_type="v5e-4"),
            ]))

    def test_invalid_new_provider_never_destroys_old_pools(
            self, fresh_fake):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kf-sub"),
            spec=PlatformConfigSpec(substrate=_spec())))
        assert len(fresh_fake.list_resources("kf-sub")) == 3
        # Switching to an unknown provider must fail BEFORE touching the
        # healthy pools (dry validation precedes deprovision).
        with pytest.raises(SubstrateError, match="unknown substrate"):
            pf.apply_config(PlatformConfig(
                metadata=ObjectMeta(name="kf-sub"),
                spec=PlatformConfigSpec(substrate=SubstrateSpec(
                    provider="gcp-dm"))))
        assert len(fresh_fake.list_resources("kf-sub")) == 3
        # Same for a REGISTERED but unwired provider (the default gcloud
        # registry entry has no executor): validate_spec refuses, so the
        # fake's pools survive.
        with pytest.raises(SubstrateError, match="no executor"):
            pf.apply_config(PlatformConfig(
                metadata=ObjectMeta(name="kf-sub"),
                spec=PlatformConfigSpec(substrate=_spec(
                    provider="gcloud"))))
        assert len(fresh_fake.list_resources("kf-sub")) == 3


class TestGcloudProviderCommands:
    """The gcloud impl's value is the command surface: assert the exact
    CLI lines the seam would execute in production."""

    def _provider(self):
        from kubeflow_tpu.controlplane.substrate import GcloudTpuProvider

        calls = []

        def runner(argv):
            calls.append(list(argv))
            return ""

        return GcloudTpuProvider(runner=runner, project="proj",
                                 zone="us-east5-a"), calls

    def test_create_commands(self):
        p, calls = self._provider()
        p.ensure_pools("dep-a", _spec())
        joined = [" ".join(c) for c in calls]
        # One tpu-vm create PER SLICE (the CLI creates one VM per call),
        # with the runtime --version the real gcloud requires.
        for vm in ("dep-a-train-pool-0", "dep-a-train-pool-1"):
            assert any(
                c.startswith(f"gcloud compute tpus tpu-vm create {vm}")
                and "--accelerator-type v5e-16" in c
                and "--version tpu-ubuntu2204-base" in c
                and "--labels kftpu-deployment=dep-a" in c
                and "--project proj" in c and "--zone us-east5-a" in c
                for c in joined), joined
        # Single-slice pools use the bare pool name.
        assert any(
            c.startswith("gcloud compute tpus tpu-vm create dep-a-serve-pool ")
            for c in joined), joined
        assert any(
            c.startswith("gcloud container node-pools create dep-a-cp-pool")
            and "--cluster kubeflow-tpu" in c
            and "--machine-type n2-standard-8" in c and "--num-nodes 3" in c
            for c in joined), joined

    def test_idempotent_ensure_issues_no_commands(self):
        p, calls = self._provider()
        p.ensure_pools("dep-a", _spec())
        n = len(calls)
        p.ensure_pools("dep-a", _spec())
        assert len(calls) == n  # nothing re-created

    def test_spec_change_recreates_pool(self):
        p, calls = self._provider()
        p.ensure_pools("dep-a", _spec())
        calls.clear()
        p.ensure_pools("dep-a", _spec(slice_pools=[
            SlicePoolSpec(name="train-pool", slice_type="v5e-16",
                          num_slices=4),
            SlicePoolSpec(name="serve-pool", slice_type="v5e-4",
                          num_slices=1)], node_pools=[]))
        joined = [" ".join(c) for c in calls]
        assert any("tpu-vm delete dep-a-train-pool-0" in c for c in joined)
        # re-created at the new width: 4 per-slice creates
        for i in range(4):
            assert any(f"tpu-vm create dep-a-train-pool-{i} " in c
                       for c in joined), joined
        # serve-pool untouched, cp-pool (dropped from spec) deleted
        assert not any("serve-pool" in c and "create" in c for c in joined)
        assert any("node-pools delete dep-a-cp-pool" in c
                   and "--cluster kubeflow-tpu" in c for c in joined)

    def test_deprovision_deletes_everything(self):
        p, calls = self._provider()
        p.ensure_pools("dep-a", _spec())
        calls.clear()
        p.deprovision("dep-a")
        joined = [" ".join(c) for c in calls]
        # train-pool has 2 slices -> 2 deletes; serve-pool 1; cp-pool 1.
        assert sum("delete" in c for c in joined) == 4
        assert p.list_resources("dep-a") == []

    def test_unwired_executor_fails_loudly(self):
        from kubeflow_tpu.controlplane.substrate import GcloudTpuProvider

        p = GcloudTpuProvider()
        with pytest.raises(SubstrateError, match="no executor"):
            p.ensure_pools("dep-a", _spec())
        # validate_spec must ALSO refuse: the platform dry-validates a
        # new provider before tearing the old pools down, and an unwired
        # provider could never provision.
        with pytest.raises(SubstrateError, match="no executor"):
            p.validate_spec(_spec())
