"""Gatekeeper auth proxy: the reference contract (AuthServer.go:62-160) —
unauthenticated requests bounce to login, password/cookie flows mint the
trusted header, and the upstream never sees a client-forged identity."""

import base64
import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta, Profile, ProfileSpec
from kubeflow_tpu.controlplane.api.types import PlatformConfig
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.webapps.gatekeeper import (
    AuthProxy,
    COOKIE_NAME,
    Gatekeeper,
    SessionSigner,
)

HDR = "x-goog-authenticated-user-email"


def _req(port, method, path, headers=None, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    for k, v in (headers or {}).items():
        req.add_header(k, v)

    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **kw):
            return None

    opener = urllib.request.build_opener(NoRedirect)
    try:
        with opener.open(req) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, (json.loads(raw) if raw else {}), dict(e.headers)


class TestGatekeeperCheck:
    def test_password_and_cookie(self):
        gk = Gatekeeper({"alice": "s3cret"}, user_domain="corp.com")
        assert gk.auth_password("alice", "s3cret") == "alice@corp.com"
        assert gk.auth_password("alice", "wrong") is None
        assert gk.auth_password("mallory", "s3cret") is None
        token = gk.signer.issue("alice@corp.com")
        assert gk.check({"cookie": f"{COOKIE_NAME}={token}"}) == "alice@corp.com"
        basic = base64.b64encode(b"alice:s3cret").decode()
        assert gk.check({"authorization": f"Basic {basic}"}) == "alice@corp.com"
        assert gk.check({}) is None

    def test_session_expiry_and_tamper(self):
        signer = SessionSigner(ttl_seconds=10)
        tok = signer.issue("u@x", now=1000.0)
        assert signer.validate(tok, now=1005.0) == "u@x"
        assert signer.validate(tok, now=1011.0) is None
        # Tampered token (flip a byte) must fail.
        raw = bytearray(base64.urlsafe_b64decode(tok))
        raw[0] ^= 1
        bad = base64.urlsafe_b64encode(bytes(raw)).decode()
        assert signer.validate(bad, now=1005.0) is None
        # Token signed with a different secret must fail.
        other = SessionSigner(ttl_seconds=10).issue("u@x", now=1000.0)
        assert signer.validate(other, now=1005.0) is None


@pytest.fixture()
def stack():
    """gatekeeper -> JWA, with a profile for alice."""
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                          spec=ProfileSpec(owner="alice@corp.com")))
    pf.reconcile()
    jwa_srv = pf.jwa.serve()
    gk = Gatekeeper({"alice": "s3cret"}, user_domain="corp.com")
    proxy = AuthProxy(gk, jwa_srv.port).start()
    yield pf, proxy.port
    proxy.stop()
    jwa_srv.stop()


class TestAuthProxyFlow:
    def test_unauthenticated_redirects_to_login(self, stack):
        _, port = stack
        code, _, headers = _req(port, "GET", "/api/namespaces")
        assert code == 302
        assert headers.get("Location") == "/kflogin"

    def test_login_then_cookie_reaches_upstream(self, stack):
        pf, port = stack
        code, out, headers = _req(port, "POST", "/kflogin",
                                  body={"username": "alice",
                                        "password": "s3cret"})
        assert code == 205  # ResetContent, as the reference login flow
        cookie = headers["Set-Cookie"].split(";")[0]
        code, out, _ = _req(port, "POST", "/api/namespaces/alice/notebooks",
                            headers={"Cookie": cookie},
                            body={"name": "nb1"})
        assert code == 200, out
        pf.reconcile()
        nb = pf.api.get("Notebook", "nb1", "alice")
        assert nb.metadata.annotations["owner"] == "alice@corp.com"

    def test_bad_password_401(self, stack):
        _, port = stack
        code, _, _ = _req(port, "POST", "/kflogin",
                          body={"username": "alice", "password": "nope"})
        assert code == 401

    def test_basic_auth_api_flow(self, stack):
        _, port = stack
        basic = base64.b64encode(b"alice:s3cret").decode()
        code, out, _ = _req(port, "GET", "/api/namespaces/alice/notebooks",
                            headers={"Authorization": f"Basic {basic}"})
        assert code == 200

    def test_forged_identity_header_is_stripped(self, stack):
        """A client cannot smuggle the trusted header past the proxy."""
        _, port = stack
        basic = base64.b64encode(b"alice:s3cret").decode()
        code, out, _ = _req(
            port, "GET", "/api/namespaces/admin-ns/notebooks",
            headers={"Authorization": f"Basic {basic}",
                     HDR: "root@corp.com"},
        )
        # alice's creds, not the forged admin header: denied in admin-ns.
        assert code == 403

    def test_whoami(self, stack):
        _, port = stack
        code, out, _ = _req(port, "GET", "/whoami")
        assert code == 200 and out["user"] == ""
        basic = base64.b64encode(b"alice:s3cret").decode()
        code, out, _ = _req(port, "GET", "/whoami",
                            headers={"Authorization": f"Basic {basic}"})
        assert out["user"] == "alice@corp.com"


class TestLoginPage:
    def test_browser_gets_html_form_api_gets_json(self):
        import json as _json
        import urllib.request

        from kubeflow_tpu.webapps.gatekeeper import AuthProxy, Gatekeeper
        from kubeflow_tpu.webapps.router import JsonHttpServer, Router

        upstream = JsonHttpServer(Router()).start()
        gk = Gatekeeper(users={"alice": "s3cret"})
        proxy = AuthProxy(gk, upstream.port)
        proxy.start()
        try:
            base = f"http://127.0.0.1:{proxy.port}"
            req = urllib.request.Request(
                f"{base}/kflogin", headers={"Accept": "text/html"}
            )
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"].startswith("text/html")
                page = r.read().decode()
            assert 'id="f"' in page and "password" in page

            with urllib.request.urlopen(f"{base}/kflogin") as r:
                assert r.headers["Content-Type"] == "application/json"
                assert "login" in _json.loads(r.read())
        finally:
            proxy.stop()
            upstream.stop()


class TestGatekeeperMain:
    def test_sidecar_entrypoint_full_flow(self, tmp_path):
        """Users file -> proxy -> login -> authenticated upstream request
        with the injected identity header (the manifest sidecar's exact
        wiring)."""
        import json as _json
        import threading
        import time
        import urllib.request

        from kubeflow_tpu.webapps.gatekeeper import main as gk_main
        from kubeflow_tpu.webapps.router import JsonHttpServer, Router

        upstream_router = Router()
        upstream_router.get("/api/whoami-up",
                            lambda q: {"caller": q.caller})
        upstream = JsonHttpServer(upstream_router).start()

        users = tmp_path / "users"
        users.write_text("# comment\nalice:s3cret\n")
        secret = tmp_path / "session.key"
        secret.write_bytes(b"0" * 32)
        import socket

        s = socket.socket(); s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]; s.close()
        t = threading.Thread(target=gk_main, args=([
            "--users-file", str(users),
            "--session-secret-file", str(secret),
            "--upstream-port", str(upstream.port),
            "--host", "127.0.0.1", "--port", str(port),
            "--user-domain", "corp.example",
        ],), daemon=True)
        t.start()
        base = f"http://127.0.0.1:{port}"
        # Poll for readiness instead of a fixed sleep (loaded CI hosts).
        deadline = time.time() + 15
        while True:
            try:
                urllib.request.urlopen(f"{base}/kflogin", timeout=1)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)

        # Unauthenticated: bounced to login, not forwarded.
        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(NoRedirect)
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            opener.open(f"{base}/api/whoami-up")
        assert e.value.code == 302

        # Basic-auth flow reaches the upstream with identity injected.
        import base64

        req = urllib.request.Request(
            f"{base}/api/whoami-up",
            headers={"Authorization": "Basic "
                     + base64.b64encode(b"alice:s3cret").decode(),
                     # Forged client copy must be stripped.
                     "x-goog-authenticated-user-email": "evil@corp"},
        )
        out = _json.load(urllib.request.urlopen(req))
        assert out["caller"] == "alice@corp.example"
        upstream.stop()

    def test_placeholder_password_refused(self, tmp_path):
        from kubeflow_tpu.webapps.gatekeeper import main as gk_main

        users = tmp_path / "users"
        users.write_text("admin:changeme\n")
        with pytest.raises(SystemExit, match="placeholder"):
            gk_main(["--users-file", str(users), "--upstream-port", "1"])
