import json
import urllib.request

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta, Profile, ProfileSpec
from kubeflow_tpu.controlplane.controllers import ProfileController
from kubeflow_tpu.controlplane.kfam import AccessManagement, KfamHttpServer
from kubeflow_tpu.controlplane.kfam.service import Binding, KfamError
from kubeflow_tpu.controlplane.runtime import ControllerManager, InMemoryApiServer
from kubeflow_tpu.utils.monitoring import MetricsRegistry

ADMIN = "root@corp.com"
ALICE = "alice@corp.com"
BOB = "bob@corp.com"


@pytest.fixture()
def world():
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    mgr = ControllerManager(api)
    mgr.register(ProfileController(api, reg))
    am = AccessManagement(api, reg)
    # Bootstrap a cluster admin.
    api.create(Profile(
        metadata=ObjectMeta(name="admin-ns", labels={"cluster-admin": "true"}),
        spec=ProfileSpec(owner=ADMIN),
    ))
    mgr.run_until_idle()
    return api, mgr, am


class TestAccessManagement:
    def test_self_service_profile(self, world):
        api, mgr, am = world
        am.create_profile(ALICE, "alice-ns")
        mgr.run_until_idle()
        assert api.get("Namespace", "alice-ns").metadata.annotations["owner"] == ALICE
        # Owner is implicit admin binding.
        bindings = am.list_bindings(user=ALICE)
        assert any(b.namespace == "alice-ns" and b.role == "admin"
                   for b in bindings)

    def test_cannot_create_profile_for_other_unless_admin(self, world):
        _, _, am = world
        with pytest.raises(KfamError) as e:
            am.create_profile(ALICE, "bob-ns", owner=BOB)
        assert e.value.status == 403
        am.create_profile(ADMIN, "bob-ns", owner=BOB)  # admin may

    def test_contributor_flow(self, world):
        api, mgr, am = world
        am.create_profile(ALICE, "alice-ns")
        mgr.run_until_idle()
        # Bob can't self-invite.
        with pytest.raises(KfamError):
            am.create_binding(BOB, Binding(user=BOB, namespace="alice-ns",
                                           role="edit"))
        # Alice grants Bob edit.
        am.create_binding(ALICE, Binding(user=BOB, namespace="alice-ns",
                                         role="edit"))
        assert am.sar.can(BOB, "create", "alice-ns")
        assert not am.sar.can(BOB, "admin", "alice-ns")
        ap = api.get("AuthorizationPolicy", "ns-owner-access-istio", "alice-ns")
        assert BOB in ap.principals
        # Revoke.
        am.delete_binding(ALICE, Binding(user=BOB, namespace="alice-ns",
                                         role="edit"))
        assert not am.sar.can(BOB, "get", "alice-ns")
        ap = api.get("AuthorizationPolicy", "ns-owner-access-istio", "alice-ns")
        assert BOB not in ap.principals
        assert ALICE in ap.principals  # owner never removed

    def test_duplicate_binding_conflicts(self, world):
        _, mgr, am = world
        am.create_profile(ALICE, "alice-ns")
        mgr.run_until_idle()
        b = Binding(user=BOB, namespace="alice-ns", role="view")
        am.create_binding(ALICE, b)
        with pytest.raises(KfamError) as e:
            am.create_binding(ALICE, b)
        assert e.value.status == 409

    def test_chip_quota_is_admin_only(self, world):
        api, mgr, am = world
        am.default_chip_quota = 8
        # Self-service gets the platform default, not a caller-chosen quota.
        with pytest.raises(KfamError) as e:
            am.create_profile(ALICE, "alice-ns", tpu_chip_quota=1024)
        assert e.value.status == 403
        with pytest.raises(KfamError) as e:
            am.create_profile(ALICE, "alice-ns", tpu_chip_quota=0)  # no opt-out
        assert e.value.status == 403
        p = am.create_profile(ALICE, "alice-ns")
        assert p.spec.tpu_chip_quota == 8
        # Cluster admin may set any quota.
        p = am.create_profile(ADMIN, "big-ns", owner=BOB, tpu_chip_quota=1024)
        assert p.spec.tpu_chip_quota == 1024

    def test_binding_names_do_not_collide(self, world):
        _, mgr, am = world
        am.create_profile(ALICE, "alice-ns")
        mgr.run_until_idle()
        # 'a.b@c' and 'a-b@c' sanitise to the same string; the digest suffix
        # must keep their bindings distinct.
        am.create_binding(ALICE, Binding(user="a.b@c", namespace="alice-ns",
                                         role="view"))
        am.create_binding(ALICE, Binding(user="a-b@c", namespace="alice-ns",
                                         role="view"))
        users = {b.user for b in am.list_bindings(namespace="alice-ns",
                                                  role="view")}
        assert {"a.b@c", "a-b@c"} <= users
        # Deleting one must not remove the other.
        am.delete_binding(ALICE, Binding(user="a.b@c", namespace="alice-ns",
                                         role="view"))
        users = {b.user for b in am.list_bindings(namespace="alice-ns",
                                                  role="view")}
        assert "a-b@c" in users and "a.b@c" not in users

    def test_delete_profile_authz(self, world):
        _, mgr, am = world
        am.create_profile(ALICE, "alice-ns")
        mgr.run_until_idle()
        with pytest.raises(KfamError):
            am.delete_profile(BOB, "alice-ns")
        am.delete_profile(ADMIN, "alice-ns")  # cluster admin may


class TestKfamHttp:
    def _req(self, port, method, path, caller=None, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
        )
        if caller:
            req.add_header("x-goog-authenticated-user-email", caller)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_rest_roundtrip(self, world):
        api, mgr, am = world
        srv = KfamHttpServer(am)
        srv.start()
        try:
            port = srv.port
            s, _ = self._req(port, "POST", "/kfam/v1/profiles",
                             caller=ALICE, body={"name": "alice-ns"})
            assert s == 200
            mgr.run_until_idle()
            s, body = self._req(
                port, "GET", f"/kfam/v1/bindings?user={ALICE}")
            assert s == 200
            assert any(b["namespace"] == "alice-ns"
                       for b in body["bindings"])
            s, body = self._req(port, "POST", "/kfam/v1/bindings",
                                caller=ALICE,
                                body={"user": BOB, "namespace": "alice-ns",
                                      "role": "view"})
            assert s == 200
            s, body = self._req(
                port, "GET", f"/kfam/v1/bindings?namespace=alice-ns&user={BOB}")
            assert body["bindings"][0]["role"] == "view"
            # Unauthenticated writes rejected.
            s, _ = self._req(port, "POST", "/kfam/v1/profiles",
                             body={"name": "x"})
            assert s == 401
            # Authz failure surfaces as 403.
            s, _ = self._req(port, "POST", "/kfam/v1/bindings", caller=BOB,
                             body={"user": BOB, "namespace": "alice-ns",
                                   "role": "admin"})
            assert s == 403
            s, _ = self._req(
                port, "DELETE",
                f"/kfam/v1/bindings?user={BOB}&namespace=alice-ns&role=view",
                caller=ALICE)
            assert s == 200
            s, ok = self._req(port, "GET", "/kfam/v1/role-clusteradmin",
                              caller=ADMIN)
            assert ok is True
        finally:
            srv.stop()
