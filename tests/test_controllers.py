"""Controller tests in the reference's envtest style (suite_test.go:50-72):
real API semantics, fake compute (FakeKubelet), deterministic draining."""

import time

import pytest

from kubeflow_tpu.controlplane.api import (
    EnvVar,
    Notebook,
    NotebookSpec,
    ObjectMeta,
    Pod,
    PodDefault,
    PodDefaultSpec,
    Profile,
    ProfileSpec,
    Tensorboard,
    TensorboardSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.api.core import PodSpec, Container
from kubeflow_tpu.controlplane.api.types import MeshAxesSpec
from kubeflow_tpu.controlplane.controllers import (
    FakeKubelet,
    NotebookController,
    PodDefaultMutator,
    ProfileController,
    TensorboardController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.runtime import ControllerManager, InMemoryApiServer
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def make_world(*, outcome=None, capacity=None, culling=None):
    api = InMemoryApiServer()
    api.register_mutator(PodDefaultMutator(api))
    reg = MetricsRegistry()
    mgr = ControllerManager(api)
    job_ctl = TpuJobController(api, reg, capacity=capacity)
    mgr.register(job_ctl)
    nb_kwargs = culling or {}
    nb_ctl = NotebookController(api, reg, **nb_kwargs)
    mgr.register(nb_ctl)
    mgr.register(ProfileController(api, reg))
    mgr.register(TensorboardController(api, reg))
    kubelet = FakeKubelet(api, reg, outcome=outcome)
    mgr.register(kubelet)
    return api, mgr, kubelet


def _job(name="train", ns="team-a", slice_type="v5e-16", **spec_kw):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type=slice_type, model="llama-tiny", **spec_kw),
    )


class TestTpuJobGang:
    def test_gang_creation_and_wiring(self):
        api, mgr, _ = make_world()
        api.create(_job())
        mgr.run_until_idle()
        # v5e-16 = 4 hosts -> 4 worker pods + headless service.
        pods = api.list("Pod", namespace="team-a")
        assert len(pods) == 4
        svc = api.get("Service", "train-workers", "team-a")
        assert svc.spec.cluster_ip == "None"
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env["KFTPU_COORDINATOR_ADDRESS"] == \
            "train-worker-0.train-workers.team-a:8476"
        assert env["KFTPU_NUM_PROCESSES"] == "4"
        assert env["KFTPU_SLICE_TYPE"] == "v5e-16"
        ids = sorted(
            {e.name: e.value for e in p.spec.containers[0].env}["KFTPU_PROCESS_ID"]
            for p in pods
        )
        assert ids == ["0", "1", "2", "3"]
        # ICI-topology-aware placement selectors.
        assert pods[0].spec.node_selector[
            "cloud.google.com/gke-tpu-topology"] == "4x4"
        assert pods[0].spec.containers[0].resources["google.com/tpu"] == "4"

    def test_job_runs_and_succeeds(self):
        phase = {"v": None}
        api, mgr, kubelet = make_world(
            outcome=lambda name: phase["v"] if name.startswith("train-") else None
        )
        api.create(_job())
        mgr.run_until_idle()
        job = api.get("TpuJob", "train", "team-a")
        assert job.status.phase == "Running"
        assert job.status.start_time > 0
        phase["v"] = "Succeeded"
        kubelet.tick()
        mgr.run_until_idle(include_timers_within=10.0)
        job = api.get("TpuJob", "train", "team-a")
        assert job.status.phase == "Succeeded"
        assert job.status.completion_time > 0

    def test_multislice_env(self):
        api, mgr, _ = make_world()
        api.create(_job(num_slices=2))
        mgr.run_until_idle()
        pods = api.list("Pod", namespace="team-a")
        assert len(pods) == 8  # 2 slices x 4 hosts
        env_by_pod = {
            p.metadata.name: {e.name: e.value for e in p.spec.containers[0].env}
            for p in pods
        }
        assert env_by_pod["train-worker-0"]["MEGASCALE_SLICE_ID"] == "0"
        assert env_by_pod["train-worker-7"]["MEGASCALE_SLICE_ID"] == "1"
        assert env_by_pod["train-worker-0"]["MEGASCALE_NUM_SLICES"] == "2"

    def test_invalid_topology_fails_fast(self):
        api, mgr, _ = make_world()
        api.create(_job(slice_type="v99-nope"))
        mgr.run_until_idle()
        job = api.get("TpuJob", "train", "team-a")
        assert job.status.phase == "Failed"
        conds = {c.type: c for c in job.status.conditions}
        assert conds["Admitted"].reason == "InvalidTopology"

    def test_invalid_mesh_fails_fast(self):
        api, mgr, _ = make_world()
        api.create(_job(mesh=MeshAxesSpec(dp=1, tp=32)))  # 32 > 16 chips
        mgr.run_until_idle()
        assert api.get("TpuJob", "train", "team-a").status.phase == "Failed"

    def test_gang_restart_on_worker_failure(self):
        fail_once = {"done": False}

        def outcome(name):
            if name == "train-worker-2" and not fail_once["done"]:
                fail_once["done"] = True
                return "Failed"
            return None

        api, mgr, _ = make_world(outcome=outcome)
        api.create(_job(checkpoint_dir="/ckpt/train", backoff_seconds=0.01))
        mgr.run_until_idle(include_timers_within=30.0)
        job = api.get("TpuJob", "train", "team-a")
        assert job.status.restarts == 1
        assert job.status.phase == "Running"  # gang came back
        pods = api.list("Pod", namespace="team-a")
        assert len(pods) == 4
        assert all(
            p.metadata.labels["restart-generation"] == "1" for p in pods
        )
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env["KFTPU_RESTART_COUNT"] == "1"
        assert env["KFTPU_CHECKPOINT_DIR"] == "/ckpt/train"
        events = [e.reason for e in api.list("Event", namespace="team-a")]
        assert "GangRestart" in events

    def test_exceeding_max_restarts_fails(self):
        api, mgr, _ = make_world(
            outcome=lambda name: "Failed" if name == "train-worker-0" else None
        )
        api.create(_job(max_restarts=2, backoff_seconds=0.01))
        mgr.run_until_idle(include_timers_within=30.0)
        job = api.get("TpuJob", "train", "team-a")
        assert job.status.phase == "Failed"
        assert job.status.restarts == 2

    def test_capacity_gate(self):
        api, mgr, _ = make_world(capacity={"v5e-16": 1})
        api.create(_job("a"))
        mgr.run_until_idle()
        api.create(_job("b"))
        mgr.run_until_idle()
        a = api.get("TpuJob", "a", "team-a")
        b = api.get("TpuJob", "b", "team-a")
        assert a.status.phase == "Running"
        assert b.status.phase == "Pending"
        conds = {c.type: c for c in b.status.conditions}
        assert conds["Admitted"].reason == "InsufficientCapacity"
        # Finish job a -> b admits on requeue.
        for p in api.list("Pod", namespace="team-a",
                          label_selector={"tpu.kubeflow.org/job-name": "a"}):
            p.status.phase = "Succeeded"
            api.update_status(p)
        mgr.run_until_idle(include_timers_within=10.0)
        assert api.get("TpuJob", "b", "team-a").status.phase == "Running"

    def test_quota_gate_from_profile(self):
        api, mgr, _ = make_world()
        api.create(Profile(
            metadata=ObjectMeta(name="team-a"),
            spec=ProfileSpec(owner="alice@example.com", tpu_chip_quota=16),
        ))
        mgr.run_until_idle()
        api.create(_job("a"))           # 16 chips: fits exactly
        mgr.run_until_idle()
        api.create(_job("b"))           # 16 more: over quota
        mgr.run_until_idle()
        assert api.get("TpuJob", "a", "team-a").status.phase == "Running"
        b = api.get("TpuJob", "b", "team-a")
        assert b.status.phase == "Pending"
        assert {c.type: c for c in b.status.conditions}[
            "Admitted"].reason == "QuotaExceeded"

    def test_concurrent_admission_cannot_overadmit(self):
        """ISSUE 5: the capacity gate is a cross-key check-then-act —
        with a reconcile worker pool, two Pending jobs checking at once
        used to BOTH see in_use=0 and both admit past cap (no conflict
        fires: each writes only its own status). The admission lock +
        reservation must admit exactly one."""
        import threading

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        ctl = TpuJobController(api, reg, capacity={"v5e-16": 1},
                               hbm_check=False)
        jobs = [api.create(_job(n)) for n in ("a", "b", "c")]
        from kubeflow_tpu.topology import get_slice

        st = get_slice("v5e-16")
        barrier = threading.Barrier(len(jobs))
        results = {}

        def admit(job):
            barrier.wait()
            results[job.metadata.name] = ctl._admission_blocked(job, st)

        threads = [threading.Thread(target=admit, args=(j,)) for j in jobs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        admitted = [n for n, blocked in results.items() if blocked is None]
        assert len(admitted) == 1, results

    def test_admission_reservation_released_on_terminal(self):
        """A reserved-but-then-terminal job frees its capacity for the
        next admission pass (and an in-use job's reservation collapses
        into its store phase instead of double-counting)."""
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        ctl = TpuJobController(api, reg, capacity={"v5e-16": 1},
                               hbm_check=False)
        from kubeflow_tpu.topology import get_slice

        st = get_slice("v5e-16")
        a = api.create(_job("a"))
        b = api.create(_job("b"))
        assert ctl._admission_blocked(a, st) is None      # a reserves
        assert ctl._admission_blocked(b, st) is not None  # b blocked by it
        a.status.phase = "Failed"
        api.update_status(a)
        b = api.get("TpuJob", "b", "team-a")
        assert ctl._admission_blocked(b, st) is None      # freed

    def test_delete_cascades_pods(self):
        api, mgr, _ = make_world()
        api.create(_job())
        mgr.run_until_idle()
        api.delete("TpuJob", "train", "team-a")
        mgr.run_until_idle()
        assert api.list("Pod", namespace="team-a") == []


class TestNotebook:
    def test_notebook_with_tpu(self):
        api, mgr, _ = make_world()
        api.create(Notebook(
            metadata=ObjectMeta(name="nb1", namespace="team-a"),
            spec=NotebookSpec(tpu_slice="v5e-8"),
        ))
        mgr.run_until_idle()
        pod = api.get("Pod", "nb1-0", "team-a")
        assert pod.spec.containers[0].resources["google.com/tpu"] == "8"
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["NB_PREFIX"] == "/notebook/team-a/nb1"
        vs = api.get("VirtualService", "notebook-nb1", "team-a")
        assert vs.http[0].prefix == "/notebook/team-a/nb1/"
        nb = api.get("Notebook", "nb1", "team-a")
        assert nb.status.container_state == "Running"
        assert nb.status.ready_replicas == 1

    def test_multihost_tpu_notebook_rejected(self):
        api, mgr, _ = make_world()
        api.create(Notebook(
            metadata=ObjectMeta(name="nb2", namespace="team-a"),
            spec=NotebookSpec(tpu_slice="v5e-16"),
        ))
        mgr.run_until_idle(include_timers_within=0.0)
        # reconcile error -> no pod; controller counted an error
        assert api.try_get("Pod", "nb2-0", "team-a") is None

    def test_culling_stops_idle_notebook(self):
        api, mgr, _ = make_world(
            culling=dict(enable_culling=True, idle_seconds=0.05,
                         culling_check_period=0.01)
        )
        api.create(Notebook(
            metadata=ObjectMeta(name="nb3", namespace="team-a"),
            spec=NotebookSpec(),
        ))
        mgr.run_until_idle()
        assert api.get("Pod", "nb3-0", "team-a").status.phase == "Running"
        time.sleep(0.1)
        mgr.run_until_idle(include_timers_within=1.0)
        nb = api.get("Notebook", "nb3", "team-a")
        assert "kubeflow-resource-stopped" in nb.metadata.annotations
        assert api.try_get("Pod", "nb3-0", "team-a") is None
        assert nb.status.container_state == "Stopped"

    def test_activity_annotation_defers_culling(self):
        api, mgr, _ = make_world(
            culling=dict(enable_culling=True, idle_seconds=3600,
                         culling_check_period=0.01)
        )
        api.create(Notebook(
            metadata=ObjectMeta(name="nb4", namespace="team-a"),
            spec=NotebookSpec(),
        ))
        mgr.run_until_idle()
        pod = api.get("Pod", "nb4-0", "team-a")
        pod.metadata.annotations[
            "notebooks.tpu.kubeflow.org/last-activity"] = str(time.time())
        api.update(pod)
        mgr.run_until_idle()
        nb = api.get("Notebook", "nb4", "team-a")
        assert "kubeflow-resource-stopped" not in nb.metadata.annotations
        assert nb.status.last_activity > 0


class TestProfile:
    def test_provisions_namespace_rbac_quota(self):
        api, mgr, _ = make_world()
        api.create(Profile(
            metadata=ObjectMeta(name="team-b"),
            spec=ProfileSpec(owner="bob@example.com", tpu_chip_quota=32),
        ))
        mgr.run_until_idle()
        ns = api.get("Namespace", "team-b")
        assert ns.metadata.annotations["owner"] == "bob@example.com"
        assert ns.metadata.labels["istio-injection"] == "enabled"
        assert api.get("ServiceAccount", "default-editor", "team-b")
        rb = api.get("RoleBinding", "namespaceAdmin", "team-b")
        assert rb.subjects[0].name == "bob@example.com"
        rq = api.get("ResourceQuota", "kf-resource-quota", "team-b")
        assert rq.hard["google.com/tpu"] == "32"
        ap = api.get("AuthorizationPolicy", "ns-owner-access-istio", "team-b")
        assert ap.principals == ["bob@example.com"]
        assert api.get("Profile", "team-b").status.phase == "Ready"

    def test_clearing_quota_deletes_resource_quota(self):
        api, mgr, _ = make_world()
        api.create(Profile(
            metadata=ObjectMeta(name="team-q"),
            spec=ProfileSpec(owner="q@example.com", tpu_chip_quota=16),
        ))
        mgr.run_until_idle()
        assert api.get("ResourceQuota", "kf-resource-quota", "team-q")
        p = api.get("Profile", "team-q")
        p.spec.tpu_chip_quota = 0
        api.update(p)
        mgr.run_until_idle()
        assert api.try_get("ResourceQuota", "kf-resource-quota",
                           "team-q") is None

    def test_profile_delete_cascades(self):
        api, mgr, _ = make_world()
        api.create(Profile(metadata=ObjectMeta(name="team-c"),
                           spec=ProfileSpec(owner="c@example.com")))
        mgr.run_until_idle()
        api.delete("Profile", "team-c")
        mgr.run_until_idle()
        assert api.try_get("Namespace", "team-c") is None
        assert api.try_get("RoleBinding", "namespaceAdmin", "team-c") is None


class TestPodDefaults:
    def test_injection_on_matching_pod(self):
        api, mgr, _ = make_world()
        api.create(PodDefault(
            metadata=ObjectMeta(name="add-gcp-secret", namespace="team-a"),
            spec=PodDefaultSpec(
                selector={"add-gcp-secret": "true"},
                env=[EnvVar("GOOGLE_APPLICATION_CREDENTIALS", "/secret/sa.json")],
                annotations={"injected": "yes"},
            ),
        ))
        api.create(Notebook(
            metadata=ObjectMeta(name="nb5", namespace="team-a",
                                labels={"add-gcp-secret": "true"}),
            spec=NotebookSpec(),
        ))
        mgr.run_until_idle()
        pod = api.get("Pod", "nb5-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["GOOGLE_APPLICATION_CREDENTIALS"] == "/secret/sa.json"
        assert pod.metadata.annotations["injected"] == "yes"
        assert "add-gcp-secret" in pod.metadata.annotations[
            "poddefaults.tpu.kubeflow.org/applied"]

    def test_no_match_no_mutation(self):
        api, mgr, _ = make_world()
        api.create(PodDefault(
            metadata=ObjectMeta(name="pd", namespace="team-a"),
            spec=PodDefaultSpec(selector={"x": "y"},
                                env=[EnvVar("A", "1")]),
        ))
        api.create(Notebook(metadata=ObjectMeta(name="nb6", namespace="team-a"),
                            spec=NotebookSpec()))
        mgr.run_until_idle()
        pod = api.get("Pod", "nb6-0", "team-a")
        assert "A" not in {e.name for e in pod.spec.containers[0].env}

    def test_conflicting_defaults_rejected(self):
        from kubeflow_tpu.controlplane.webhook.poddefault import (
            PodDefaultConflictError,
        )

        api, mgr, _ = make_world()
        for i, val in enumerate(("1", "2")):
            api.create(PodDefault(
                metadata=ObjectMeta(name=f"pd{i}", namespace="team-a"),
                spec=PodDefaultSpec(selector={"sel": "on"},
                                    env=[EnvVar("SAME", val)]),
            ))
        with pytest.raises(PodDefaultConflictError):
            api.create(Pod(
                metadata=ObjectMeta(name="p", namespace="team-a",
                                    labels={"sel": "on"}),
                spec=PodSpec(containers=[Container(name="c")]),
            ))


class TestTensorboard:
    def test_tensorboard_stack(self):
        api, mgr, _ = make_world()
        api.create(Tensorboard(
            metadata=ObjectMeta(name="tb1", namespace="team-a"),
            spec=TensorboardSpec(logspath="gs://bkt/logs",
                                 trace_dir="gs://bkt/traces"),
        ))
        mgr.run_until_idle()
        pod = api.get("Pod", "tb1-tb", "team-a")
        assert "--logdir=gs://bkt/logs" in pod.spec.containers[0].args
        vs = api.get("VirtualService", "tensorboard-tb1", "team-a")
        assert vs.http[0].prefix == "/tensorboard/team-a/tb1/"
        tb = api.get("Tensorboard", "tb1", "team-a")
        assert tb.status.ready is True


class TestServing:
    def _world(self):
        from kubeflow_tpu.controlplane.controllers import ServingController

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ServingController(api, reg))
        kubelet = FakeKubelet(api, reg)
        mgr.register(kubelet)
        return api, mgr, kubelet

    def _serving(self, name="llm", ns="team-a", **kw):
        from kubeflow_tpu.controlplane.api import Serving, ServingSpec

        kw.setdefault("model", "llama-tiny")
        kw.setdefault("slice_type", "v5e-8")
        return Serving(metadata=ObjectMeta(name=name, namespace=ns),
                       spec=ServingSpec(**kw))

    def test_deploy_wait_ready_contract(self):
        """The reference's serving lifecycle (test_tf_serving.py:60-156):
        deploy, readiness gate flips when the pod runs, endpoint routed."""
        api, mgr, kubelet = self._world()
        api.create(self._serving(max_batch=4, port=9000))
        mgr.run_until_idle()

        pod = api.get("Pod", "llm-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_SERVING_MODEL"] == "llama-tiny"
        assert env["KFTPU_SERVING_PORT"] == "9000"
        assert env["KFTPU_SERVING_MAX_BATCH"] == "4"
        assert pod.spec.containers[0].command[-1] == \
            "kubeflow_tpu.serving.server"
        assert "google.com/tpu" in str(pod.spec.containers[0].resources)

        kubelet.tick()
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert sv.status.ready is True
        assert sv.status.phase == "Ready"
        assert sv.status.endpoint == "/serving/team-a/llm/"
        svc = api.get("Service", "llm-serving", "team-a")
        assert svc.spec.ports[0].target_port == 9000
        vs = api.get("VirtualService", "serving-llm", "team-a")
        assert vs.http[0].prefix == "/serving/team-a/llm/"

    def test_invalid_model_fails(self):
        api, mgr, _ = self._world()
        api.create(self._serving(name="bad", model="no-such-model"))
        mgr.run_until_idle()
        sv = api.get("Serving", "bad", "team-a")
        assert sv.status.phase == "Failed"
        assert sv.status.ready is False
        assert api.try_get("Pod", "bad-serving-0", "team-a") is None

    def test_multihost_slice_rejected(self):
        api, mgr, _ = self._world()
        api.create(self._serving(name="big", slice_type="v5e-16"))
        mgr.run_until_idle()
        sv = api.get("Serving", "big", "team-a")
        assert sv.status.phase == "Failed"

    def test_unknown_slice_type_fails_not_crashes(self):
        api, mgr, _ = self._world()
        api.create(self._serving(name="typo", slice_type="v5e-7"))
        mgr.run_until_idle()
        sv = api.get("Serving", "typo", "team-a")
        assert sv.status.phase == "Failed"
        assert "slice_type" in sv.status.conditions[-1].message

    def test_user_label_cannot_break_selector(self):
        api, mgr, kubelet = self._world()
        sv = self._serving(name="lbl")
        sv.metadata.labels["serving-name"] = "sabotage"
        api.create(sv)
        mgr.run_until_idle()
        pod = api.get("Pod", "lbl-serving-0", "team-a")
        assert pod.metadata.labels["serving-name"] == "lbl"

    def test_engine_knobs_ride_env_contract(self):
        """quantize/param_dtype/prefill_buckets/pipeline_depth reach the
        pod env (the int8 path must be switchable from the CRD — it's what
        fits an 8B model on a 16G chip)."""
        api, mgr, kubelet = self._world()
        api.create(self._serving(
            name="q8", quantize="int8", param_dtype="float32",
            prefill_buckets=[64, 256], pipeline_depth=3, logprobs=True,
        ))
        mgr.run_until_idle()
        pod = api.get("Pod", "q8-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_SERVING_QUANTIZE"] == "int8"
        assert env["KFTPU_SERVING_PARAM_DTYPE"] == "float32"
        assert env["KFTPU_SERVING_PREFILL_BUCKETS"] == "64,256"
        assert env["KFTPU_SERVING_PIPELINE_DEPTH"] == "3"
        assert env["KFTPU_SERVING_LOGPROBS"] == "1"
        # defaults stay off the env so existing pods see no spec drift
        api.create(self._serving(name="plain"))
        mgr.run_until_idle()
        pod = api.get("Pod", "plain-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        for k in ("KFTPU_SERVING_QUANTIZE", "KFTPU_SERVING_PARAM_DTYPE",
                  "KFTPU_SERVING_PREFILL_BUCKETS",
                  "KFTPU_SERVING_PIPELINE_DEPTH",
                  "KFTPU_SERVING_LOGPROBS"):
            assert k not in env

    def test_invalid_quantize_rejected(self):
        api, mgr, _ = self._world()
        api.create(self._serving(name="badq", quantize="fp4"))
        mgr.run_until_idle()
        sv = api.get("Serving", "badq", "team-a")
        assert sv.status.phase == "Failed"
        assert "quantize" in sv.status.conditions[-1].message

    def test_max_queue_rides_env_contract(self):
        """ISSUE 7: spec.max_queue reaches the replica pod env — the
        engine's bounded-admission cap AND the watermark its /healthz
        reports to the LB. 0 (unbounded) stays off the env."""
        api, mgr, _ = self._world()
        api.create(self._serving(name="bounded", max_queue=17))
        api.create(self._serving(name="unbounded", max_queue=0))
        mgr.run_until_idle()
        pod = api.get("Pod", "bounded-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_SERVING_MAX_QUEUE"] == "17"
        pod = api.get("Pod", "unbounded-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert "KFTPU_SERVING_MAX_QUEUE" not in env

    def test_negative_max_queue_rejected(self):
        api, mgr, _ = self._world()
        api.create(self._serving(name="badmq", max_queue=-1))
        mgr.run_until_idle()
        sv = api.get("Serving", "badmq", "team-a")
        assert sv.status.phase == "Failed"
        assert "max_queue" in sv.status.conditions[-1].message

    def test_invalid_autoscale_specs_rejected(self):
        from kubeflow_tpu.controlplane.api import AutoscaleSpec

        cases = {
            "as-min": AutoscaleSpec(min_replicas=0, max_replicas=2),
            "as-max": AutoscaleSpec(min_replicas=3, max_replicas=2),
            "as-tgt": AutoscaleSpec(min_replicas=1, max_replicas=2,
                                    target_queue_wait_s=0.0),
        }
        api, mgr, _ = self._world()
        for name, a in cases.items():
            api.create(self._serving(name=name, autoscale=a))
        mgr.run_until_idle()
        for name in cases:
            sv = api.get("Serving", name, "team-a")
            assert sv.status.phase == "Failed", name
            assert "autoscale" in sv.status.conditions[-1].message

    def _replica_world(self, drain_grace_s=0.0):
        from kubeflow_tpu.controlplane.controllers import ServingController

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ServingController(api, reg,
                                       drain_grace_s=drain_grace_s))
        kubelet = FakeKubelet(api, reg)
        mgr.register(kubelet)
        return api, mgr, kubelet

    def test_replicas_scale_up(self):
        api, mgr, kubelet = self._replica_world()
        api.create(self._serving(name="llm", replicas=2, port=9000))
        mgr.run_until_idle()
        for i in range(2):
            pod = api.get("Pod", f"llm-serving-{i}", "team-a")
            env = {e.name: e.value for e in pod.spec.containers[0].env}
            # ordinal port offset: replicas must not collide on the flat
            # process-kubelet host network
            assert env["KFTPU_SERVING_PORT"] == str(9000 + i)
        kubelet.tick()
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        assert sv.status.ready_replicas == 2
        assert sv.status.replicas == 2
        assert len(sv.status.endpoints) == 2
        assert {e.split(":")[1] for e in sv.status.endpoints} == \
            {"9000", "9001"}

    def test_scale_down_drains_before_delete(self):
        api, mgr, kubelet = self._replica_world(drain_grace_s=30.0)
        api.create(self._serving(name="llm", replicas=2, port=9000))
        mgr.run_until_idle()
        kubelet.tick()
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        sv.spec.replicas = 1
        api.update(sv)
        mgr.run_until_idle()
        # within the grace window: replica 1 still exists (in-flight
        # requests finish) but is gone from the dispatch set
        pod1 = api.try_get("Pod", "llm-serving-1", "team-a")
        assert pod1 is not None
        from kubeflow_tpu.controlplane.controllers.serving import (
            ServingController,
        )
        assert ServingController.DRAIN_ANNOTATION in pod1.metadata.annotations
        sv = api.get("Serving", "llm", "team-a")
        assert len(sv.status.endpoints) == 1
        assert sv.status.endpoints[0].endswith(":9000")

    def test_scale_down_deletes_after_grace(self):
        api, mgr, kubelet = self._replica_world(drain_grace_s=0.0)
        api.create(self._serving(name="llm", replicas=3, port=9000))
        mgr.run_until_idle()
        kubelet.tick()
        mgr.run_until_idle()
        sv = api.get("Serving", "llm", "team-a")
        sv.spec.replicas = 1
        api.update(sv)
        mgr.run_until_idle()
        mgr.run_until_idle()   # second pass: drain marked, then deleted
        assert api.try_get("Pod", "llm-serving-1", "team-a") is None
        assert api.try_get("Pod", "llm-serving-2", "team-a") is None
        assert api.try_get("Pod", "llm-serving-0", "team-a") is not None

    def test_failed_replica_recreated(self):
        api, mgr, kubelet = self._replica_world()
        kubelet.outcome = lambda name: None
        api.create(self._serving(name="llm", replicas=2))
        mgr.run_until_idle()
        kubelet.tick()
        mgr.run_until_idle()
        old_uid = api.get("Pod", "llm-serving-1", "team-a").metadata.uid
        # replica 1 crashes ONCE (a one-shot outcome: the recreated pod
        # must not be re-failed or reconcile livelocks by design)
        crashed = []

        def crash_once(name):
            if name.endswith("-1") and not crashed:
                crashed.append(name)
                return "Failed"
            return None

        kubelet.outcome = crash_once
        kubelet.tick()
        mgr.run_until_idle()
        kubelet.outcome = None
        kubelet.tick()
        mgr.run_until_idle()
        pod = api.get("Pod", "llm-serving-1", "team-a")
        assert pod.metadata.uid != old_uid
        assert pod.status.phase == "Running"
        sv = api.get("Serving", "llm", "team-a")
        assert sv.status.ready_replicas == 2

    def test_spec_change_recreates_pod(self):
        api, mgr, kubelet = self._world()
        api.create(self._serving(name="llm2", port=8000))
        mgr.run_until_idle()
        kubelet.tick()
        mgr.run_until_idle()
        sv = api.get("Serving", "llm2", "team-a")
        sv.spec.port = 9100
        api.update(sv)
        mgr.run_until_idle()
        pod = api.get("Pod", "llm2-serving-0", "team-a")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_SERVING_PORT"] == "9100"
        svc = api.get("Service", "llm2-serving", "team-a")
        assert svc.spec.ports[0].target_port == 9100


class TestAdmissionRaceSafety:
    def test_capacity_gate_under_background_manager(self):
        """Admission must stay all-or-nothing when the manager runs in
        background mode with API writers racing it: with capacity 1, at no
        point may two jobs hold the slice (VERDICT weak #6 — pins the
        serialized-reconcile semantics the gate relies on)."""
        api, mgr, kubelet = make_world(capacity={"v5e-16": 1})
        mgr.start()
        try:
            running_ish = ("Scheduling", "Starting", "Running", "Restarting")
            violations = []
            for i in range(5):
                api.create(_job(f"race-{i}"))
            deadline = time.time() + 10
            while time.time() < deadline:
                kubelet.tick()
                jobs = api.list("TpuJob", namespace="team-a")
                admitted = [j.metadata.name for j in jobs
                            if j.status.phase in running_ish]
                if len(admitted) > 1:
                    violations.append(admitted)
                if any(j.status.phase == "Running" for j in jobs):
                    break
                time.sleep(0.05)
            assert not violations, f"double admission observed: {violations}"
            jobs = api.list("TpuJob", namespace="team-a")
            phases = sorted(j.status.phase for j in jobs)
            assert phases.count("Running") == 1
            assert phases.count("Pending") == 4
        finally:
            mgr.stop()


class TestProfilePlugins:
    def _world(self):
        from kubeflow_tpu.controlplane.controllers.profile import (
            ProfileController,
            WorkloadIdentityPlugin,
        )

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        wi = WorkloadIdentityPlugin()
        mgr.register(ProfileController(api, reg, plugins={wi.KIND: wi}))
        return api, mgr, wi

    def _profile(self, name="team-wi", gsa="robot@proj.iam.gserviceaccount.com"):
        from kubeflow_tpu.controlplane.api.types import ProfilePluginSpec

        return Profile(
            metadata=ObjectMeta(name=name),
            spec=ProfileSpec(
                owner="alice@corp",
                plugins=[ProfilePluginSpec(
                    kind="WorkloadIdentity",
                    params={"gcpServiceAccount": gsa},
                )],
            ),
        )

    def test_plugin_applies_and_finalizer_guards(self):
        from kubeflow_tpu.controlplane.controllers.profile import (
            PLUGIN_FINALIZER,
            WI_ANNOTATION,
        )

        api, mgr, wi = self._world()
        api.create(self._profile())
        mgr.run_until_idle()

        prof = api.get("Profile", "team-wi")
        assert PLUGIN_FINALIZER in prof.metadata.finalizers
        sa = api.get("ServiceAccount", "default-editor", "team-wi")
        assert sa.metadata.annotations[WI_ANNOTATION] == \
            "robot@proj.iam.gserviceaccount.com"
        assert wi.iam["robot@proj.iam.gserviceaccount.com"] == {
            "serviceAccount:team-wi/default-editor"
        }

        # Delete: revoke runs, finalizer releases, profile goes away.
        api.delete("Profile", "team-wi")
        mgr.run_until_idle()
        assert api.try_get("Profile", "team-wi") is None
        assert wi.iam["robot@proj.iam.gserviceaccount.com"] == set()

    def test_unknown_plugin_fails_profile(self):
        from kubeflow_tpu.controlplane.api.types import ProfilePluginSpec

        api, mgr, _ = self._world()
        api.create(Profile(
            metadata=ObjectMeta(name="bad"),
            spec=ProfileSpec(owner="bob@corp", plugins=[
                ProfilePluginSpec(kind="NoSuchCloud"),
            ]),
        ))
        mgr.run_until_idle()
        prof = api.get("Profile", "bad")
        assert prof.status.phase == "Failed"

    def test_param_change_revokes_old_grant(self):
        from kubeflow_tpu.controlplane.api.types import ProfilePluginSpec

        api, mgr, wi = self._world()
        api.create(self._profile(gsa="old@proj.iam.gserviceaccount.com"))
        mgr.run_until_idle()
        assert wi.iam["old@proj.iam.gserviceaccount.com"]

        prof = api.get("Profile", "team-wi")
        prof.spec.plugins = [ProfilePluginSpec(
            kind="WorkloadIdentity",
            params={"gcpServiceAccount": "new@proj.iam.gserviceaccount.com"},
        )]
        api.update(prof)
        mgr.run_until_idle()
        # Old grant revoked, new one applied — no privilege leak.
        assert wi.iam["old@proj.iam.gserviceaccount.com"] == set()
        assert wi.iam["new@proj.iam.gserviceaccount.com"] == {
            "serviceAccount:team-wi/default-editor"
        }

    def test_plugin_removal_revokes(self):
        api, mgr, wi = self._world()
        api.create(self._profile(gsa="g@proj.iam.gserviceaccount.com"))
        mgr.run_until_idle()
        prof = api.get("Profile", "team-wi")
        prof.spec.plugins = []
        api.update(prof)
        mgr.run_until_idle()
        assert wi.iam["g@proj.iam.gserviceaccount.com"] == set()

    def test_misconfigured_plugin_fails_not_hotloops(self):
        from kubeflow_tpu.controlplane.api.types import ProfilePluginSpec

        api, mgr, _ = self._world()
        api.create(Profile(
            metadata=ObjectMeta(name="noparams"),
            spec=ProfileSpec(owner="c@corp", plugins=[
                ProfilePluginSpec(kind="WorkloadIdentity", params={}),
            ]),
        ))
        mgr.run_until_idle()          # must converge, not livelock
        prof = api.get("Profile", "noparams")
        assert prof.status.phase == "Failed"
        assert "gcpServiceAccount" in prof.status.conditions[-1].message
