"""Frontend page-script verification (webapps/frontend.py).

The reference drove its UIs with Selenium/puppeteer against live
deployments (testing/test_jwa.py:32-423,
components/centraldashboard/test/e2e.test.ts). This environment ships NO
JavaScript runtime (checked: node, bun, deno, d8, jsc, gjs, chromium,
python quickjs/dukpy/js2py — none installed, zero egress to fetch one),
so the page JS is covered at two tiers:

1. **Static sink audit (always runs):** every ``${...}`` interpolation in
   every page script must pass through ``esc()`` or
   ``encodeURIComponent()`` (or be a ``.toFixed()`` numeral) — the
   invariant that makes stored XSS via resource names impossible. This is
   the regression class a DOM test would catch, enforced structurally.
2. **Real execution (runs when a JS runtime exists):** a DOM/fetch shim
   drives the REAL served page script against the REAL platform REST
   surface over HTTP — spawner create -> list -> delete, hub contributor
   add, and an XSS payload in a notebook name rendered inert. Skipped
   with a loud reason where no runtime exists; runs under node or bun.
"""

import json
import re
import shutil
import subprocess
import textwrap
import threading

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta
from kubeflow_tpu.controlplane.api.types import PlatformConfig, Profile, ProfileSpec
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.webapps.frontend import central_hub
from kubeflow_tpu.webapps.router import JsonHttpServer, Request

USER_HEADER = "x-goog-authenticated-user-email"
USER = "alice@example.com"


def _page(path: str) -> str:
    """Render a page exactly as served (script helpers included)."""
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    pf.reconcile()
    hub = central_hub(pf.api, pf.dashboard, pf.jwa)
    status, body = hub.dispatch(Request(
        method="GET", path=path, params={}, query={}, body={},
        caller=USER, headers={},
    ))
    assert status == 200
    return str(body)


def _scripts(html: str):
    return re.findall(r"<script>(.*?)</script>", html, re.S)


def _any_page(path: str) -> str:
    """Hub pages plus the bootstrap deploy form — every served page with
    inline JS goes through the same structural audit."""
    if path == "bootstrap:/":
        from kubeflow_tpu.controlplane.bootstrap import _deploy_page

        return _deploy_page()
    return _page(path)


class TestStaticSinkAudit:
    """Structural XSS guarantee: no template interpolation reaches the
    DOM unescaped."""

    # spark() is the one helper allowed to produce markup: its output is
    # built solely from toFixed() numerals and esc() — both audited here
    # since its body lives in the same script.
    ALLOWED = re.compile(
        r"^\s*(esc|encodeURIComponent|spark)\s*\(|\.toFixed\(\d+\)\s*$"
    )

    @pytest.mark.parametrize("path", ["/", "/spawner", "bootstrap:/"])
    def test_every_interpolation_is_escaped(self, path):
        html = _any_page(path)
        scripts = _scripts(html)
        assert scripts, "page must inline its script"
        checked = 0
        for script in scripts:
            for m in re.finditer(r"\$\{([^{}]+)\}", script):
                expr = m.group(1)
                assert self.ALLOWED.search(expr), (
                    f"unescaped interpolation in {path} page script: "
                    f"${{{expr}}} — wrap in esc() (DOM) or "
                    f"encodeURIComponent() (URL)"
                )
                checked += 1
        assert checked >= 5     # the audit actually saw the real sinks

    def test_esc_covers_the_html_metacharacters(self):
        html = _page("/")
        (script,) = _scripts(html)[:1]
        m = re.search(
            r"function esc\(s\)\s*{\s*return String\(s\)\.replace\("
            r"/\[(.*?)\]/g", script)
        assert m, "esc() definition changed — update this audit"
        cls = m.group(1)
        for ch in ["&", "<", ">", '"']:
            assert ch in cls, f"esc() must escape {ch!r}"
        assert "'" in cls or "\\'" in cls
        # the replacement map carries the right entities
        for entity in ("&amp;", "&lt;", "&gt;", "&quot;", "&#39;"):
            assert entity in script

    def test_delete_buttons_use_dataset_not_inline_js(self):
        """Event delegation contract: no inline onclick strings built from
        user data (the classic injection that esc() alone cannot fix)."""
        html = _page("/spawner")
        script = "".join(_scripts(html))
        assert 'data-name="${esc(n.name)}"' in script
        assert "onclick=\"" not in script.replace('b.onclick', '')


JS_RUNTIME = shutil.which("node") or shutil.which("bun")

# DOM/fetch shim: just enough browser for the page scripts — element
# registry with innerHTML/value/onsubmit/onclick, button.del delegation
# via regex over the rendered HTML, fetch with the trusted identity
# header injected (standing in for the gatekeeper AuthProxy).
_SHIM = r"""
const HUB = process.env.HUB;
const USER_HEADER = process.env.USER_HEADER;
const USER = process.env.USER_ID;
const elements = new Map();
function makeEl(id) {
  const el = {
    id, _html: "", value: "", textContent: "",
    listeners: {},
    set innerHTML(v) { this._html = String(v); },
    get innerHTML() { return this._html; },
    set onsubmit(f) { this.listeners.submit = f; },
    get onsubmit() { return this.listeners.submit; },
    set onclick(f) { this.listeners.click = f; },
    get onclick() { return this.listeners.click; },
    set onchange(f) { this.listeners.change = f; },
    get onchange() { return this.listeners.change; },
    querySelectorAll(sel) {
      if (sel !== "button.del") return [];
      const out = [];
      const re = /<button class="del" data-name="([^"]*)"/g;
      let m;
      while ((m = re.exec(this._html)) !== null) {
        const unescaped = m[1]
          .replace(/&lt;/g, "<").replace(/&gt;/g, ">")
          .replace(/&quot;/g, '"').replace(/&#39;/g, "'")
          .replace(/&amp;/g, "&");
        out.push({ dataset: { name: unescaped }, set onclick(f) {
          this._click = f; }, get onclick() { return this._click; } });
      }
      this._delBtns = out;
      return out;
    },
  };
  return el;
}
const document = {
  getElementById(id) {
    if (!elements.has(id)) elements.set(id, makeEl(id));
    return elements.get(id);
  },
};
const location = { reload() {} };
const realFetch = globalThis.fetch;
async function fetch(path, opts) {
  opts = opts || {};
  opts.headers = Object.assign({}, opts.headers || {},
                               { [USER_HEADER]: USER });
  return realFetch(HUB + path, opts);
}
function setInterval() {}
async function settle(ms) { await new Promise(r => setTimeout(r, ms)); }
"""

_DRIVER = r"""
async function main() {
  await settle(300);   // init()/loadNs() fire at script end; let them land
  const PAYLOAD = '<img src=x onerror=globalThis.__xss=1>';
  if (process.env.PAGE === "spawner") {
    const list = document.getElementById("list");
    if (!list._html.includes("<table"))
      throw new Error("init/refresh never rendered: " + list._html);
    // create a notebook whose NAME is an XSS payload
    document.getElementById("name").value = PAYLOAD;
    document.getElementById("image").value = "jupyter:latest";
    document.getElementById("slice").value = "";
    let err = null;
    try {
      await document.getElementById("spawn").listeners.submit(
        { preventDefault() {} });
    } catch (e) { err = e; }
    if (err === null) {
      await settle(200);
      if (globalThis.__xss) throw new Error("XSS PAYLOAD EXECUTED");
      if (list._html.includes("<img"))
        throw new Error("payload reached innerHTML unescaped: "
                        + list._html);
      if (!list._html.includes("&lt;img"))
        throw new Error("payload row missing (escaped form not found): "
                        + list._html);
      // delete it through the page's own delegation path
      const btns = list.querySelectorAll("button.del");
      const victim = btns.find(b => b.dataset.name === PAYLOAD);
      if (!victim) throw new Error("delete button for payload not found");
    } else {
      // server-side name validation (DNS-1123) may reject the payload —
      // equally inert; fall through to the clean-name flow
    }
    // clean create -> list -> delete
    document.getElementById("name").value = "jsdrive";
    await document.getElementById("spawn").listeners.submit(
      { preventDefault() {} });
    await settle(200);
    if (!list._html.includes(">jsdrive<"))
      throw new Error("created notebook not listed: " + list._html);
    const btn = list.querySelectorAll("button.del")
      .find(b => b.dataset.name === "jsdrive");
    await btn.onclick();
    await settle(200);
    if (list._html.includes(">jsdrive<"))
      throw new Error("deleted notebook still listed");
    console.log("SPAWNER_OK xss_inert=" + !globalThis.__xss);
  } else {
    const contributors = document.getElementById("contributors");
    document.getElementById("cemail").value = "bob@example.com";
    await document.getElementById("addc").listeners.submit(
      { preventDefault() {} });
    await settle(300);
    if (!contributors.textContent.includes("bob@example.com"))
      throw new Error("contributor not rendered: "
                      + contributors.textContent);
    console.log("HUB_OK");
  }
}
main().then(() => process.exit(0),
            e => { console.error(e.stack || e); process.exit(1); });
"""


@pytest.mark.skipif(
    JS_RUNTIME is None,
    reason="no JS runtime in this image (node/bun absent; zero egress); "
           "tier-1 static audit still enforces the escaping contract",
)
class TestRealPageExecution:
    @pytest.fixture()
    def stack(self):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                              spec=ProfileSpec(owner=USER)))
        pf.reconcile()
        pf.manager.start()
        hub = central_hub(pf.api, pf.dashboard, pf.jwa)
        srv = JsonHttpServer(hub, port=0).start()
        yield pf, srv
        srv.stop()
        pf.manager.stop()

    def _run_page(self, srv, page, tmp_path):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/"
            + ("spawner" if page == "spawner" else ""),
            headers={USER_HEADER: USER},
        )
        html = urllib.request.urlopen(req).read().decode()
        (page_script,) = _scripts(html)
        harness = tmp_path / f"{page}.js"
        harness.write_text(_SHIM + page_script + _DRIVER)
        env = {
            "HUB": f"http://127.0.0.1:{srv.port}",
            "USER_HEADER": USER_HEADER,
            "USER_ID": USER,
            "PAGE": page,
            "PATH": "/usr/bin:/bin:/usr/local/bin",
        }
        return subprocess.run(
            [JS_RUNTIME, str(harness)], env=env,
            capture_output=True, text=True, timeout=60,
        )

    def test_spawner_create_list_delete_and_xss_inert(self, stack,
                                                      tmp_path):
        _, srv = stack
        out = self._run_page(srv, "spawner", tmp_path)
        assert out.returncode == 0, out.stderr
        assert "SPAWNER_OK" in out.stdout

    def test_hub_contributor_add(self, stack, tmp_path):
        _, srv = stack
        out = self._run_page(srv, "hub", tmp_path)
        assert out.returncode == 0, out.stderr
        assert "HUB_OK" in out.stdout
