"""Frontend page-script verification (webapps/frontend.py).

The reference drove its UIs with Selenium/puppeteer against live
deployments (testing/test_jwa.py:32-423,
components/centraldashboard/test/e2e.test.ts). This environment ships no
external JavaScript runtime (node, bun, deno, d8, jsc, gjs, chromium,
python quickjs/dukpy/js2py — none installed, zero egress to fetch one),
so the framework vendors its own: ``webapps.minijs`` (a tree-walking JS
interpreter covering the pages' dialect) under ``webapps.browser``'s
MicroBrowser (document/fetch shim over the live HTTP server). The page JS
is covered at two tiers:

1. **Static sink audit (always runs):** every ``${...}`` interpolation in
   every page script must pass through ``esc()`` or
   ``encodeURIComponent()`` (or be a ``.toFixed()`` numeral) — the
   invariant that makes stored XSS via resource names impossible. This is
   the regression class a DOM test would catch, enforced structurally.
2. **Real execution (always runs):** MicroBrowser fetches the served
   page over HTTP, EXECUTES its inline script with minijs against the
   live platform REST surface — spawner create -> list -> delete, hub
   contributor add, click-to-deploy create/delete, and an XSS payload in
   a resource name rendered inert by the *executed* esc(), not by static
   audit.
"""

import re

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta
from kubeflow_tpu.controlplane.api.types import PlatformConfig, Profile, ProfileSpec
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.webapps.frontend import central_hub
from kubeflow_tpu.webapps.router import JsonHttpServer, Request

USER_HEADER = "x-goog-authenticated-user-email"
USER = "alice@example.com"


def _page(path: str) -> str:
    """Render a page exactly as served (script helpers included)."""
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    pf.reconcile()
    hub = central_hub(pf.api, pf.dashboard, pf.jwa)
    status, body = hub.dispatch(Request(
        method="GET", path=path, params={}, query={}, body={},
        caller=USER, headers={},
    ))
    assert status == 200
    return str(body)


def _scripts(html: str):
    return re.findall(r"<script>(.*?)</script>", html, re.S)


def _any_page(path: str) -> str:
    """Hub pages plus the bootstrap deploy form — every served page with
    inline JS goes through the same structural audit."""
    if path == "bootstrap:/":
        from kubeflow_tpu.controlplane.bootstrap import _deploy_page

        return _deploy_page()
    return _page(path)


class TestStaticSinkAudit:
    """Structural XSS guarantee: no template interpolation reaches the
    DOM unescaped."""

    # spark() is the one helper allowed to produce markup: its output is
    # built solely from toFixed() numerals and esc() — both audited here
    # since its body lives in the same script.
    ALLOWED = re.compile(
        r"^\s*(esc|encodeURIComponent|spark)\s*\(|\.toFixed\(\d+\)\s*$"
    )

    @pytest.mark.parametrize("path", ["/", "/spawner", "bootstrap:/"])
    def test_every_interpolation_is_escaped(self, path):
        html = _any_page(path)
        scripts = _scripts(html)
        assert scripts, "page must inline its script"
        checked = 0
        for script in scripts:
            for m in re.finditer(r"\$\{([^{}]+)\}", script):
                expr = m.group(1)
                assert self.ALLOWED.search(expr), (
                    f"unescaped interpolation in {path} page script: "
                    f"${{{expr}}} — wrap in esc() (DOM) or "
                    f"encodeURIComponent() (URL)"
                )
                checked += 1
        assert checked >= 5     # the audit actually saw the real sinks

    def test_esc_covers_the_html_metacharacters(self):
        html = _page("/")
        (script,) = _scripts(html)[:1]
        m = re.search(
            r"function esc\(s\)\s*{\s*return String\(s\)\.replace\("
            r"/\[(.*?)\]/g", script)
        assert m, "esc() definition changed — update this audit"
        cls = m.group(1)
        for ch in ["&", "<", ">", '"']:
            assert ch in cls, f"esc() must escape {ch!r}"
        assert "'" in cls or "\\'" in cls
        # the replacement map carries the right entities
        for entity in ("&amp;", "&lt;", "&gt;", "&quot;", "&#39;"):
            assert entity in script

    def test_delete_buttons_use_dataset_not_inline_js(self):
        """Event delegation contract: no inline onclick strings built from
        user data (the classic injection that esc() alone cannot fix)."""
        html = _page("/spawner")
        script = "".join(_scripts(html))
        assert 'data-name="${esc(n.name)}"' in script
        assert "onclick=\"" not in script.replace('b.onclick', '')




# ---------------------------------------------------------------------------
# Tier 2: real execution. MicroBrowser + minijs — the vendored JS runtime —
# fetch the served page over HTTP and run its actual inline script against
# the live REST surface. Reference analogue: testing/test_jwa.py:32-423
# (Selenium spawn/delete), centraldashboard/test/e2e.test.ts (puppeteer).

from kubeflow_tpu.webapps.browser import MicroBrowser
from kubeflow_tpu.webapps.minijs import JSError

PAYLOAD = '<img src=x onerror=alert(1)>'


class TestRealPageExecution:
    @pytest.fixture()
    def stack(self):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                              spec=ProfileSpec(owner=USER)))
        pf.reconcile()
        pf.manager.start()
        hub = central_hub(pf.api, pf.dashboard, pf.jwa)
        srv = JsonHttpServer(hub, port=0).start()
        yield pf, srv
        srv.stop()
        pf.manager.stop()

    def _browser(self, srv) -> MicroBrowser:
        return MicroBrowser(f"http://127.0.0.1:{srv.port}",
                            user_header=USER_HEADER, user=USER)

    def test_spawner_create_list_delete_roundtrip(self, stack):
        """The REAL page script drives create -> list -> delete end to end:
        init() populated the pickers from /api/config, the submit handler
        POSTed, refresh() re-rendered, the delegation-bound delete button
        DELETEd."""
        _, srv = stack
        b = self._browser(srv).open("/spawner")
        lst = b.element("list")
        assert "<table" in lst.innerHTML, lst.innerHTML

        # init() populated the image picker from /api/config and select
        # semantics chose the first option.
        assert b.element("image").value, "image picker never populated"

        b.set_value("name", "jsdrive")
        b.submit("spawn")
        assert ">jsdrive<" in lst.innerHTML, lst.innerHTML

        b.click_delete("list", "jsdrive")
        assert ">jsdrive<" not in lst.innerHTML, lst.innerHTML

    def test_spawner_xss_payload_inert_via_executed_esc(self, stack):
        """A resource name that is an XSS payload must come back through
        the EXECUTED esc() as inert text. The payload bypasses the JWA's
        own DNS-1123 validation by being created directly on the API
        server (the stored-XSS vector: the page renders names it did not
        create)."""
        pf, srv = stack
        from kubeflow_tpu.controlplane.api.types import (
            Notebook,
            NotebookSpec,
        )

        pf.api.create(Notebook(
            metadata=ObjectMeta(name=PAYLOAD, namespace="alice"),
            spec=NotebookSpec(image="jupyter:latest")))
        b = self._browser(srv).open("/spawner")
        lst = b.element("list")
        html = lst.innerHTML
        assert "<img" not in html, f"payload reached innerHTML raw: {html}"
        assert "&lt;img src=x onerror=alert(1)&gt;" in html, html
        # The delegation button carries the raw name via dataset (that is
        # the XSS-safe channel) — delete through it.
        b.click_delete("list", PAYLOAD)
        assert "&lt;img" not in lst.innerHTML

    def test_spawner_submit_rejects_bad_name_via_server(self, stack):
        """Submitting an invalid name surfaces the server's DNS-1123
        rejection as a thrown api() error (the page's contract)."""
        _, srv = stack
        b = self._browser(srv).open("/spawner")
        b.set_value("name", PAYLOAD)
        with pytest.raises(JSError, match="name"):
            b.submit("spawn")

    def test_hub_contributor_add_and_tables(self, stack):
        """loadNs() rendered the namespace picker + resource tables; the
        addc submit handler POSTed and refresh() re-rendered the
        contributor list."""
        _, srv = stack
        b = self._browser(srv).open("/")
        assert "Signed in as " + USER in b.element("whoami").textContent
        assert b.element("ns").value == "alice"
        assert "<h3>Notebook</h3>" in b.element("resources").innerHTML

        b.set_value("cemail", "bob@example.com")
        b.submit("addc")
        assert "bob@example.com" in b.element("contributors").textContent

    def test_hub_needs_workgroup_path(self, stack):
        """A caller with no namespaces gets the create-workgroup button;
        clicking it POSTs and reloads."""
        _, srv = stack
        b = MicroBrowser(f"http://127.0.0.1:{srv.port}",
                         user_header=USER_HEADER,
                         user="newbie@example.com").open("/")
        res = b.element("resources")
        assert "No workgroup yet" in res.innerHTML
        mkwg = b.element("mkwg")
        assert callable(mkwg.onclick)
        mkwg.onclick()
        assert b.location.reloaded == 1
        # The workgroup now exists, but the profile-controller reconciles
        # the new namespace's authz on a background thread — poll the
        # reload like a user mashing F5 until the page stops 403ing.
        import time

        deadline = time.monotonic() + 10
        while True:
            try:
                b2 = MicroBrowser(f"http://127.0.0.1:{srv.port}",
                                  user_header=USER_HEADER,
                                  user="newbie@example.com").open("/")
                break
            except JSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        assert b2.element("ns").value == "newbie"


class TestDeployFormExecution:
    """The click-to-deploy page (controlplane/bootstrap.py) — form submit
    wiring through the REAL script against a live DeploymentServer."""

    @pytest.fixture()
    def server(self, tmp_path):
        from kubeflow_tpu.controlplane.bootstrap import DeploymentServer

        srv = DeploymentServer(state_dir=str(tmp_path))
        srv.start()
        yield srv
        srv.stop()

    def _wait_phase(self, b, name, phase, tries=100):
        import time

        for _ in range(tries):
            b.call("refresh")
            if f">{phase}<" in b.element("list").innerHTML:
                return
            time.sleep(0.05)
        raise AssertionError(
            f"{name} never reached {phase}: {b.element('list').innerHTML}")

    def test_deploy_create_and_delete(self, server):
        b = MicroBrowser(f"http://127.0.0.1:{server.port}").open("/")
        # The submit handler collects the component checkboxes via
        # document.querySelectorAll and POSTs the typed spec.
        b.set_value("name", "jsdeploy")
        b.set_value("slice", "v5e-16")
        b.submit("deploy")
        assert b.element("err").textContent == ""
        self._wait_phase(b, "jsdeploy", "Ready")
        assert ">jsdeploy<" in b.element("list").innerHTML

        b.click_delete("list", "jsdeploy")
        b.call("refresh")
        assert ">jsdeploy<" not in b.element("list").innerHTML

    def test_deploy_error_path_renders_not_throws(self, server):
        """A bad name is shown via showErr() — the handler catches it."""
        b = MicroBrowser(f"http://127.0.0.1:{server.port}").open("/")
        b.set_value("name", "Bad/Name")
        b.submit("deploy")   # must NOT raise: the page catches api errors
        assert b.element("err").textContent != ""


class TestExecutedXssPolyglots:
    """Stored-XSS polyglot battery through the EXECUTED pipeline: every
    payload is created directly on the API server (bypassing JWA's name
    validation — the stored vector) and must come back inert through the
    real page script's esc()."""

    PAYLOADS = [
        '"><svg onload=alert(1)>',
        "'onmouseover='alert(1)",
        '<img src=x onerror=alert(1)>',
        '&lt;already-escaped&gt;<b>',
        '<script>alert(1)</script>',
    ]

    @pytest.fixture()
    def stack(self):
        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                              spec=ProfileSpec(owner=USER)))
        pf.reconcile()
        hub = central_hub(pf.api, pf.dashboard, pf.jwa)
        srv = JsonHttpServer(hub, port=0).start()
        yield pf, srv
        srv.stop()

    def test_all_polyglots_inert_and_deletable(self, stack):
        from kubeflow_tpu.controlplane.api.types import (
            Notebook,
            NotebookSpec,
        )

        pf, srv = stack
        for i, payload in enumerate(self.PAYLOADS):
            pf.api.create(Notebook(
                metadata=ObjectMeta(name=payload, namespace="alice"),
                spec=NotebookSpec(image=f"img-{i}:latest")))
        b = MicroBrowser(f"http://127.0.0.1:{srv.port}",
                         user_header=USER_HEADER, user=USER).open("/spawner")
        html = b.element("list").innerHTML
        # No raw executable sinks survive (the '&lt;already-escaped&gt;'
        # payload must be DOUBLE-escaped — rendering stored text verbatim
        # would un-escape it).
        assert "<svg" not in html and "<script" not in html
        assert "<img src=x" not in html
        assert "onmouseover='alert" not in html
        assert "&amp;lt;already-escaped&amp;gt;" in html
        # Attribute context: every delete button's TAG must have exactly
        # the expected shape — an attribute breakout would add attributes
        # or truncate the quoted value.
        import re as _re

        tags = _re.findall(r'<button class="del"[^>]*>', html)
        assert len(tags) == len(self.PAYLOADS)
        for tag in tags:
            assert _re.fullmatch(
                r'<button class="del" data-name="[^"<>]*">', tag), tag
        # Every payload row is deletable through the delegation path.
        for payload in self.PAYLOADS:
            b.click_delete("list", payload)
        final = b.element("list").innerHTML
        for i in range(len(self.PAYLOADS)):
            assert f"img-{i}" not in final, final


class TestExecutedMetricsPanel:
    """loadMetrics() + spark() through the real script against a live
    MetricsService — the one audit-whitelisted markup helper (spark)
    executes for real."""

    def test_sparkline_table_renders(self):
        from kubeflow_tpu.webapps.metrics import (
            MetricsService,
            TimeSeriesStore,
        )

        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                              spec=ProfileSpec(owner=USER)))
        pf.reconcile()
        store = TimeSeriesStore()
        for i in range(8):
            store.record("tokens_per_sec", 1000.0 + 50 * i,
                         labels=(("job", "pretrain"),))
        hub = central_hub(pf.api, pf.dashboard, pf.jwa,
                          metrics_service=MetricsService(store))
        srv = JsonHttpServer(hub, port=0).start()
        try:
            b = MicroBrowser(f"http://127.0.0.1:{srv.port}",
                             user_header=USER_HEADER, user=USER).open("/")
            html = b.element("metrics").innerHTML
            assert "tokens_per_sec{job=pretrain}" in html
            assert "<svg" in html and "<polyline" in html
            assert "1350" in html    # latest value via toPrecision(4)
        finally:
            srv.stop()
