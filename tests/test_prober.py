"""Availability prober: the metric-collector equivalent
(kubeflow-readiness.py:20-37 — endpoint probe -> 0/1 availability gauge)."""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.controlplane.prober import (
    AvailabilityProber,
    heartbeat_target,
    http_target,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def _http_server(status=200):
    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(status)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestProber:
    def test_http_target_up_down(self):
        srv = _http_server()
        url = f"http://127.0.0.1:{srv.server_address[1]}/healthz"
        reg = MetricsRegistry()
        prober = AvailabilityProber({"web": http_target(url)}, reg)
        assert prober.probe() is True
        assert "kftpu_availability 1" in reg.render()

        srv.shutdown()
        assert prober.probe() is False
        rendered = reg.render()
        assert "kftpu_availability 0" in rendered
        assert "kftpu_component_up_web 0" in rendered

    def test_500_is_down(self):
        srv = _http_server(status=503)
        url = f"http://127.0.0.1:{srv.server_address[1]}/healthz"
        prober = AvailabilityProber(
            {"web": http_target(url)}, MetricsRegistry()
        )
        assert prober.probe() is False
        srv.shutdown()

    def test_add_target_races_probe_loop(self):
        """add_target mutates the target dict while probe() iterates it on
        the background thread; without the snapshot+lock this raised
        'dictionary changed size during iteration' and killed the loop."""
        reg = MetricsRegistry()
        prober = AvailabilityProber({"seed": lambda: True}, reg)
        stop = threading.Event()
        errors = []

        def register_many():
            try:
                for i in range(300):
                    prober.add_target(f"t{i}", lambda: True, reg)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                stop.set()

        t = threading.Thread(target=register_many)
        t.start()
        try:
            while not stop.is_set():
                assert prober.probe() is True
        finally:
            t.join(timeout=10)
        assert not errors
        assert prober.probe() is True
        assert "kftpu_component_up_t299" in reg.render()

    def test_heartbeat_target_staleness(self):
        reg = MetricsRegistry()
        hb = reg.heartbeat("testctl")
        probe = heartbeat_target(hb, max_age_s=0.2)
        assert probe() is False           # never beat
        hb.beat()
        assert probe() is True
        time.sleep(0.3)
        assert probe() is False           # wedged loop

    def test_raising_probe_is_down_not_fatal(self):
        def boom():
            raise RuntimeError("probe exploded")

        prober = AvailabilityProber({"bad": boom}, MetricsRegistry())
        assert prober.probe() is False

    def test_platform_component_exports_availability(self):
        from kubeflow_tpu.controlplane.platform import Platform

        platform = Platform()
        platform.apply_config(_default_config())
        platform.reconcile()
        rendered = platform.registry.render()
        assert "kftpu_availability 1" in rendered
        assert "kftpu_component_up_kfam 1" in rendered


def _default_config():
    from kubeflow_tpu.controlplane.api.meta import ObjectMeta
    from kubeflow_tpu.controlplane.api.types import PlatformConfig

    return PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu"))


class TestControllerTarget:
    def test_wedged_loop_down_idle_up(self):
        from kubeflow_tpu.controlplane.prober import controller_target
        from kubeflow_tpu.controlplane.runtime import (
            ControllerManager,
            InMemoryApiServer,
        )
        from kubeflow_tpu.controlplane.controllers import NotebookController

        api = InMemoryApiServer()
        mgr = ControllerManager(api)
        reg = MetricsRegistry()
        ctl = NotebookController(api, reg)
        mgr.register(ctl)
        probe = controller_target(mgr, ctl, max_age_s=0.2)

        assert probe() is True            # idle, never beat: healthy
        ctl.heartbeat.beat()
        assert probe() is True            # fresh beat
        # Work arrives but the loop never runs (wedge): stale + pending.
        from kubeflow_tpu.controlplane.api import Notebook, NotebookSpec, ObjectMeta

        api.create(Notebook(metadata=ObjectMeta(name="n", namespace="ns"),
                            spec=NotebookSpec()))
        time.sleep(0.3)
        assert probe() is False
        # Loop drains -> healthy again.
        mgr.run_until_idle()
        assert probe() is True
