"""CachedReader (informer read cache) + ControllerManager watch-queue
lifecycle (ISSUE 3 satellites): reads served from the watch stream, chaos
injection staying ahead of the cache, and unregister/close releasing the
watch queues that used to leak from discarded managers."""

import pytest

from kubeflow_tpu.chaos import ChaosApiServer, FaultSpec, TransientApiError
from kubeflow_tpu.controlplane.api import (
    ObjectMeta,
    Pod,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.runtime import (
    CachedReader,
    Controller,
    ControllerManager,
    InMemoryApiServer,
    NotFoundError,
    Result,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def _job(name="j1", ns="u", labels=None):
    j = TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
               spec=TpuJobSpec())
    j.metadata.labels = dict(labels or {})
    return j


class TestCachedReader:
    def _reader(self, api=None):
        api = api or InMemoryApiServer(registry=MetricsRegistry())
        reader = CachedReader(api)
        reader.watch_kind("TpuJob")
        return api, reader

    def test_serves_reads_from_watch_stream(self):
        api, reader = self._reader()
        api.create(_job("a"))
        api.create(_job("b", labels={"team": "x"}))
        assert [o.metadata.name for o in reader.list("TpuJob", "u")] == \
            ["a", "b"]
        assert [o.metadata.name
                for o in reader.list("TpuJob", "u",
                                     label_selector={"team": "x"})] == ["b"]
        assert reader.get("TpuJob", "a", "u").metadata.name == "a"

    def test_cache_is_zero_copy_over_store_snapshots(self):
        api, reader = self._reader()
        api.create(_job("a"))
        assert reader.list("TpuJob", "u", copy=False)[0] is \
            api.get("TpuJob", "a", "u", copy=False)
        # The default (copy=True) hands out a private, mutate-safe object —
        # the same safe default as every API-server implementation.
        mine = reader.get("TpuJob", "a", "u")
        mine.spec.max_restarts = 9
        assert api.get("TpuJob", "a", "u").spec.max_restarts == 3

    def test_follows_updates_and_deletes(self):
        api, reader = self._reader()
        api.create(_job("a"))
        assert reader.try_get("TpuJob", "a", "u") is not None
        live = api.get("TpuJob", "a", "u")
        live.status.phase = "Running"
        api.update_status(live)
        assert reader.get("TpuJob", "a", "u").status.phase == "Running"
        api.delete("TpuJob", "a", "u")
        assert reader.try_get("TpuJob", "a", "u") is None
        with pytest.raises(NotFoundError):
            reader.get("TpuJob", "a", "u")

    def test_unwatched_kind_falls_through_to_api(self):
        api, reader = self._reader()
        api.create(Pod(metadata=ObjectMeta(name="p", namespace="u")))
        assert not reader.caches("Pod")
        assert [p.metadata.name for p in reader.list("Pod", "u")] == ["p"]

    def test_chaos_injects_ahead_of_the_cache(self):
        """The chaos wrapper sits between the store and the reader: cached
        reads are informer reads (never injected, like try_get), while
        fall-through reads of unwatched kinds still roll the dice."""
        inner = InMemoryApiServer(registry=MetricsRegistry())
        chaos = ChaosApiServer(
            inner, seed=0,
            rules={"list:Pod": FaultSpec(transient_rate=1.0)},
            registry=MetricsRegistry(),
        )
        reader = CachedReader(chaos)
        reader.watch_kind("TpuJob")
        inner.create(_job("a"))
        assert reader.list("TpuJob", "u")          # cached: no injection
        with pytest.raises(TransientApiError):
            reader.list("Pod", "u")                # fall-through: injected

    def test_close_releases_watches(self):
        api, reader = self._reader()
        assert len(api._watchers) == 1
        reader.close()
        assert len(api._watchers) == 0

    def test_concurrent_writers_cannot_wedge_the_cache_stale(self):
        """Watch events are emitted under the store lock, so delivery order
        is write order and a last-wins cache always converges to the live
        state — racing writers used to be able to enqueue their events
        inverted and leave the cache stale forever."""
        import threading

        api, reader = self._reader()
        api.create(_job("a"))

        def hammer(n):
            for _ in range(200):
                try:
                    live = api.get("TpuJob", "a", "u")
                    live.status.phase = f"w{n}"
                    api.update_status(live)
                except Exception:
                    pass

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        live = api.get("TpuJob", "a", "u", copy=False)
        cached = reader.get("TpuJob", "a", "u", copy=False)
        assert cached is live
        assert cached.metadata.resource_version == \
            live.metadata.resource_version

    def test_reads_not_serialized_behind_unrelated_kind_drain(self):
        """ISSUE 5 satellite: the drain is split per kind. A reader of
        TpuJob must complete even while another thread holds Pod's drain
        (the old sync() drained EVERY subscription under one lock on
        every read — an unrelated slow drain serialized all readers)."""
        api, reader = self._reader()
        reader.watch_kind("Pod")
        api.create(_job("a"))
        api.create(Pod(metadata=ObjectMeta(name="p", namespace="u")))
        # Simulate a stuck/slow Pod drain: hold its drain lock.
        assert reader._drain_locks["Pod"].acquire(timeout=1)
        try:
            assert reader.get("TpuJob", "a", "u").metadata.name == "a"
            assert [o.metadata.name
                    for o in reader.list("TpuJob", "u")] == ["a"]
        finally:
            reader._drain_locks["Pod"].release()
        # Pod reads catch up once the drain frees.
        assert reader.get("Pod", "p", "u").metadata.name == "p"

    def test_concurrent_readers_of_distinct_kinds(self):
        """Per-kind drains + short store-lock holds: concurrent readers
        over different kinds converge on the live state under a write
        storm (the worker-pool read pattern)."""
        import threading

        api, reader = self._reader()
        reader.watch_kind("Pod")
        api.create(_job("a"))
        api.create(Pod(metadata=ObjectMeta(name="p", namespace="u")))
        errors = []

        def read_loop(kind, name):
            try:
                for _ in range(200):
                    assert reader.get(kind, name, "u",
                                      copy=False) is not None
            except Exception as e:          # pragma: no cover - fail path
                errors.append(e)

        def write_loop():
            for i in range(200):
                live = api.get("TpuJob", "a", "u")
                live.status.phase = f"w{i}"
                api.update_status(live)

        threads = [threading.Thread(target=read_loop, args=("TpuJob", "a")),
                   threading.Thread(target=read_loop, args=("Pod", "p")),
                   threading.Thread(target=write_loop)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == []
        live = api.get("TpuJob", "a", "u", copy=False)
        assert reader.get("TpuJob", "a", "u", copy=False) is live


class TestBookmarkResync:
    """ISSUE 6 satellite: watch bookmarks + resume. A restarted reader
    seeded from persisted state resyncs from its last bookmarked resource
    version — the server replays only the missed delta, never an O(store)
    ADDED replay and never a copying relist (gated on the deterministic
    ``api.replayed`` / ``api.copied`` tallies)."""

    def test_initial_bookmark_carries_snapshot_rv(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        reader = CachedReader(api)
        reader.watch_kind("TpuJob")
        assert reader.resume_rv("TpuJob") == api._rv

    def test_periodic_bookmarks_advance_the_watermark(self):
        api = InMemoryApiServer(registry=MetricsRegistry(),
                                bookmark_interval=3)
        reader = CachedReader(api)
        reader.watch_kind("TpuJob")
        # Writes of an UNWATCHED kind still advance the store version;
        # only the periodic bookmark can tell the TpuJob reader so.
        for i in range(6):
            api.create(Pod(metadata=ObjectMeta(name=f"p{i}",
                                               namespace="u")))
        assert reader.resume_rv("TpuJob") == api._rv

    def test_restarted_reader_resyncs_without_relist(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        for i in range(40):
            api.create(_job(f"j{i:02d}"))
        reader = CachedReader(api)
        reader.watch_kind("TpuJob")
        rv = reader.resume_rv("TpuJob")
        seed = tuple(reader.list("TpuJob", copy=False))
        reader.close()                         # the "crash"

        # Writes landing while the reader is down — the missed delta.
        api.create(_job("late"))
        live = api.get("TpuJob", "j00", "u")
        live.status.phase = "Running"
        api.update_status(live)
        api.delete("TpuJob", "j01", "u")

        full_before = api.replayed.get("full", 0)
        resume_before = api.replayed.get("resume", 0)
        copied_before = dict(api.copied)
        restarted = CachedReader(api)
        restarted.watch_kind("TpuJob", resume_rv=rv, seed=seed)
        # No O(store) replay, and no copying relist anywhere on the path.
        assert api.replayed.get("full", 0) == full_before
        assert api.copied == copied_before
        # Exactly the three missed events were replayed.
        assert api.replayed.get("resume", 0) - resume_before == 3
        # ... and the reader converged to the live world.
        assert restarted.get("TpuJob", "late", "u",
                             copy=False) is not None
        assert restarted.get("TpuJob", "j00", "u",
                             copy=False).status.phase == "Running"
        assert restarted.try_get("TpuJob", "j01", "u") is None
        assert len(restarted.list("TpuJob", copy=False)) == 40
        assert restarted.resume_rv("TpuJob") == api._rv

    def test_resume_too_old_falls_back_to_full_replay(self):
        """A resume point the bounded event log no longer covers must NOT
        silently lose events — the server falls back to the full replay."""
        api = InMemoryApiServer(registry=MetricsRegistry(),
                                event_log_size=4)
        api.create(_job("old"))
        rv = api._rv
        for i in range(10):                      # evicts rv+1 from the log
            api.create(_job(f"j{i}"))
        restarted = CachedReader(api)
        full_before = api.replayed.get("full", 0)
        restarted.watch_kind("TpuJob", resume_rv=rv)
        assert api.replayed.get("full", 0) - full_before == 11
        assert len(restarted.list("TpuJob", copy=False)) == 11


class _Echo(Controller):
    NAME = "echo-cache"
    WATCH_KINDS = ("TpuJob",)

    def reconcile(self, namespace, name):
        return Result()


class TestManagerLifecycle:
    def test_register_wires_shared_reader(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        mgr = ControllerManager(api, MetricsRegistry())
        ctl = _Echo(api, registry=MetricsRegistry())
        assert ctl.reader is api                   # pre-registration default
        mgr.register(ctl)
        assert isinstance(ctl.reader, CachedReader)
        api.create(_job("a"))
        assert ctl.reader.get("TpuJob", "a", "u").metadata.name == "a"
        mgr.close()

    def test_close_releases_every_watch_queue(self):
        """The leak this PR fixes: a discarded manager's registered watches
        kept every future event alive forever."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        mgr = ControllerManager(api, MetricsRegistry())
        mgr.register(_Echo(api, registry=MetricsRegistry()))
        # 1 manager queue + 1 shared-cache subscription for the kind.
        assert len(api._watchers) == 2
        mgr.close()
        assert len(api._watchers) == 0
        assert mgr.controllers == []

    def test_unregister_single_controller(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        mgr = ControllerManager(api, MetricsRegistry())
        a = _Echo(api, registry=MetricsRegistry())

        class _Other(_Echo):
            NAME = "other"
            WATCH_KINDS = ("Pod",)

        b = _Other(api, registry=MetricsRegistry())
        mgr.register(a)
        mgr.register(b)
        before = len(api._watchers)
        mgr.unregister(a)
        assert len(api._watchers) == before - 1
        assert mgr.controllers == [b]
        assert a.reader is api                     # reader unwired
        api.create(_job("x"))
        mgr.run_until_idle()                       # only b's queues pumped
        mgr.close()

    def test_kubectl_style_backend_skips_cache(self):
        """A backend without synchronous watches keeps reader == api."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        mgr = ControllerManager(api, MetricsRegistry(), use_cache=False)
        ctl = _Echo(api, registry=MetricsRegistry())
        mgr.register(ctl)
        assert ctl.reader is api
        mgr.close()
