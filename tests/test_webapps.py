"""Functional tests for the L3 REST plane, entirely over HTTP.

Mirrors the reference's UI E2E (testing/test_jwa.py:32-423 drives login ->
namespace -> notebook create/delete through the live dashboard+JWA) minus
Selenium: the trusted identity header plays the role of the logged-in
session, and assertions hit the same REST routes the Angular/Polymer
frontends call (base_app.py:22-175, api_workgroup.ts:247-381).
"""

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta, Profile, ProfileSpec
from kubeflow_tpu.controlplane.api.types import PodDefault, PodDefaultSpec
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.controlplane.api.types import PlatformConfig

HDR = "x-goog-authenticated-user-email"
ADMIN = "root@corp.com"
ALICE = "alice@corp.com"
BOB = "bob@corp.com"


def _req(port, method, path, caller=None, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    if caller:
        req.add_header(HDR, caller)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture()
def platform():
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    # Bootstrap a cluster admin (as the installer would).
    pf.api.create(Profile(
        metadata=ObjectMeta(name="admin-ns", labels={"cluster-admin": "true"}),
        spec=ProfileSpec(owner=ADMIN),
    ))
    pf.reconcile()
    return pf


@pytest.fixture()
def servers(platform):
    jwa_srv = platform.jwa.serve()
    dash_srv = platform.dashboard.serve()
    yield platform, jwa_srv.port, dash_srv.port
    jwa_srv.stop()
    dash_srv.stop()


class TestOnboardingToNotebookFlow:
    """The full multi-user path: login header -> workgroup -> spawn a TPU
    notebook -> list -> delete, all over HTTP."""

    def test_end_to_end(self, servers):
        pf, jwa, dash = servers

        # 1. New user: no workgroup yet.
        code, out = _req(dash, "GET", "/api/workgroup/exists", ALICE)
        assert code == 200 and out["hasWorkgroup"] is False

        # 2. Onboard (profile -> namespace via profile controller).
        code, out = _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        assert code == 200, out
        pf.reconcile()
        code, out = _req(dash, "GET", "/api/workgroup/exists", ALICE)
        assert out["hasWorkgroup"] is True
        ns = "alice"

        # 3. Spawner config offers TPU slices instead of GPU vendors.
        code, out = _req(jwa, "GET", "/api/config")
        assert code == 200
        assert "v5e-8" in out["config"]["tpuSlices"]
        assert all(s.endswith(("-1", "-4", "-8")) or "-" in s
                   for s in out["config"]["tpuSlices"])

        # 4. Spawn a TPU notebook in her namespace.
        code, out = _req(jwa, "POST", f"/api/namespaces/{ns}/notebooks",
                         ALICE, {"name": "nb1", "tpuSlice": "v5e-8",
                                 "cpu": "4", "memory": "8Gi"})
        assert code == 200, out
        pf.reconcile()

        # 5. List: the notebook is there, with derived status + events.
        code, out = _req(jwa, "GET", f"/api/namespaces/{ns}/notebooks", ALICE)
        assert code == 200
        nbs = out["notebooks"]
        assert len(nbs) == 1 and nbs[0]["name"] == "nb1"
        assert nbs[0]["tpuSlice"] == "v5e-8"
        assert nbs[0]["owner"] == ALICE
        assert nbs[0]["status"]["phase"] in ("running", "waiting")

        # The controller actually provisioned the pod + service.
        assert pf.api.try_get("Pod", "nb1-0", ns) is not None

        # 6. Delete over HTTP; resources cascade.
        code, out = _req(jwa, "DELETE",
                         f"/api/namespaces/{ns}/notebooks/nb1", ALICE)
        assert code == 200
        pf.reconcile()
        code, out = _req(jwa, "GET", f"/api/namespaces/{ns}/notebooks", ALICE)
        assert out["notebooks"] == []
        assert pf.api.try_get("Pod", "nb1-0", ns) is None


class TestAuthzBoundaries:
    def test_unauthenticated_gets_401(self, servers):
        _, jwa, dash = servers
        code, _ = _req(jwa, "GET", "/api/namespaces/admin-ns/notebooks")
        assert code == 401
        code, out = _req(dash, "GET", "/api/workgroup/exists")
        assert code == 200 and out["hasAuth"] is False

    def test_cross_namespace_denied(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        # Bob cannot list or create in alice's namespace.
        code, _ = _req(jwa, "GET", "/api/namespaces/alice/notebooks", BOB)
        assert code == 403
        code, _ = _req(jwa, "POST", "/api/namespaces/alice/notebooks", BOB,
                       {"name": "intruder"})
        assert code == 403
        # Cluster admin can.
        code, _ = _req(jwa, "GET", "/api/namespaces/alice/notebooks", ADMIN)
        assert code == 200

    def test_contributor_gains_access(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        code, out = _req(dash, "POST",
                         "/api/workgroup/add-contributor/alice", ALICE,
                         {"contributor": BOB})
        assert code == 200 and BOB in out
        code, _ = _req(jwa, "POST", "/api/namespaces/alice/notebooks", BOB,
                       {"name": "bobs-nb"})
        assert code == 200
        # Remove: access revoked.
        code, out = _req(dash, "DELETE",
                         "/api/workgroup/remove-contributor/alice", ALICE,
                         {"contributor": BOB})
        assert code == 200 and BOB not in out
        code, _ = _req(jwa, "GET", "/api/namespaces/alice/notebooks", BOB)
        assert code == 403


class TestJwaValidation:
    def test_multi_host_slice_rejected(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        code, out = _req(jwa, "POST", "/api/namespaces/alice/notebooks",
                         ALICE, {"name": "big", "tpuSlice": "v5e-16"})
        assert code == 400
        assert "hosts" in out["error"]

    def test_unknown_slice_rejected(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        code, out = _req(jwa, "POST", "/api/namespaces/alice/notebooks",
                         ALICE, {"name": "x", "tpuSlice": "h100-8"})
        assert code == 400

    def test_duplicate_conflicts(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        _req(jwa, "POST", "/api/namespaces/alice/notebooks", ALICE,
             {"name": "nb"})
        code, _ = _req(jwa, "POST", "/api/namespaces/alice/notebooks", ALICE,
                       {"name": "nb"})
        assert code == 409

    def test_poddefault_listing(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        pf.api.create(PodDefault(
            metadata=ObjectMeta(name="gcs-creds", namespace="alice"),
            spec=PodDefaultSpec(selector={"inject-gcs": "true"},
                                desc="Mount GCS credentials"),
        ))
        code, out = _req(jwa, "GET", "/api/namespaces/alice/poddefaults",
                         ALICE)
        assert code == 200
        assert out["poddefaults"] == [
            {"label": "inject-gcs", "desc": "Mount GCS credentials"}
        ]


class TestDashboardViews:
    def test_env_info_and_all_namespaces(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        _req(dash, "POST", "/api/workgroup/add-contributor/alice", ALICE,
             {"contributor": BOB})

        code, out = _req(dash, "GET", "/api/workgroup/env-info", ALICE)
        assert code == 200
        assert out["isClusterAdmin"] is False
        assert {"namespace": "alice", "role": "admin"} in out["namespaces"]
        assert "kfam" in out["platform"]["components"]

        code, out = _req(dash, "GET",
                         "/api/workgroup/get-all-namespaces", ADMIN)
        assert code == 200
        rows = {r[0]: r for r in out}
        assert rows["alice"][1] == ALICE
        assert BOB in rows["alice"][2]

    def test_nuke_self(self, servers):
        pf, jwa, dash = servers
        _req(dash, "POST", "/api/workgroup/create", ALICE, {})
        pf.reconcile()
        code, _ = _req(dash, "DELETE", "/api/workgroup/nuke-self", ALICE)
        assert code == 200
        pf.reconcile()
        assert pf.api.try_get("Profile", "alice") is None
        assert pf.api.try_get("Namespace", "alice") is None
