"""HPO tests: search space / suggestion algorithms, the StudyJob
controller's trial lifecycle (katib surface, reference:
testing/katib_studyjob_test.py:39-216), and a real ViT-tiny sweep on the
virtual 8-device mesh (compute path)."""

import json
import math

import pytest

from kubeflow_tpu.controlplane.api.core import EnvVar
from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    MeshAxesSpec,
    StudyJob,
    StudyJobSpec,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.controllers import (
    FakeKubelet,
    StudyJobController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.hpo import (
    ParameterSpec,
    budget,
    grid,
    run_study,
    sample,
    suggest,
    validate_space,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry

SPACE = [
    ParameterSpec(name="learning_rate", type="double",
                  min=1e-4, max=1e-2, log_scale=True),
    ParameterSpec(name="weight_decay", type="double", min=0.0, max=0.3),
    ParameterSpec(name="warmup_steps", type="int", min=10, max=100),
    ParameterSpec(name="attn", type="categorical",
                  values=["full", "ring"]),
]


# ---------------------------------------------------------------- space


class TestSpace:
    def test_validate_rejects_bad_spaces(self):
        with pytest.raises(ValueError):
            validate_space([ParameterSpec(name="x", min=1.0, max=1.0)])
        with pytest.raises(ValueError):
            validate_space([ParameterSpec(name="x", type="categorical")])
        with pytest.raises(ValueError):
            validate_space([ParameterSpec(name="x", min=0.0, max=1.0,
                                          log_scale=True)])
        with pytest.raises(ValueError):
            validate_space([
                ParameterSpec(name="x", min=0, max=1),
                ParameterSpec(name="x", min=0, max=1),
            ])

    def test_sample_deterministic_and_in_bounds(self):
        for i in range(20):
            a = sample(SPACE, seed=7, index=i)
            b = sample(SPACE, seed=7, index=i)
            assert a == b, "same (seed, index) must reproduce"
            assert 1e-4 <= a["learning_rate"] <= 1e-2
            assert 0.0 <= a["weight_decay"] <= 0.3
            assert isinstance(a["warmup_steps"], int)
            assert 10 <= a["warmup_steps"] <= 100
            assert a["attn"] in ("full", "ring")
        assert sample(SPACE, 7, 0) != sample(SPACE, 7, 1)
        assert sample(SPACE, 7, 0) != sample(SPACE, 8, 0)

    def test_grid_cartesian(self):
        g = grid([
            ParameterSpec(name="lr", min=0.1, max=0.4, step=0.1),
            ParameterSpec(name="opt", type="categorical",
                          values=["adam", "sgd"]),
        ])
        assert len(g) == 8  # 4 lr values x 2 categories
        assert g[0] == {"lr": 0.1, "opt": "adam"}
        assert g[-1]["opt"] == "sgd"
        assert abs(g[-1]["lr"] - 0.4) < 1e-9

    def test_grid_points_log_scale(self):
        g = grid([ParameterSpec(name="lr", min=1e-4, max=1e-1,
                                grid_points=4, log_scale=True)])
        vals = [a["lr"] for a in g]
        assert len(vals) == 4
        ratios = [vals[i + 1] / vals[i] for i in range(3)]
        assert all(abs(r - 10.0) < 1e-6 for r in ratios), \
            "log grid must be geometric"

    def test_int_grid_dedupes(self):
        g = grid([ParameterSpec(name="k", type="int", min=1, max=2,
                                grid_points=5)])
        assert [a["k"] for a in g] == [1, 2]


# ------------------------------------------------------------- suggest


class TestSuggest:
    def test_grid_budget_caps_at_grid_size(self):
        params = [ParameterSpec(name="lr", min=0.1, max=0.2, step=0.1),
                  ParameterSpec(name="o", type="categorical",
                                values=["a", "b"])]
        assert budget(params, "grid", max_trials=100) == 4
        assert budget(params, "grid", max_trials=3) == 3
        assert budget(params, "random", max_trials=7) == 7

    def test_grid_size_matches_grid_without_materialising(self):
        from kubeflow_tpu.hpo.space import grid, grid_size
        params = [
            ParameterSpec(name="lr", min=1e-4, max=1e-1, grid_points=5,
                          log_scale=True),
            ParameterSpec(name="wd", min=0.0, max=0.2, grid_points=3),
            ParameterSpec(name="attn", type="categorical",
                          values=["full", "ring", "flash"]),
        ]
        assert grid_size(params) == len(grid(params)) == 45

    def test_grid_exhaustion_raises(self):
        params = [ParameterSpec(name="lr", min=0.1, max=0.2, step=0.1)]
        with pytest.raises(IndexError):
            suggest(params, "grid", 0, 99)

    def test_successive_halving_contracts_toward_best(self):
        params = [ParameterSpec(name="lr", type="double",
                                min=1e-4, max=1e-1, log_scale=True)]
        best_lr = 1e-3
        history = [
            {"parameters": {"lr": best_lr}, "objective": 0.1},
            {"parameters": {"lr": 5e-2}, "objective": 9.0},
            {"parameters": {"lr": 2e-4}, "objective": 5.0},
            {"parameters": {"lr": 8e-2}, "objective": 7.0},
        ]
        prop = suggest(params, "successive-halving", 0, 6, history)["lr"]
        base = sample(params, 0, 6)["lr"]
        # Proposal is the log-midpoint of (incumbent, fresh sample).
        assert abs(math.log(prop)
                   - 0.5 * (math.log(best_lr) + math.log(base))) < 1e-9

    def test_tpe_concentrates_near_good_region(self):
        """TPE proposals must land near the good cluster of history (the
        Parzen l(x) mixture), not uniformly over the range."""
        params = [ParameterSpec(name="lr", type="double",
                                min=1e-4, max=1e-1, log_scale=True)]
        # Good cluster around 1e-3; bad points far away.
        history = (
            [{"parameters": {"lr": 1e-3 * f}, "objective": 0.1 * f}
             for f in (0.8, 1.0, 1.25)]
            + [{"parameters": {"lr": v}, "objective": 5.0 + i}
               for i, v in enumerate((5e-2, 8e-2, 2e-4, 3e-2, 6e-2,
                                      9e-2, 1.5e-4, 4e-2, 7e-2))]
        )
        props = [suggest(params, "tpe", 0, i, history)["lr"]
                 for i in range(8, 40)]
        assert all(1e-4 <= v <= 1e-1 for v in props)
        # Median log-distance to the incumbent stays well inside the
        # 3-decade range (a uniform sampler's median distance is ~1.1
        # decades; the Parzen mixture's is bandwidth-sized).
        dists = sorted(abs(math.log10(v) - math.log10(1e-3))
                       for v in props)
        assert dists[len(dists) // 2] < 0.5, dists

    def test_tpe_categorical_prefers_good_choice(self):
        params = [ParameterSpec(name="opt", type="categorical",
                                values=["adamw", "lion", "sgd"])]
        history = (
            [{"parameters": {"opt": "lion"}, "objective": 0.1}] * 3
            + [{"parameters": {"opt": "adamw"}, "objective": 5.0}] * 5
            + [{"parameters": {"opt": "sgd"}, "objective": 6.0}] * 4
        )
        picks = [suggest(params, "tpe", 0, i, history)["opt"]
                 for i in range(8, 48)]
        assert picks.count("lion") > len(picks) / 2, picks

    def test_tpe_deterministic_and_startup_random(self):
        params = [ParameterSpec(name="x", type="double", min=0.0, max=1.0)]
        history = [{"parameters": {"x": 0.5}, "objective": 1.0}] * 6
        a = suggest(params, "tpe", 7, 20, history)
        b = suggest(params, "tpe", 7, 20, history)
        assert a == b
        # Below n_startup (or thin history) TPE IS the seeded random
        # stream — reconcile-replayable like every other algorithm.
        assert suggest(params, "tpe", 7, 3, history) == sample(params, 7, 3)

    def test_tpe_int_params_in_bounds(self):
        params = [ParameterSpec(name="bs", type="int", min=8, max=64)]
        history = (
            [{"parameters": {"bs": 16}, "objective": 0.1}] * 3
            + [{"parameters": {"bs": 56}, "objective": 9.0}] * 5
        )
        for i in range(8, 24):
            v = suggest(params, "tpe", 0, i, history)["bs"]
            assert isinstance(v, int) and 8 <= v <= 64

    def test_unknown_algorithm(self):
        with pytest.raises(ValueError):
            suggest(SPACE, "bayesian-magic", 0, 0)


# ---------------------------------------------- StudyJob controller


def make_hpo_world(*, outcome=None):
    """Platform world with TpuJob + StudyJob controllers and a FakeKubelet
    whose 'workload' reports loss = f(hparams) through the termination
    message — deterministic compute, real metric plumbing."""
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    mgr = ControllerManager(api)
    mgr.register(TpuJobController(api, reg))
    mgr.register(StudyJobController(api, reg))

    def termination(pod):
        env = {e.name: e.value for c in pod.spec.containers for e in c.env}
        hp = json.loads(env.get("KFTPU_HPARAMS", "{}"))
        # Quadratic bowl with known optimum at lr=3e-3.
        lr = float(hp.get("learning_rate", 1.0))
        loss = (math.log10(lr) - math.log10(3e-3)) ** 2
        return json.dumps({"loss": loss, "tokens_per_sec": 1000.0})

    kubelet = FakeKubelet(api, reg, outcome=outcome, termination=termination)
    mgr.register(kubelet)
    return api, mgr, kubelet


def _study(name="study", ns="team-a", **spec_kw):
    spec_kw.setdefault("parameters", [
        ParameterSpec(name="learning_rate", type="double",
                      min=1e-4, max=1e-1, log_scale=True),
        ParameterSpec(name="weight_decay", type="double", min=0.0, max=0.2),
    ])
    spec_kw.setdefault("trial", TpuJobSpec(
        slice_type="v5e-8", model="vit-tiny",
        mesh=MeshAxesSpec(dp=-1),
    ))
    return StudyJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=StudyJobSpec(**spec_kw),
    )


class TestStudyJobController:
    def test_parallelism_window_respected(self):
        # Trials never finish (outcome=None): the controller must hold at
        # exactly parallel_trials in flight and report condition=Running —
        # the condition the reference's katib test polls for.
        api, mgr, kubelet = make_hpo_world(outcome=None)
        api.create(_study(max_trials=6, parallel_trials=2))
        mgr.run_until_idle()
        kubelet.tick()
        mgr.run_until_idle()
        study = api.get("StudyJob", "study", "team-a")
        jobs = api.list("TpuJob", namespace="team-a")
        assert len(jobs) == 2
        assert study.status.condition == "Running"
        assert study.status.trials_running == 2

    def test_study_runs_to_completion_and_picks_best(self):
        api, mgr, kubelet = make_hpo_world(outcome=lambda name: "Succeeded")
        api.create(_study(max_trials=6, parallel_trials=2, seed=3))
        # Drive to completion: drain -> tick kubelet (pods run/succeed) ->
        # drain, until the study goes terminal.
        for _ in range(30):
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            study = api.get("StudyJob", "study", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
        assert study.status.condition == "Completed"
        assert study.status.trials_completed == 6
        assert len(study.status.trials) == 6
        # Best = argmin over the quadratic bowl the fake kubelet computes.
        vals = {t.name: t.objective_value for t in study.status.trials}
        assert all(v is not None for v in vals.values())
        expect = min(vals, key=vals.get)
        assert study.status.best_trial == expect
        assert study.status.best_objective == pytest.approx(vals[expect])
        assert "learning_rate" in study.status.best_parameters

    def test_tpe_study_beats_random_tail(self):
        """End-to-end TPE through the StudyJob controller on the fake
        kubelet's quadratic bowl (optimum lr=3e-3): post-startup TPE
        trials must average closer to the optimum than the startup
        (random) trials — history steering through real status plumbing."""
        api, mgr, kubelet = make_hpo_world(outcome=lambda name: "Succeeded")
        api.create(_study(max_trials=16, parallel_trials=2, seed=5,
                          algorithm="tpe"))
        for _ in range(80):
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            study = api.get("StudyJob", "study", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
        assert study.status.condition == "Completed"
        assert study.status.trials_completed == 16
        objs = [t.objective_value for t in study.status.trials]
        assert all(o is not None for o in objs)
        startup, steered = objs[:8], objs[8:]
        assert sum(steered) / len(steered) < sum(startup) / len(startup), (
            startup, steered)

    def test_grid_study_exact_budget(self):
        api, mgr, kubelet = make_hpo_world(outcome=lambda name: "Succeeded")
        api.create(_study(
            name="gridstudy",
            algorithm="grid", max_trials=100, parallel_trials=3,
            parameters=[
                ParameterSpec(name="learning_rate", min=1e-3, max=1e-2,
                              grid_points=2, log_scale=True),
                ParameterSpec(name="attn", type="categorical",
                              values=["full", "ring"]),
            ],
        ))
        for _ in range(20):
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            study = api.get("StudyJob", "gridstudy", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
        assert study.status.condition == "Completed"
        # 2 x 2 grid => exactly 4 trials despite max_trials=100.
        assert study.status.trials_completed == 4
        assert len(api.list("TpuJob", namespace="team-a")) == 4

    def test_deleted_trial_is_respawned(self):
        """A trial deleted out from under the study leaves an index hole;
        the spawn loop must refill it or the study can never reach its
        budget (it would hang in Running forever)."""
        api, mgr, kubelet = make_hpo_world(outcome=lambda name: "Succeeded")
        api.create(_study(max_trials=4, parallel_trials=4))
        mgr.run_until_idle()
        victim = StudyJobController.trial_name("study", 1)
        api.delete("TpuJob", victim, "team-a")
        mgr.run_until_idle()
        for _ in range(30):
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            study = api.get("StudyJob", "study", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
        assert study.status.condition == "Completed"
        assert study.status.trials_completed == 4
        assert {t.index for t in study.status.trials} == {0, 1, 2, 3}

    def test_foreign_job_name_conflict_fails_study(self):
        """A TpuJob squatting a trial name (without the study label) must
        fail the study, not leave it Running with phantom trials."""
        from kubeflow_tpu.controlplane.api.types import TpuJob, TpuJobSpec

        api, mgr, _ = make_hpo_world(outcome=None)
        api.create(TpuJob(
            metadata=ObjectMeta(
                name=StudyJobController.trial_name("study", 0),
                namespace="team-a",
            ),
            spec=TpuJobSpec(slice_type="v5e-8", model="vit-tiny"),
        ))
        api.create(_study(max_trials=2, parallel_trials=2))
        mgr.run_until_idle()
        study = api.get("StudyJob", "study", "team-a")
        assert study.status.condition == "Failed"
        reasons = [c.reason for c in study.status.conditions]
        assert "TrialNameConflict" in reasons

    def test_zero_parallelism_fails_study(self):
        api, mgr, _ = make_hpo_world(outcome=None)
        api.create(_study(max_trials=2, parallel_trials=0))
        mgr.run_until_idle()
        study = api.get("StudyJob", "study", "team-a")
        assert study.status.condition == "Failed"
        assert api.list("TpuJob", namespace="team-a") == []

    def test_trial_jobs_carry_hparams_and_owner(self):
        api, mgr, _ = make_hpo_world(outcome=None)
        api.create(_study(max_trials=2, parallel_trials=2))
        mgr.run_until_idle()
        jobs = api.list("TpuJob", namespace="team-a")
        assert len(jobs) == 2
        for j in jobs:
            env = {e.name: e.value for e in j.spec.env}
            hp = json.loads(env["KFTPU_HPARAMS"])
            assert set(hp) == {"learning_rate", "weight_decay"}
            assert j.metadata.owner_references[0].kind == "StudyJob"
            assert j.metadata.owner_references[0].name == "study"

    def test_all_trials_failed_marks_study_failed(self):
        api, mgr, kubelet = make_hpo_world(outcome=lambda name: "Failed")
        api.create(_study(max_trials=2, parallel_trials=2,
                          trial=TpuJobSpec(slice_type="v5e-8",
                                           model="vit-tiny",
                                           max_restarts=0)))
        for _ in range(20):
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            study = api.get("StudyJob", "study", "team-a")
            if study.status.condition in ("Completed", "Failed"):
                break
        assert study.status.condition == "Failed"
        assert study.status.trials_failed == 2
        assert study.status.best_trial == ""

    def test_invalid_space_fails_study(self):
        api, mgr, _ = make_hpo_world()
        api.create(_study(
            name="bad",
            parameters=[ParameterSpec(name="lr", min=2.0, max=1.0)],
        ))
        mgr.run_until_idle()
        study = api.get("StudyJob", "bad", "team-a")
        assert study.status.condition == "Failed"


# ------------------------------------------------- compute path (sweep)


class TestSweep:
    def test_run_study_best_and_isolation(self):
        def trial_fn(hp):
            if hp["flaky"] == "crash":
                raise RuntimeError("boom")
            return {"loss": (hp["x"] - 0.25) ** 2}

        res = run_study(
            [ParameterSpec(name="x", min=0.0, max=1.0, grid_points=5),
             ParameterSpec(name="flaky", type="categorical",
                           values=["ok", "crash"])],
            trial_fn, algorithm="grid", max_trials=0,
        )
        assert len(res.trials) == 10
        failed = [t for t in res.trials if t.objective is None]
        assert len(failed) == 5 and all("boom" in t.error for t in failed)
        assert res.best is not None
        assert res.best.parameters["x"] == pytest.approx(0.25)
        assert res.trials_per_hour > 0

    def test_vit_tiny_sweep_on_mesh(self, devices8):
        """The VERDICT-prescribed acceptance: sweep ViT-tiny over >=2
        hyperparameters with real training steps on the virtual mesh."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import get_model
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
        from kubeflow_tpu.train import TrainConfig, Trainer

        model, mcfg = get_model("vit-tiny")
        mesh = make_host_local_mesh(AxisSpec(dp=-1))

        def trial_fn(hp):
            tc = TrainConfig(task="image", total_steps=3,
                             warmup_steps=1,
                             learning_rate=float(hp["learning_rate"]),
                             weight_decay=float(hp["weight_decay"]))
            trainer = Trainer(model, tc, mesh)
            rng = jax.random.PRNGKey(0)
            batch = trainer.shard_batch({
                "inputs": jnp.zeros((8, mcfg.image_size, mcfg.image_size, 3),
                                    jnp.float32),
                "labels": jnp.zeros((8,), jnp.int32),
            })
            state = trainer.init_state(rng, batch)
            for _ in range(3):
                state, metrics = trainer.step(state, batch)
            return {"loss": float(metrics["loss"])}

        res = run_study(
            [ParameterSpec(name="learning_rate", min=1e-4, max=1e-2,
                           log_scale=True),
             ParameterSpec(name="weight_decay", min=0.0, max=0.1)],
            trial_fn, algorithm="random", max_trials=2, seed=1,
        )
        assert res.best is not None
        assert all(t.objective is not None and math.isfinite(t.objective)
                   for t in res.trials)


class TestSharedCompileSweep:
    def test_trials_reuse_one_compiled_step(self, devices8):
        """Hyperparams ride the optimizer state: N trials, ONE compile."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.hpo.sweep import SharedCompileSweep, run_study
        from kubeflow_tpu.models import get_model
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh

        model, mcfg = get_model("vit-tiny")
        mesh = make_host_local_mesh(AxisSpec(dp=-1))
        batch = {
            "inputs": jnp.zeros((8, mcfg.image_size, mcfg.image_size, 3),
                                jnp.float32),
            "labels": jnp.zeros((8,), jnp.int32),
        }
        sweep = SharedCompileSweep(model, mesh, batch, steps=3, task="image")
        res = run_study(
            [ParameterSpec(name="learning_rate", min=1e-4, max=1e-2,
                           log_scale=True),
             ParameterSpec(name="weight_decay", min=0.0, max=0.2)],
            sweep.trial_fn, algorithm="random", max_trials=4,
        )
        assert res.best is not None
        assert len({t.objective for t in res.trials}) > 1  # lr matters
        # The point: every trial is ONE dispatch of ONE compiled program —
        # hyperparams are traced inputs, so no trial ever recompiles.
        assert sweep._run_trial._cache_size() == 1
