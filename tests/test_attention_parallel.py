import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded
from kubeflow_tpu.parallel.ulysses import ulysses_attention_sharded


def _qkv(key, B, S, H, D, Hkv=None, dtype=jnp.float32):
    Hkv = Hkv or H
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, S, H, D), dtype)
    k = jax.random.normal(k2, (B, S, Hkv, D), dtype)
    v = jax.random.normal(k3, (B, S, Hkv, D), dtype)
    return q, k, v


@pytest.fixture
def sp_mesh(devices8):
    devs = np.asarray(devices8).reshape(2, 4)
    return Mesh(devs, ("dp", "sp"))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0), B=2, S=32, H=4, D=16)
        ref = mha_reference(q, k, v, causal=causal)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(1), B=2, S=32, H=8, D=16, Hkv=2)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bf16(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(2), B=2, S=32, H=4, D=16, dtype=jnp.bfloat16)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
        )

    def test_jit_and_grad(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(3), B=2, S=32, H=4, D=16)

        def loss_ring(q, k, v):
            return ring_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            ).sum()

        def loss_ref(q, k, v):
            return mha_reference(q, k, v, causal=True).sum()

        g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
        g_ref = jax.grad(loss_ref)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)


class TestRingFlashPath:
    """Shapes that block cleanly (per-device S % 128 == 0) must route ring
    attention through the pallas flash kernel + lse merge, and still match
    the full-softmax reference."""

    def _assert_flash_eligible(self, q, k, sp):
        from kubeflow_tpu.parallel.ring_attention import _ring_flash_supported
        B, S, H, D = q.shape
        local_q = q[:, : S // sp]
        local_k = k[:, : S // sp]
        assert _ring_flash_supported(local_q, local_k)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(10), B=2, S=512, H=4, D=64, Hkv=2)
        self._assert_flash_eligible(q, k, sp=4)
        ref = mha_reference(q, k, v, causal=causal)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_reference(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(11), B=2, S=512, H=4, D=64, Hkv=2)
        co = jax.random.normal(jax.random.PRNGKey(12), q.shape)

        def loss_ring(q, k, v):
            return (ring_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            ) * co).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True) * co).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-4,
                err_msg=f"d{name} mismatch through flash ring",
            )


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        # H=8 divisible by sp=4
        q, k, v = _qkv(jax.random.PRNGKey(4), B=2, S=32, H=8, D=16)
        ref = mha_reference(q, k, v, causal=causal)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_repeat(self, sp_mesh):
        # Hkv=2 < sp=4 → internally repeated
        q, k, v = _qkv(jax.random.PRNGKey(5), B=2, S=32, H=8, D=16, Hkv=2)
        ref = mha_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_indivisible_heads_raise(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(6), B=2, S=32, H=6, D=16)
        with pytest.raises(ValueError):
            ulysses_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            )


class TestUlyssesGqaLcm:
    def test_kv_heads_not_divisor_of_sp(self, sp_mesh):
        # Hkv=6 with sp=4: lcm repeat → 12 heads, divisible by 4.
        q, k, v = _qkv(jax.random.PRNGKey(7), B=2, S=32, H=12, D=16, Hkv=6)
        ref = mha_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
