import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded
from kubeflow_tpu.parallel.ulysses import ulysses_attention_sharded


def _qkv(key, B, S, H, D, Hkv=None, dtype=jnp.float32):
    Hkv = Hkv or H
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, S, H, D), dtype)
    k = jax.random.normal(k2, (B, S, Hkv, D), dtype)
    v = jax.random.normal(k3, (B, S, Hkv, D), dtype)
    return q, k, v


@pytest.fixture
def sp_mesh(devices8):
    devs = np.asarray(devices8).reshape(2, 4)
    return Mesh(devs, ("dp", "sp"))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0), B=2, S=32, H=4, D=16)
        ref = mha_reference(q, k, v, causal=causal)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(1), B=2, S=32, H=8, D=16, Hkv=2)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bf16(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(2), B=2, S=32, H=4, D=16, dtype=jnp.bfloat16)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
        )

    def test_jit_and_grad(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(3), B=2, S=32, H=4, D=16)

        def loss_ring(q, k, v):
            return ring_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            ).sum()

        def loss_ref(q, k, v):
            return mha_reference(q, k, v, causal=True).sum()

        g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
        g_ref = jax.grad(loss_ref)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)


class TestRingFlashPath:
    """Shapes that block cleanly (per-device S % 128 == 0) must route ring
    attention through the pallas flash kernel + lse merge, and still match
    the full-softmax reference."""

    def _assert_flash_eligible(self, q, k, sp):
        from kubeflow_tpu.parallel.ring_attention import _ring_flash_supported
        B, S, H, D = q.shape
        local_q = q[:, : S // sp]
        local_k = k[:, : S // sp]
        assert _ring_flash_supported(local_q, local_k)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(10), B=2, S=512, H=4, D=64, Hkv=2)
        self._assert_flash_eligible(q, k, sp=4)
        ref = mha_reference(q, k, v, causal=causal)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_reference(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(11), B=2, S=512, H=4, D=64, Hkv=2)
        co = jax.random.normal(jax.random.PRNGKey(12), q.shape)

        def loss_ring(q, k, v):
            return (ring_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            ) * co).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True) * co).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-4,
                err_msg=f"d{name} mismatch through flash ring",
            )


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        # H=8 divisible by sp=4
        q, k, v = _qkv(jax.random.PRNGKey(4), B=2, S=32, H=8, D=16)
        ref = mha_reference(q, k, v, causal=causal)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_repeat(self, sp_mesh):
        # Hkv=2 < sp=4 → internally repeated
        q, k, v = _qkv(jax.random.PRNGKey(5), B=2, S=32, H=8, D=16, Hkv=2)
        ref = mha_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_indivisible_heads_raise(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(6), B=2, S=32, H=6, D=16)
        with pytest.raises(ValueError):
            ulysses_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            )


class TestUlyssesGqaLcm:
    def test_kv_heads_not_divisor_of_sp(self, sp_mesh):
        # Hkv=6 with sp=4: lcm repeat → 12 heads, divisible by 4.
        q, k, v = _qkv(jax.random.PRNGKey(7), B=2, S=32, H=12, D=16, Hkv=6)
        ref = mha_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestUlyssesFlashPath:
    """Kernel-eligible shapes must route Ulysses' local attention through
    the pallas flash kernel (the a2a output is head-sharded full-sequence —
    exactly the kernel's layout) and still match the reference. The O(S^2)
    reference path remains only as the tiny-shape fallback inside
    flash_attention itself."""

    def _assert_flash_eligible(self, q, k, sp):
        # Shapes as the local flash call sees them: full S, H/P heads.
        from kubeflow_tpu.ops.flash_attention import _supported, default_blocks
        B, S, H, D = q.shape
        Hkv = k.shape[2]
        bq, bkv = default_blocks(S, S)
        assert _supported(S, S, H // sp, max(Hkv // sp, 1), bq, bkv)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(20), B=2, S=512, H=8, D=64, Hkv=4)
        self._assert_flash_eligible(q, k, sp=4)
        ref = mha_reference(q, k, v, causal=causal)
        out = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_reference(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(21), B=2, S=512, H=8, D=64, Hkv=4)
        co = jax.random.normal(jax.random.PRNGKey(22), q.shape)

        def loss_uly(q, k, v):
            return (ulysses_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None
            ) * co).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True) * co).sum()

        g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_uly, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-4,
                err_msg=f"d{name} mismatch through flash ulysses",
            )

    def test_parity_vs_ring_8k(self, sp_mesh):
        """At the contexts SP exists for (8k+), ring and Ulysses are two
        routings of the same attention: outputs must agree without either
        touching an O(S^2) score tensor."""
        q, k, v = _qkv(jax.random.PRNGKey(23), B=2, S=8192, H=8, D=64, Hkv=4)
        self._assert_flash_eligible(q, k, sp=4)
        ring = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        uly = ulysses_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None, causal=True
        )
        np.testing.assert_allclose(
            np.asarray(uly), np.asarray(ring), atol=2e-4)


class TestSpPolicy:
    """choose_sp_impl encodes the MEASURED crossover (bench.py
    sp-crossover): Ulysses' balanced causal split beats ring's skewed one
    ~2x on the kernel critical path, so Ulysses is preferred whenever its
    collectives stay exact (head counts divide sp) and its a2a bytes don't
    inflate past the compute win (extreme GQA/MQA)."""

    def test_divisible_heads_prefer_ulysses_at_any_length(self):
        from kubeflow_tpu.parallel.policy import choose_sp_impl
        for S in (2048, 8192, 32768):
            assert choose_sp_impl(
                seq_len=S, sp=4, num_heads=32, num_kv_heads=8) == "ulysses"

    def test_indivisible_heads_force_ring(self):
        from kubeflow_tpu.parallel.policy import choose_sp_impl
        assert choose_sp_impl(
            seq_len=2048, sp=4, num_heads=6, num_kv_heads=2) == "ring"

    def test_gqa_repeat_forces_ring(self):
        # kv heads don't divide sp: Ulysses would inflate kv on the wire.
        from kubeflow_tpu.parallel.policy import choose_sp_impl
        assert choose_sp_impl(
            seq_len=2048, sp=4, num_heads=8, num_kv_heads=2) == "ring"

    def test_extreme_gqa_wire_ratio_forces_ring(self):
        # Divisible, but Ulysses' a2a would move (16+2)/(2*2) = 4.5x
        # ring's rotation bytes — past the ~2x compute win.
        from kubeflow_tpu.parallel.policy import choose_sp_impl
        assert choose_sp_impl(
            seq_len=8192, sp=2, num_heads=16, num_kv_heads=2) == "ring"

    def test_sp_auto_resolves_in_training(self, devices8):
        """attn_impl='sp_auto' must trace and step end-to-end (tiny config
        has 2 kv heads vs sp=4: resolves to ring via the divisibility
        guard)."""
        from kubeflow_tpu.models import Llama, LlamaConfig
        from kubeflow_tpu.topology import AxisSpec
        from kubeflow_tpu.topology.mesh import make_host_local_mesh
        from kubeflow_tpu.train import TrainConfig, Trainer
        from kubeflow_tpu.train.data import SyntheticTextConfig, synthetic_text

        mesh = make_host_local_mesh(AxisSpec(dp=2, sp=4))
        model = Llama(LlamaConfig.tiny(scan_layers=True, num_layers=2))
        trainer = Trainer(
            model, TrainConfig(task="lm", attn_impl="sp_auto",
                               warmup_steps=1), mesh)
        it = synthetic_text(SyntheticTextConfig(
            batch_size=4, seq_len=32, vocab_size=256))
        batch = trainer.shard_batch(
            {kk: jnp.asarray(vv) for kk, vv in next(it).items()})
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestZigzagRing:
    """Zigzag schedule (mirror-swapped q halves — balanced causal work):
    must be output- and grad-identical to the reference and to the
    contiguous ring at every eligible shape; ineligible shapes fall back
    silently."""

    @pytest.mark.parametrize("Hkv", [4, 2])
    def test_matches_reference(self, sp_mesh, Hkv):
        q, k, v = _qkv(jax.random.PRNGKey(30), B=2, S=512, H=4, D=64,
                       Hkv=Hkv)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
            causal=True, zigzag=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match_reference(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(31), B=2, S=512, H=4, D=64, Hkv=2)
        co = jax.random.normal(jax.random.PRNGKey(32), q.shape)

        def loss_zz(q, k, v):
            return (ring_attention_sharded(
                q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
                zigzag=True) * co).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True) * co).sum()

        g_zz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_zz, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-4,
                err_msg=f"d{name} mismatch through zigzag ring")

    def test_auto_default_matches_contiguous_at_8k(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(33), B=2, S=8192, H=8, D=64,
                       Hkv=4)
        auto = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
            causal=True)
        plain = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
            causal=True, zigzag=False)
        np.testing.assert_allclose(np.asarray(auto), np.asarray(plain),
                                   atol=2e-5)

    def test_non_causal_falls_back(self, sp_mesh):
        # zigzag exists to balance CAUSAL skew; non-causal is already
        # balanced and must not take the zigzag path implicitly.
        q, k, v = _qkv(jax.random.PRNGKey(34), B=2, S=512, H=4, D=64)
        ref = mha_reference(q, k, v, causal=False)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
            causal=False, zigzag=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_odd_local_length_falls_back(self, sp_mesh):
        # S/P odd -> halves can't block; the zigzag hint must degrade to
        # the contiguous path, not crash.
        q, k, v = _qkv(jax.random.PRNGKey(35), B=2, S=36, H=4, D=16)
        ref = mha_reference(q, k, v, causal=True)
        out = ring_attention_sharded(
            q, k, v, sp_mesh, batch_axes=("dp",), head_axis=None,
            causal=True, zigzag=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
