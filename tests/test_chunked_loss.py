"""Chunked lm_head + cross-entropy (train/losses.chunked_cross_entropy):
the [tokens, vocab] logits tensor never materialises; loss/grads/accuracy
must match the unchunked path exactly (same f32 statistics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import get_model
from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
from kubeflow_tpu.train import TrainConfig, Trainer
from kubeflow_tpu.train.losses import (
    chunked_cross_entropy,
    cross_entropy_loss,
    softmax_accuracy,
)


def _data(n=50, e=16, v=37, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(size=(n, e)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(e, v)) * 0.2, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, size=(n,)), jnp.float32)
    return hidden, kernel, labels, mask


class TestChunkedMatchesUnchunked:
    @pytest.mark.parametrize("block", [8, 16, 50, 64])
    def test_loss_count_accuracy_match(self, block):
        hidden, kernel, labels, mask = _data()
        logits = hidden @ kernel
        want_loss, want_count = cross_entropy_loss(
            logits, labels, mask=mask, z_loss_weight=1e-3)
        want_acc = softmax_accuracy(logits, labels, mask=mask)
        loss, count, hits = chunked_cross_entropy(
            hidden, kernel, labels, mask=mask, z_loss_weight=1e-3,
            block=block)
        assert float(count) == float(want_count)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(hits / count), float(want_acc),
                                   rtol=1e-6)

    def test_no_mask_counts_everything(self):
        hidden, kernel, labels, _ = _data(n=32)
        logits = hidden @ kernel
        want_loss, _ = cross_entropy_loss(logits, labels)
        loss, count, _ = chunked_cross_entropy(
            hidden, kernel, labels, block=8)
        assert float(count) == 32.0
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)

    def test_padding_tokens_do_not_leak(self):
        # n not divisible by block: the pad rows carry mask 0 and must not
        # move the loss
        hidden, kernel, labels, mask = _data(n=50)
        l1, c1, h1 = chunked_cross_entropy(
            hidden, kernel, labels, mask=mask, block=16)
        l2, c2, h2 = chunked_cross_entropy(
            hidden, kernel, labels, mask=mask, block=50)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        assert float(c1) == float(c2)
        assert float(h1) == float(h2)

    def test_grads_match_unchunked(self):
        hidden, kernel, labels, mask = _data(n=48, e=12, v=29)

        def chunked(h, k):
            loss, _, _ = chunked_cross_entropy(
                h, k, labels, mask=mask, z_loss_weight=1e-3, block=16)
            return loss

        def dense(h, k):
            loss, _ = cross_entropy_loss(
                h @ k, labels, mask=mask, z_loss_weight=1e-3)
            return loss

        gh1, gk1 = jax.grad(chunked, argnums=(0, 1))(hidden, kernel)
        gh2, gk2 = jax.grad(dense, argnums=(0, 1))(hidden, kernel)
        np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gk1), np.asarray(gk2),
                                   rtol=2e-4, atol=1e-6)


class TestTrainerIntegration:
    def _world(self, loss_chunk):
        model, _ = get_model("llama-tiny")
        mesh = make_host_local_mesh(AxisSpec(dp=-1))
        trainer = Trainer(
            model,
            TrainConfig(task="lm", warmup_steps=2, total_steps=50,
                        loss_chunk=loss_chunk),
            mesh,
        )
        rng = np.random.default_rng(0)
        batch = trainer.shard_batch({"inputs": jnp.asarray(
            rng.integers(1, 250, size=(8, 17)), jnp.int32)})
        return trainer, batch

    def test_chunked_step_matches_unchunked(self):
        t0, batch = self._world(0)
        t1, _ = self._world(16)
        s0 = t0.init_state(jax.random.PRNGKey(0), batch)
        s1 = t1.init_state(jax.random.PRNGKey(0), batch)
        s0, m0 = t0.step(s0, batch)
        s1, m1 = t1.step(s1, batch)
        np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                                   rtol=1e-4)
        np.testing.assert_allclose(
            float(m0["accuracy"]), float(m1["accuracy"]), rtol=1e-5)
        # params after one step agree => identical gradients flowed,
        # including into lm_head through the fused loss
        for a, b in zip(jax.tree.leaves(s0.params),
                        jax.tree.leaves(s1.params)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=3e-3, atol=3e-5)

    def test_chunked_loss_decreases(self):
        trainer, batch = self._world(16)
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for _ in range(8):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_tp_sharded_vocab_falls_back(self):
        model, _ = get_model("llama-tiny")
        mesh = make_host_local_mesh(AxisSpec(dp=-1, tp=2))
        trainer = Trainer(
            model, TrainConfig(task="lm", loss_chunk=16), mesh)
        assert trainer._use_chunked_loss() is False
        rng = np.random.default_rng(0)
        batch = trainer.shard_batch({"inputs": jnp.asarray(
            rng.integers(1, 250, size=(8, 17)), jnp.int32)})
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
