"""Multi-tenant capacity market (ISSUE 13): the tenant tree, weighted
DRF math, scheduler fairness protection, goodput tenant rollup with
versioned journal records (old journals replay byte-identically), the
LB's tenant-weighted shedding, and the radix prefix-matching A/B."""

import json

import pytest

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    MeshAxesSpec,
    Profile,
    ProfileSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.obs.goodput import GoodputAccountant
from kubeflow_tpu.scheduler.core import GangScheduler
from kubeflow_tpu.scheduler.fleet import Fleet
from kubeflow_tpu.tenancy import (
    TenantTree,
    compute_shares,
    slo_burn,
    slo_state,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry

SPECS = [
    {"name": "org", "weight": 1.0, "quota_chips": 64},
    {"name": "team-a", "parent": "org", "weight": 2.0, "quota_chips": 48,
     "goodput_slo": 0.5},
    {"name": "team-b", "parent": "org", "weight": 1.0, "quota_chips": 32},
    {"name": "solo", "weight": 1.0},
]


class TestTenantTree:
    def test_resolve_and_ancestry(self):
        tree = TenantTree.from_specs(SPECS)
        assert tree.resolve("team-a") == "org/team-a"
        assert tree.resolve("solo") == "solo"
        assert tree.resolve("unknown-ns") == ""
        assert tree.ancestry("team-b") == ["org", "team-b"]
        assert tree.roots() == ["org", "solo"]

    def test_fair_fractions_weighted_and_work_conserving(self):
        tree = TenantTree.from_specs(SPECS)
        # Both teams active: org's share (1/2 vs solo) splits 2:1.
        f = tree.fair_fractions({"team-a", "team-b", "solo"})
        assert f["solo"] == pytest.approx(0.5)
        assert f["team-a"] == pytest.approx(0.5 * 2 / 3)
        assert f["team-b"] == pytest.approx(0.5 * 1 / 3)
        assert sum(f.values()) == pytest.approx(1.0)
        # team-b idle: its share flows to team-a, NOT to solo (the
        # hierarchical split is per level).
        f = tree.fair_fractions({"team-a", "solo"})
        assert f["team-a"] == pytest.approx(0.5)
        assert "team-b" not in f

    def test_active_internal_node_competes_with_children(self):
        tree = TenantTree.from_specs(SPECS)
        f = tree.fair_fractions({"org", "team-a"})
        # org's own workloads claim a sibling share next to team-a.
        assert f["org"] == pytest.approx(1.0 / 3)
        assert f["team-a"] == pytest.approx(2.0 / 3)

    def test_validate_overcommit_flagged_not_fatal(self):
        tree = TenantTree.from_specs(SPECS)
        errors, over = tree.validate()
        assert errors == []
        assert len(over) == 1 and "org" in over[0]   # 48+32 > 64

    def test_validate_child_exceeding_parent_is_error(self):
        specs = [{"name": "p", "quota_chips": 16},
                 {"name": "c", "parent": "p", "quota_chips": 32}]
        errors, _ = TenantTree.from_specs(specs).validate()
        assert any("exceeds parent" in e for e in errors)

    def test_unknown_parent_and_cycle_degrade_to_root(self):
        specs = [{"name": "a", "parent": "ghost"},
                 {"name": "b", "parent": "c"},
                 {"name": "c", "parent": "b"}]
        tree = TenantTree.from_specs(specs)
        # Everything still resolves (root-attached), flags recorded.
        assert tree.resolve("a") == "a"
        assert tree.resolve("b") != ""
        errors, _ = tree.validate()
        assert any("unknown parent" in e for e in errors)
        assert any("cycle" in e for e in errors)

    def test_bad_weight_flagged_and_defaulted(self):
        tree = TenantTree.from_specs([{"name": "x", "weight": -2}])
        assert tree.node("x").weight == 1.0
        errors, _ = tree.validate()
        assert any("non-positive weight" in e for e in errors)


class TestDRFMath:
    def test_shares_deficit_and_protection_predicates(self):
        tree = TenantTree.from_specs(SPECS)
        shares = compute_shares(
            tree, held_chips={"team-a": 48, "team-b": 8},
            demanding={"solo"}, total_chips=64)
        assert shares.share("team-a") == pytest.approx(0.75)
        assert shares.over_fair("team-a")          # fair = 1/3
        assert shares.at_or_below_fair("team-b")   # 0.125 <= 1/6
        assert shares.at_or_below_fair("solo")     # holds nothing
        assert shares.deficit("solo") == pytest.approx(0.5)

    def test_eps_is_one_chip(self):
        tree = TenantTree.from_specs([{"name": "a"}, {"name": "b"}])
        shares = compute_shares(tree, held_chips={"a": 32, "b": 32},
                                total_chips=64)
        # Exactly at fair: neither over.
        assert not shares.over_fair("a") and not shares.over_fair("b")

    def test_slo_burn_and_state(self):
        assert slo_burn(0.8, 0.6) == pytest.approx(0.5)
        assert slo_state(slo_burn(0.8, 0.6)) == "ok"
        assert slo_state(slo_burn(0.4, 0.6)) == "warn"
        assert slo_state(slo_burn(0.1, 0.6)) == "page"
        assert slo_burn(0.5, 0.0) is None
        assert slo_state(None) == "-"


def _job(name, ns, *, uid=None, priority=0, slices=1, phase="Running"):
    j = TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type="v5e-16", num_slices=slices,
                        mesh=MeshAxesSpec(dp=-1), priority=priority,
                        preemption_policy="restart"),
    )
    j.metadata.uid = uid or f"uid-{ns}-{name}"
    j.status.phase = phase
    return j


class TestSchedulerDRF:
    """The protection invariant and DRF ordering on a bare scheduler
    (no control plane: fleet state driven directly)."""

    def _world(self, *, drf=True):
        tree = TenantTree.from_specs(
            [{"name": "hog"}, {"name": "meek"}, {"name": "newbie"}])
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        sched = GangScheduler(fleet, registry=MetricsRegistry(),
                              tenants=tree, drf=drf)
        return sched, fleet

    def _fill(self, sched, fleet):
        """hog holds 3 of 4 units, meek holds 1 — hog over fair
        (3/4 > ~1/3), meek at-or-below (1/4 <= 1/3)."""
        jobs = []
        for i in range(3):
            j = _job(f"hog-{i}", "hog", priority=5)
            rendered, blocked = sched.assign(j, jobs=jobs)
            assert blocked is None
            jobs.append(j)
        m = _job("meek-0", "meek", priority=0)
        rendered, blocked = sched.assign(m, jobs=jobs)
        assert blocked is None
        jobs.append(m)
        return jobs

    def test_over_fair_requester_cannot_evict_below_fair_tenant(self):
        sched, fleet = self._world(drf=True)
        jobs = self._fill(sched, fleet)

        class _Api:                     # preempt_gang sees no pods
            def list(self, *a, **k):
                return []

            def update_status(self, obj):
                pass

        req = _job("hog-new", "hog", priority=9, phase="Pending")
        jobs2 = jobs + [req]
        rendered, blocked = sched.assign(req, jobs=jobs2, api=_Api())
        # The only viable victim set includes meek's gang (hog's own
        # gangs alone can free at most... they CAN free enough; hog may
        # preempt its own lower-priority gangs) — but meek must never
        # be chosen while hog is over fair.
        assert all(e.get("victim_tenant") != "meek"
                   for e in sched.preemption_log)
        assert not any(e.get("fair_violation")
                       for e in sched.preemption_log)

    def test_observe_mode_records_violation_instead_of_blocking(self):
        sched, fleet = self._world(drf=False)
        jobs = self._fill(sched, fleet)
        shares = sched.tenant_shares(jobs)
        assert shares.over_fair("hog")
        assert shares.at_or_below_fair("meek")

    def test_drf_admission_yields_to_more_deficit_tenant(self):
        sched, fleet = self._world(drf=True)
        # hog fills the whole fleet minus one unit; meek and newbie
        # both queue a 1-wide gang; newbie (deficit, placeable) should
        # make hog's NEXT gang yield.
        jobs = self._fill(sched, fleet)
        # Free one unit by releasing meek's gang: one unit free now.
        sched.release(jobs[-1].metadata.uid)
        jobs = jobs[:-1]
        pending_newbie = _job("nb-0", "newbie", phase="Pending")
        req = _job("hog-more", "hog", phase="Pending")
        jobs2 = jobs + [pending_newbie, req]
        rendered, blocked = sched.assign(req, jobs=jobs2)
        assert blocked is not None and blocked[0] == "TenantFairShare"
        # The deficit tenant itself places straight into the free unit.
        rendered, blocked = sched.assign(pending_newbie, jobs=jobs2)
        assert blocked is None

    def test_no_tree_byte_identical_contract(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        sched = GangScheduler(fleet, registry=MetricsRegistry())
        j = _job("a", "anywhere", phase="Pending")
        rendered, blocked = sched.assign(j, jobs=[j])
        assert blocked is None
        assert sched.tenant_shares([j]) is None
        assert sched.tenant_of(j) == ""


class TestGoodputTenantRollup:
    def _tree(self):
        return TenantTree.from_specs(SPECS)

    def test_tenant_attribution_and_rollup(self):
        import types as _types

        acc = GoodputAccountant.from_capacity({"v5e-16": 2},
                                              tenants=self._tree())
        ja = _job("a", "team-a", phase="Running")
        jb = _job("b", "team-b", phase="Running")
        for j in (ja, jb):
            acc.apply_event(_types.SimpleNamespace(type="ADDED", object=j))
        acc.tick(10)
        snap = acc.tenant_snapshot()
        assert snap["conserved"]
        t = snap["tenants"]
        assert t["org/team-a"]["categories_ticks"]["productive"] == 10
        # The org rollup sums both teams.
        assert t["org"]["categories_ticks"]["productive"] == 20
        assert t["org"]["share"] == pytest.approx(1.0)
        # SLO state present where declared.
        assert t["org/team-a"]["slo_state"] in ("ok", "warn", "page")
        # The full snapshot carries the same rollup.
        assert acc.snapshot()["tenants"]["org"]["held_ticks"] == 20

    def test_journal_tn_records_versioned_and_replayed(self, tmp_path):
        import types as _types

        path = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity(
            {"v5e-16": 1}, tenants=self._tree(), journal_path=path,
            fsync=False)
        j = _job("a", "team-a", phase="Running")
        acc.apply_event(_types.SimpleNamespace(type="ADDED", object=j))
        acc.tick(5)
        acc.close()
        recs = [json.loads(line) for line in open(path)]
        tn = [r for r in recs if r["op"] == "tn"]
        assert tn and tn[0]["v"] == 2 \
            and tn[0]["tenant"] == "org/team-a"
        # Replay into a fresh accountant: byte-identical fingerprint,
        # tenant rollup included.
        twin = GoodputAccountant.from_capacity({"v5e-16": 1})
        twin.replay_from(path)
        assert twin.fingerprint() == acc.fingerprint()
        assert twin.tenant_snapshot()["tenants"]["org/team-a"][
            "categories_ticks"]["productive"] == 5

    def test_pre_tenant_journal_replays_byte_identically(self, tmp_path):
        """The regression contract: a journal written BEFORE ISSUE 13
        (no tn records — exactly what a tenant-less accountant writes)
        replays through a tenant-enabled accountant to the SAME
        fingerprint a pre-ISSUE-13 accountant produces."""
        import types as _types

        path = str(tmp_path / "old.jsonl")
        old = GoodputAccountant.from_capacity(
            {"v5e-16": 2}, journal_path=path, fsync=False)
        j = _job("a", "team-a", phase="Running")
        old.apply_event(_types.SimpleNamespace(type="ADDED", object=j))
        old.tick(7)
        old.close()
        assert all(json.loads(line)["op"] != "tn" for line in open(path))
        # Pre-ISSUE-13 replayer (no tree) vs tenant-enabled replayer:
        # identical fingerprints — replay applies records, it never
        # invents tenant attributions the journal does not carry.
        plain = GoodputAccountant.from_capacity({"v5e-16": 2})
        plain.replay_from(path)
        aware = GoodputAccountant.from_capacity(
            {"v5e-16": 2}, tenants=self._tree())
        aware.replay_from(path)
        assert plain.fingerprint() == aware.fingerprint() \
            == old.fingerprint()
        assert aware.tenant_snapshot()["tenants"] == {} or \
            "org/team-a" not in aware.tenant_snapshot()["tenants"]

    def test_set_tenants_resolves_known_jobs_and_journals(self, tmp_path):
        import types as _types

        path = str(tmp_path / "g.jsonl")
        acc = GoodputAccountant.from_capacity(
            {"v5e-16": 1}, journal_path=path, fsync=False)
        j = _job("a", "team-b", phase="Running")
        acc.apply_event(_types.SimpleNamespace(type="ADDED", object=j))
        acc.tick(3)
        acc.set_tenants(self._tree())
        acc.close()
        recs = [json.loads(line) for line in open(path)]
        assert any(r["op"] == "tn" and r["tenant"] == "org/team-b"
                   for r in recs)


class TestLBTenantMarket:
    def test_resolve_tenant_paths(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        tree = TenantTree.from_specs(SPECS)
        lb = ServingLoadBalancer(tenants=tree)
        assert lb.resolve_tenant({"tenant": "team-a"}) == "team-a"
        assert lb.resolve_tenant({"namespace": "team-b"}) == "team-b"
        assert lb.resolve_tenant(
            {}, {"x-kftpu-namespace": "solo"}) == "solo"
        assert lb.resolve_tenant({"namespace": "ghost"}) is None
        # Session key -> namespace -> tenant (the registry leg).
        lb.session_namespaces["sess-9"] = "team-a"
        assert lb.resolve_tenant({"session": "sess-9"}) == "team-a"
        assert lb.resolve_tenant({"session": "unknown"}) is None
        blind = ServingLoadBalancer()
        assert blind.resolve_tenant({"tenant": "team-a"}) is None

    def test_session_registry_tofu_binds_and_matching_ns_resolves(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        lb = ServingLoadBalancer(tenants=TenantTree.from_specs(SPECS))
        # Unbound session WITHOUT a namespace: PR-12 behaviour
        # byte-identical — affinity works, traffic untenanted.
        keys, tenant = lb._resolve_identity({"session": "s1"}, None)
        assert "s:s1" in keys and tenant is None
        assert "s1" not in lb.session_namespaces
        # Unbound session WITH a namespace: trust-on-first-use bind.
        keys, tenant = lb._resolve_identity(
            {"session": "s1", "namespace": "team-a"}, None)
        assert "s:s1" in keys and tenant == "team-a"
        assert lb.session_namespaces["s1"] == "team-a"
        # Bound + matching namespace: the honest-client path.
        keys, tenant = lb._resolve_identity(
            {"session": "s1", "namespace": "team-a"}, None)
        assert "s:s1" in keys and tenant == "team-a"
        assert lb.session_rejects == 0

    def test_cross_tenant_session_spoof_rejected_403(self):
        from kubeflow_tpu.serving.lb import RestError, ServingLoadBalancer

        lb = ServingLoadBalancer(tenants=TenantTree.from_specs(SPECS))
        lb.register_session("owner-sess", "team-a")
        # A team-b client replaying team-a's session id must NOT
        # inherit team-a's share (the PR-13 spoofing follow-up).
        with pytest.raises(RestError) as ei:
            lb._resolve_identity(
                {"session": "owner-sess", "namespace": "team-b"}, None)
        assert ei.value.status == 403
        # Declared-tenant spoofing through the header leg too.
        with pytest.raises(RestError) as ei:
            lb._resolve_identity(
                {"session": "owner-sess"},
                {"x-kftpu-tenant": "team-b"})
        assert ei.value.status == 403
        assert lb.session_rejects == 2

    def test_bare_bound_session_demoted_not_trusted(self):
        from kubeflow_tpu.serving.blocks import prefix_key
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        lb = ServingLoadBalancer(tenants=TenantTree.from_specs(SPECS))
        lb.register_session("owner-sess", "team-a")
        # Session id alone (the stolen-bearer shape): the session
        # affinity key is stripped and the request is untenanted.
        # Session identity dominates key derivation, so nothing is
        # left — the spoofer gets anonymous round-robin routing.
        toks = list(range(64))
        keys, tenant = lb._resolve_identity(
            {"session": "owner-sess", "tokens": toks}, None)
        assert "s:owner-sess" not in keys
        assert keys == []
        assert tenant is None
        assert lb.session_rejects == 1
        # Prompt-only traffic keeps its prefix-hash keys: those encode
        # the prompt, not a stolen identity.
        assert prefix_key(toks) in lb.affinity_keys({"tokens": toks})

    def test_register_session_validates(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        lb = ServingLoadBalancer()
        with pytest.raises(ValueError):
            lb.register_session("", "ns")
        with pytest.raises(ValueError):
            lb.register_session("s", "")

    def test_overage_math_weighted(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        # Both window modes share one overage formula; a frozen clock
        # makes the decayed masses equal the raw counts exactly.
        clock = {"t": 0.0}
        for mode in ("decay", "count"):
            lb = ServingLoadBalancer(tenants={"big": 3.0, "small": 1.0},
                                     share_window=mode,
                                     share_clock=lambda: clock["t"])
            for _ in range(4):
                lb.note_tenant_arrival("big")
            for _ in range(4):
                lb.note_tenant_arrival("small")
            # fair(big) = 8 * 3/4 = 6 -> under; fair(small) = 2 ->
            # over by 2.
            assert lb._tenant_overage_locked("big") == \
                pytest.approx(-2.0), mode
            assert lb._tenant_overage_locked("small") == \
                pytest.approx(2.0), mode

    def test_decayed_window_forgets_by_time_not_volume(self):
        """ISSUE 15 (the PR-13 follow-up): on a low-QPS fleet an old
        burst must stop deciding sheds once TIME passes — even though
        a 4096-request count window would still be full of it."""
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        clock = {"t": 0.0}
        lb = ServingLoadBalancer(tenants={"big": 1.0, "small": 1.0},
                                 share_half_life_s=10.0,
                                 share_clock=lambda: clock["t"])
        assert lb.share_window == "decay"
        for _ in range(100):                  # the morning burst
            lb.note_tenant_arrival("big")
        lb.note_tenant_arrival("small")
        assert lb._tenant_overage_locked("big") > 0
        # Ten half-lives later, one fresh arrival each: the burst mass
        # decayed to ~0.1 — "big" is no longer the over-share tenant.
        clock["t"] = 100.0
        lb.note_tenant_arrival("small")
        lb.note_tenant_arrival("small")
        assert lb._tenant_overage_locked("big") < 0
        assert lb._tenant_overage_locked("small") > 0
        shares = lb.tenant_shares_snapshot()
        assert shares["small"] > shares["big"]
        # The count window, by contrast, still blames the burst.
        lbc = ServingLoadBalancer(tenants={"big": 1.0, "small": 1.0},
                                  share_window="count")
        for _ in range(100):
            lbc.note_tenant_arrival("big")
        for _ in range(3):
            lbc.note_tenant_arrival("small")
        assert lbc._tenant_overage_locked("big") > 0

    def test_decay_quiet_tenant_drops_off_the_table(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        clock = {"t": 0.0}
        lb = ServingLoadBalancer(tenants={"a": 1.0, "b": 1.0},
                                 share_half_life_s=1.0,
                                 share_clock=lambda: clock["t"])
        lb.note_tenant_arrival("a")
        clock["t"] = 60.0                     # 60 half-lives: dust
        lb.note_tenant_arrival("b")
        # "a" no longer participates in the fair split at all.
        assert lb.tenant_shares_snapshot() == {"b": 1.0}
        assert lb._tenant_overage_locked("a") == 0.0

    def test_share_window_validation(self):
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        with pytest.raises(ValueError):
            ServingLoadBalancer(share_window="sliding")
        with pytest.raises(ValueError):
            ServingLoadBalancer(share_half_life_s=0.0)

    def test_tenant_burst_soak_exact_accounting(self):
        from kubeflow_tpu.chaos.serving_soak import run_tenant_burst_soak

        rep = run_tenant_burst_soak(warmup_rounds=2, burst_rounds=5,
                                    cooldown_rounds=2)
        assert rep.accounting_ok and rep.ledger_ok
        assert rep.errors == 0
        assert rep.shed.get(rep.in_share_tenant, 0) == 0
        assert rep.shed.get(rep.burst_tenant, 0) >= rep.burst_overage
        assert rep.clean


class TestRadixPrefixMatching:
    def test_prefix_chain_shapes(self):
        from kubeflow_tpu.serving.blocks import prefix_chain

        assert prefix_chain(list(range(5))) == []
        assert len(prefix_chain(list(range(8)))) == 1
        assert len(prefix_chain(list(range(40)))) == 4   # capped at 32
        # Shared head -> shared chain prefix; divergence after block 1.
        a = prefix_chain(list(range(24)))
        b = prefix_chain(list(range(8)) + [99] * 16)
        assert a[0] == b[0] and a[1] != b[1]

    def test_affinity_keys_ordering_and_modes(self):
        from kubeflow_tpu.serving.blocks import prefix_chain, prefix_key
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        toks = list(range(24))
        lb = ServingLoadBalancer()                     # radix default
        keys = lb.affinity_keys({"tokens": toks})
        assert keys[0] == prefix_key(toks)
        assert keys[1:] == list(reversed(prefix_chain(toks)))
        # Sessions keep their single sticky key.
        assert lb.affinity_keys({"session": "s1"}) == ["s:s1"]
        exact = ServingLoadBalancer(prefix_match="exact")
        assert exact.affinity_keys({"tokens": toks}) == [prefix_key(toks)]
        with pytest.raises(ValueError):
            ServingLoadBalancer(prefix_match="fuzzy")

    def test_radix_matches_partially_overlapping_prompt(self):
        from kubeflow_tpu.serving.blocks import prefix_chain
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        lb = ServingLoadBalancer(["a:1", "b:1"])
        head = list(range(100, 132))
        # Backend b reports the 2-block chain key resident (an earlier
        # family member's head lives there).
        with lb._lock:
            lb._backends["b:1"].resident_prefixes = frozenset(
                [prefix_chain(head)[1]])
        # A DIFFERENT prompt sharing only 2 head blocks must land on b.
        probe = head[:16] + [7] * 16
        picked = lb._acquire(keys=lb.affinity_keys({"tokens": probe}))
        assert picked.addr == "b:1"
        assert lb.affinity_hits == 1
        # Exact-mode LB ignores the chain hint for the same probe.
        lb2 = ServingLoadBalancer(["a:1", "b:1"], prefix_match="exact")
        with lb2._lock:
            lb2._backends["b:1"].resident_prefixes = frozenset(
                [prefix_chain(head)[1]])
        lb2._acquire(keys=lb2.affinity_keys({"tokens": probe}))
        assert lb2.affinity_hits == 0


class TestProfileTenantValidation:
    def _world(self):
        from kubeflow_tpu.controlplane.controllers.profile import (
            ProfileController,
        )
        from kubeflow_tpu.controlplane.runtime import (
            ControllerManager,
            InMemoryApiServer,
        )

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api, reg)
        mgr.register(ProfileController(api, reg))
        return api, mgr

    def test_weight_must_be_positive(self):
        api, mgr = self._world()
        api.create(Profile(metadata=ObjectMeta(name="bad"),
                           spec=ProfileSpec(owner="o@x", weight=0.0)))
        mgr.run_until_idle()
        assert api.get("Profile", "bad").status.phase == "Failed"
        mgr.close()

    def test_child_quota_exceeding_parent_fails(self):
        api, mgr = self._world()
        api.create(Profile(metadata=ObjectMeta(name="p"),
                           spec=ProfileSpec(owner="o@x",
                                            tpu_chip_quota=16)))
        api.create(Profile(metadata=ObjectMeta(name="c"),
                           spec=ProfileSpec(owner="o@x", parent="p",
                                            tpu_chip_quota=32)))
        mgr.run_until_idle()
        assert api.get("Profile", "c").status.phase == "Failed"
        assert api.get("Profile", "p").status.phase == "Ready"
        mgr.close()

    def test_overcommit_flagged_on_parent_not_fatal(self):
        api, mgr = self._world()
        api.create(Profile(metadata=ObjectMeta(name="p"),
                           spec=ProfileSpec(owner="o@x",
                                            tpu_chip_quota=32)))
        for name in ("c1", "c2"):
            api.create(Profile(
                metadata=ObjectMeta(name=name),
                spec=ProfileSpec(owner="o@x", parent="p",
                                 tpu_chip_quota=24)))
        mgr.run_until_idle()
        parent = api.get("Profile", "p")
        assert parent.status.phase == "Ready"
        cond = {c.type: c.status for c in parent.status.conditions}
        assert cond.get("QuotaOvercommitted") == "True"
        for name in ("c1", "c2"):
            assert api.get("Profile", name).status.phase == "Ready"
        mgr.close()

    def test_unknown_parent_parks_then_resolves(self):
        api, mgr = self._world()
        api.create(Profile(metadata=ObjectMeta(name="child"),
                           spec=ProfileSpec(owner="o@x",
                                            parent="later")))
        mgr.run_until_idle()
        child = api.get("Profile", "child")
        cond = {c.type: (c.status, c.reason)
                for c in child.status.conditions}
        assert cond.get("TenantTree") == ("False", "UnknownParent")
        api.create(Profile(metadata=ObjectMeta(name="later"),
                           spec=ProfileSpec(owner="o@x")))
        mgr.run_until_idle(include_timers_within=60.0)
        child = api.get("Profile", "child")
        assert child.status.phase == "Ready"
        cond = {c.type: c.status for c in child.status.conditions}
        assert cond.get("TenantTree") == "True"
        mgr.close()

    def test_self_parent_and_cycle_fail(self):
        api, mgr = self._world()
        api.create(Profile(metadata=ObjectMeta(name="narcissus"),
                           spec=ProfileSpec(owner="o@x",
                                            parent="narcissus")))
        mgr.run_until_idle()
        assert api.get("Profile", "narcissus").status.phase == "Failed"
        mgr.close()


class TestTenantStormSmoke:
    """One small DRF-enforced tenant storm through the REAL control
    plane: the acceptance gate's invariants at test scale."""

    def test_small_tenant_storm_gates(self):
        from kubeflow_tpu.scheduler.benchmark import (
            DEFAULT_TENANT_SPECS,
            check_tenant_gates,
            run_schedule_storm,
        )

        rep = run_schedule_storm(
            policy="priority", num_jobs=24, seed=1,
            tenants=list(DEFAULT_TENANT_SPECS), drf=True)
        check_tenant_gates(rep)            # raises on any gate breach
        assert rep.converged
        assert rep.fairness_violations == 0
        assert rep.inversions == 0
        assert rep.goodput["conserved"]
        tenants = rep.goodput["tenants"]
        assert len(tenants) >= 2
        # Shares/fair/deficit render from the same rows.
        for entry in tenants.values():
            assert entry["deficit"] == pytest.approx(
                entry["fair_share"] - entry["share"], abs=1e-6)
