"""Topology-aware gang scheduler (ISSUE 8): fleet model, placement,
preemption-as-policy through the shared eviction path, defragmentation,
the slice_assignment lifecycle, and the mixed-priority storm bench."""

import pytest

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    ComponentConfig,
    MeshAxesSpec,
    PlatformConfig,
    PlatformConfigSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.scheduler import (
    DefragController,
    Fleet,
    GangScheduler,
    PlacementEngine,
    parse_assignment,
    select_victims,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer


def make_job(name, *, ns="ml", prio=0, n=1, policy="restart",
             slice_type="v5e-16", backoff=0.0):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(
            slice_type=slice_type, num_slices=n,
            mesh=MeshAxesSpec(dp=-1), priority=prio,
            backoff_seconds=backoff, preemption_policy=policy,
        ),
    )


class Rig:
    """api + manager + TpuJobController(scheduler) + FakeKubelet."""

    def __init__(self, fleet_cap, *, pool_size=4, policy="priority",
                 defrag=False, defrag_threshold=0.4, outcome=None):
        self.registry = MetricsRegistry()
        self.tracer = Tracer()
        self.api = InMemoryApiServer(registry=self.registry,
                                     tracer=self.tracer)
        self.mgr = ControllerManager(self.api, self.registry,
                                     tracer=self.tracer)
        self.fleet = Fleet.from_capacity(fleet_cap, pool_size=pool_size)
        self.scheduler = GangScheduler(self.fleet, policy=policy,
                                       registry=self.registry,
                                       tracer=self.tracer)
        self.ctl = TpuJobController(self.api, self.registry,
                                    hbm_check=False,
                                    scheduler=self.scheduler,
                                    requeue_pending_s=3600.0)
        self.mgr.register(self.ctl)
        self.defrag = None
        if defrag:
            self.defrag = DefragController(
                self.api, self.registry, scheduler=self.scheduler,
                tracer=self.tracer, threshold=defrag_threshold,
                interval_s=0.0,
            )
            self.mgr.register(self.defrag)
        self.kubelet = FakeKubelet(self.api, self.registry,
                                   outcome=outcome or (lambda name: None))
        self.mgr.register(self.kubelet)

    def drain(self):
        self.mgr.kick_timers(2 * 3600.0)
        self.mgr.run_until_idle(max_iterations=100000)
        self.kubelet.tick()
        self.mgr.run_until_idle(max_iterations=100000)

    def job(self, name, ns="ml"):
        return self.api.get("TpuJob", name, ns)

    def close(self):
        self.mgr.close()


# --------------------------------------------------------------------------
# Fleet model
# --------------------------------------------------------------------------


class TestFleet:
    def test_pools_and_coords_from_topology_rank(self):
        fleet = Fleet.from_capacity({"v5e-16": 8}, pool_size=4)
        assert [p.pool_id for p in fleet.pools] == ["p00", "p01"]
        # v5e-16 is rank-2 (4x4): 4 units arrange as a 2x2 grid.
        assert fleet.pools[0].dims == (2, 2)
        assert sorted(u.coord for u in fleet.pools[0].units) == [
            (0, 0), (0, 1), (1, 0), (1, 1)]
        # Unit ids are stable, catalog-derived strings.
        assert fleet.pools[0].units[0].uid == "v5e-16/p00/u00"

    def test_allocate_release_idempotent(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        units = [u.uid for u in fleet.free("v5e-16")[:2]]
        fleet.allocate("job-a", units)
        assert fleet.assignment("job-a") == units
        assert len(fleet.free("v5e-16")) == 2
        with pytest.raises(ValueError):
            fleet.allocate("job-b", units)      # already taken
        assert fleet.release("job-a") == units
        assert fleet.release("job-a") == []     # idempotent
        assert fleet.release("never-seen") == []
        assert len(fleet.free("v5e-16")) == 4

    def test_fragmentation_metric(self):
        fleet = Fleet.from_capacity({"v5e-16": 8}, pool_size=4)
        # Empty fleet: NOT fragmented (pool walls are topology).
        assert fleet.fragmentation("v5e-16") == 0.0
        # Checkerboard one pool: free units at (0,0) and (1,1) are not
        # adjacent -> largest block 1 of a possible 4-wide pool block.
        p0 = fleet.pools[0]
        taken = [u.uid for u in p0.units if u.coord in ((0, 1), (1, 0))]
        fleet.allocate("holes", taken)
        # Other pool fully free (block of 4): still 0 overall.
        assert fleet.fragmentation("v5e-16") == 0.0
        filler = [u.uid for u in fleet.pools[1].units]
        fleet.allocate("filler", filler)
        # Only the checkerboard remains: largest block 1, free 2.
        assert fleet.fragmentation("v5e-16") == pytest.approx(0.5)

    def test_utilization(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        assert fleet.utilization() == 0.0
        fleet.allocate("a", [fleet.pools[0].units[0].uid])
        assert fleet.utilization() == pytest.approx(0.25)


# --------------------------------------------------------------------------
# Placement engine
# --------------------------------------------------------------------------


class TestPlacement:
    def test_single_slice_best_fit_prefers_tightest_pool(self):
        fleet = Fleet.from_capacity({"v5e-16": 8}, pool_size=4)
        engine = PlacementEngine(fleet)
        # Make p01 tighter (3 free) than p00 (4 free).
        fleet.allocate("x", [fleet.pools[1].units[0].uid])
        p = engine.find("v5e-16", 1)
        assert p.pools == ["p01"] and not p.spilled

    def test_multislice_prefers_one_pool_minimal_spread(self):
        fleet = Fleet.from_capacity({"v5e-16": 8}, pool_size=4)
        engine = PlacementEngine(fleet)
        p = engine.find("v5e-16", 2)
        assert len(p.unit_uids) == 2 and p.pools in (["p00"], ["p01"])
        coords = [fleet.unit(u).coord for u in p.unit_uids]
        assert abs(coords[0][0] - coords[1][0]) \
            + abs(coords[0][1] - coords[1][1]) == 1  # adjacent
        assert not p.spilled

    def test_spill_only_when_no_single_pool_fits(self):
        fleet = Fleet.from_capacity({"v5e-16": 8}, pool_size=4)
        engine = PlacementEngine(fleet)
        # 2 free in each pool -> a 4-wide gang must cross pools.
        fleet.allocate("a", [u.uid for u in fleet.pools[0].units[:2]])
        fleet.allocate("b", [u.uid for u in fleet.pools[1].units[:2]])
        p = engine.find("v5e-16", 4)
        assert p.spilled and sorted(p.pools) == ["p00", "p01"]
        assert engine.find("v5e-16", 5) is None     # only 4 free

    def test_extra_free_what_if(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        engine = PlacementEngine(fleet)
        held = [u.uid for u in fleet.pools[0].units]
        fleet.allocate("victim", held)
        assert engine.find("v5e-16", 2) is None
        p = engine.find("v5e-16", 2, extra_free=set(held[:2]))
        assert p is not None
        # The what-if never mutates the fleet.
        assert fleet.assignment("victim") == held

    def test_assignment_render_parse_roundtrip(self):
        fleet = Fleet.from_capacity({"v5e-16": 4}, pool_size=4)
        engine = PlacementEngine(fleet)
        p = engine.find("v5e-16", 2)
        assert parse_assignment(p.render()) == p.unit_uids
        # Legacy (pre-scheduler) strings parse as "no placement".
        assert parse_assignment("v5e-16x2") is None
        assert parse_assignment("") is None


# --------------------------------------------------------------------------
# Victim selection
# --------------------------------------------------------------------------


class TestVictimSelection:
    def _candidates(self):
        jobs = []
        for i, prio in enumerate([0, 0, 5]):
            j = make_job(f"v{i}", prio=prio)
            j.metadata.uid = f"uid-{i}"
            j.status.phase = "Running"
            jobs.append(j)
        units = {"uid-0": ["u0"], "uid-1": ["u1"], "uid-2": ["u2"]}
        return jobs, units

    def test_minimal_set_lowest_priority_first(self):
        jobs, units = self._candidates()
        picked = select_victims(
            jobs,
            fits=lambda extra: len(extra) >= 1,
            units_of=lambda j: units[j.metadata.uid],
        )
        # One victim suffices; the priority-5 gang must not be chosen.
        assert [v.metadata.name for v in picked] == ["v0"]

    def test_inclusion_prune_drops_unneeded_victims(self):
        jobs, units = self._candidates()
        picked = select_victims(
            jobs,
            fits=lambda extra: "u1" in extra,   # only v1's unit matters
            units_of=lambda j: units[j.metadata.uid],
        )
        assert [v.metadata.name for v in picked] == ["v1"]

    def test_none_when_even_everything_cannot_fit(self):
        jobs, units = self._candidates()
        assert select_victims(
            jobs, fits=lambda extra: False,
            units_of=lambda j: units[j.metadata.uid],
        ) is None


# --------------------------------------------------------------------------
# Controller integration: the slice_assignment lifecycle (satellite 4)
# --------------------------------------------------------------------------


class TestLifecycle:
    def test_assigned_on_place_with_span(self):
        rig = Rig({"v5e-16": 4})
        rig.api.create(make_job("a", n=2))
        rig.drain()
        job = rig.job("a")
        units = parse_assignment(job.status.slice_assignment)
        assert units is not None and len(units) == 2
        assert job.status.phase == "Running"
        assert rig.fleet.assignment(job.metadata.uid) == units
        spans = rig.tracer.spans("schedule.place")
        assert len(spans) == 1 and spans[0].attrs["num_slices"] == 2
        rig.close()

    def test_cleared_on_preempt_and_reassigned_after_backoff(self):
        from kubeflow_tpu.chaos import SlicePreemptor

        rig = Rig({"v5e-16": 2}, pool_size=2)
        rig.api.create(make_job("a", backoff=0.2))
        rig.drain()
        job = rig.job("a")
        first = parse_assignment(job.status.slice_assignment)
        assert first
        pre = SlicePreemptor(rig.api, seed=3)
        assert pre.preempt(job) > 0
        rig.mgr.run_until_idle(max_iterations=100000)
        job = rig.job("a")
        # Preemption, not failure: budget untouched, gang torn down, and
        # the assignment was CLEARED then re-placed (capacity was free,
        # so the scheduler hands the gang a slice set again immediately
        # — the clear itself is visible as a SECOND placement decision).
        assert job.status.phase == "Restarting"
        assert job.status.preemptions == 1 and job.status.restarts == 0
        assert [e["job"] for e in rig.scheduler.placement_log] == ["a", "a"]
        assert rig.api.list("Pod", namespace="ml") == []  # backoff holds
        # After the backoff the gang's pods recreate on the new set.
        import time
        time.sleep(0.25)
        rig.drain()
        job = rig.job("a")
        assert parse_assignment(job.status.slice_assignment)
        assert job.status.phase == "Running"
        rig.close()

    def test_released_on_success(self):
        done = set()
        rig = Rig({"v5e-16": 2}, pool_size=2,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        rig.api.create(make_job("a"))
        rig.drain()
        uid = rig.job("a").metadata.uid
        assert rig.fleet.assignment(uid)
        done.add("a")
        rig.drain()
        rig.drain()
        job = rig.job("a")
        assert job.status.phase == "Succeeded"
        assert rig.fleet.assignment(uid) is None
        # The record of WHERE it ran survives in status.
        assert parse_assignment(job.status.slice_assignment)
        rig.close()

    def test_stable_across_platform_restart_wal_replay(self, tmp_path):
        from kubeflow_tpu.controlplane.platform import Platform

        state = str(tmp_path / "state")
        cfg = PlatformConfig(
            metadata=ObjectMeta(name="kf"),
            spec=PlatformConfigSpec(components=[
                ComponentConfig(name="tpujob-controller",
                                params={"fleet": "v5e-16=4",
                                        "poolSize": "4"}),
                ComponentConfig(name="fake-kubelet"),
            ]),
        )
        platform = Platform()
        platform.attach_wal(state)
        platform.apply_config(cfg)
        platform.api.create(make_job("a", n=2))
        platform.reconcile()
        job = platform.api.get("TpuJob", "a", "ml")
        units_before = parse_assignment(job.status.slice_assignment)
        assert units_before and job.status.phase == "Running"
        platform.save(state)

        # A fresh process loads the WAL-backed state: the scheduler must
        # re-pin the EXACT units — a restart never migrates a gang.
        reloaded = Platform.load(state)
        n = reloaded.reconcile()
        job2 = reloaded.api.get("TpuJob", "a", "ml")
        assert parse_assignment(job2.status.slice_assignment) \
            == units_before
        assert reloaded.scheduler.assignment_of(job2.metadata.uid) \
            == units_before
        assert job2.status.phase == "Running"


# --------------------------------------------------------------------------
# Priority preemption end-to-end
# --------------------------------------------------------------------------


class TestPriorityPreemption:
    def test_high_priority_evicts_minimal_lower_set(self):
        rig = Rig({"v5e-16": 4})
        for i in range(4):
            rig.api.create(make_job(f"low-{i}", prio=0))
        rig.drain()
        rig.api.create(make_job("hi", prio=10, n=2))
        rig.drain()
        rig.drain()
        hi = rig.job("hi")
        assert hi.status.phase == "Running"
        assert len(parse_assignment(hi.status.slice_assignment)) == 2
        evicted = [rig.job(f"low-{i}") for i in range(4)]
        preempted = [j for j in evicted if j.status.preemptions == 1]
        running = [j for j in evicted if j.status.phase == "Running"]
        assert len(preempted) == 2 and len(running) == 2  # minimal set
        for j in preempted:
            assert j.status.phase == "Pending"
            assert j.status.slice_assignment == ""
        # Decision surfaces: spans, log, zero inversions.
        assert len(rig.tracer.spans("schedule.preempt")) == 2
        log = rig.scheduler.preemption_log
        assert all(e["victim_priority"] < e["requester_priority"]
                   for e in log)
        inv = rig.registry.get(
            "kftpu_scheduler_priority_inversions_total")
        assert inv.value() == 0
        # Victims carry the SchedulerPreempted event.
        events = [e for e in rig.api.list("Event", namespace="ml")
                  if e.reason == "SchedulerPreempted"]
        assert len(events) == 2
        rig.close()

    def test_never_evicts_equal_or_higher_priority(self):
        rig = Rig({"v5e-16": 2}, pool_size=2)
        rig.api.create(make_job("a", prio=5, n=2))
        rig.drain()
        rig.api.create(make_job("b", prio=5, n=2))
        rig.drain()
        rig.drain()
        assert rig.job("a").status.phase == "Running"
        b = rig.job("b")
        assert b.status.phase == "Pending"
        assert b.status.preemptions == 0
        assert rig.scheduler.preemption_log == []
        rig.close()

    def test_preemption_policy_fail_gangs_are_not_victims(self):
        rig = Rig({"v5e-16": 2}, pool_size=2)
        rig.api.create(make_job("pinned", prio=0, n=2, policy="fail"))
        rig.drain()
        rig.api.create(make_job("hi", prio=10, n=2))
        rig.drain()
        assert rig.job("pinned").status.phase == "Running"
        assert rig.job("hi").status.phase == "Pending"
        rig.close()

    def test_evicted_gang_replaces_when_capacity_frees(self):
        done = set()
        rig = Rig({"v5e-16": 2}, pool_size=2,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        rig.api.create(make_job("low", prio=0, n=2))
        rig.drain()
        rig.api.create(make_job("hi", prio=10, n=2))
        rig.drain()
        rig.drain()
        assert rig.job("hi").status.phase == "Running"
        assert rig.job("low").status.phase == "Pending"
        done.add("hi")
        rig.drain()
        rig.drain()
        assert rig.job("hi").status.phase == "Succeeded"
        low = rig.job("low")
        assert low.status.phase == "Running"
        assert parse_assignment(low.status.slice_assignment)
        rig.close()


# --------------------------------------------------------------------------
# FIFO baseline policy
# --------------------------------------------------------------------------


class TestFifoPolicy:
    def test_head_of_line_blocking(self):
        rig = Rig({"v5e-16": 4}, policy="fifo")
        rig.api.create(make_job("wide", n=4))
        rig.drain()
        assert rig.job("wide").status.phase == "Running"
        rig.api.create(make_job("wide-2", n=4))   # head of line, no room
        rig.api.create(make_job("small", n=1))    # MUST NOT backfill
        rig.drain()
        assert rig.job("wide-2").status.phase == "Pending"
        small = rig.job("small")
        assert small.status.phase == "Pending"
        reasons = {c.reason for c in small.status.conditions
                   if c.type == "Admitted"}
        assert "HeadOfLine" in reasons
        assert rig.scheduler.preemption_log == []
        rig.close()


# --------------------------------------------------------------------------
# Shared eviction path (satellite 2): chaos == policy transitions
# --------------------------------------------------------------------------


class TestSharedEvictionPath:
    @staticmethod
    def _run_one(evict):
        """Identical rig; evict(api, job) fires the eviction. Returns the
        observable transition: status fields + event reasons."""
        rig = Rig({"v5e-16": 2}, pool_size=2)
        rig.api.create(make_job("a", n=2))
        rig.drain()
        job = rig.job("a")
        evict(rig.api, job)
        rig.mgr.run_until_idle(max_iterations=100000)
        rig.drain()
        job = rig.job("a")
        out = {
            "phase_after": job.status.phase,
            "preemptions": job.status.preemptions,
            "restarts": job.status.restarts,
            "assignment": job.status.slice_assignment,
            "events": sorted(
                e.reason
                for e in rig.api.list("Event", namespace="ml")
                if e.involved_name == "a"
                and e.reason in ("SlicePreempted", "GangRestart",
                                 "JobFailed")),
        }
        rig.close()
        return out

    def test_chaos_and_scheduler_eviction_transitions_identical(self):
        from kubeflow_tpu.chaos import SlicePreemptor
        from kubeflow_tpu.scheduler import preempt_gang

        def chaos_evict(api, job):
            pre = SlicePreemptor(api, seed=0)
            # Both slice groups — the whole gang, like the scheduler.
            assert pre.preempt(job, slice_id=0) > 0
            assert pre.preempt(job, slice_id=1) > 0

        def policy_evict(api, job):
            assert preempt_gang(api, job) > 0

        chaos = self._run_one(chaos_evict)
        policy = self._run_one(policy_evict)
        assert chaos == policy
        # Both re-place after the teardown (restart policy, no budget).
        assert chaos["preemptions"] == 1 and chaos["restarts"] == 0
        assert chaos["events"] == ["SlicePreempted"]


# --------------------------------------------------------------------------
# Defragmentation
# --------------------------------------------------------------------------


class TestDefrag:
    def _fragment(self, rig):
        """Fill both pools with x1 gangs, then finish a checkerboard of
        them so the free units are scattered holes."""
        for i in range(8):
            rig.api.create(make_job(f"j{i}", prio=0))
        rig.drain()
        return {f"j{i}" for i in range(8)}

    def test_sweep_migrates_to_consolidate(self):
        done = set()
        # Unregistered controller: sweeps run only when the test says so,
        # keeping the fragmented before-state observable.
        rig = Rig({"v5e-16": 8}, pool_size=4,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        defrag = DefragController(
            rig.api, rig.registry, scheduler=rig.scheduler,
            tracer=rig.tracer, threshold=0.4, interval_s=0.0)
        defrag.reader = rig.api
        self._fragment(rig)
        # Finish alternating jobs -> holes in both pools.
        by_unit = {}
        for i in range(8):
            job = rig.job(f"j{i}")
            units = rig.fleet.assignment(job.metadata.uid)
            by_unit[units[0]] = f"j{i}"
        # Finish the jobs on each pool's DIAGONAL (non-adjacent) units:
        # 4 free slices, largest contiguous block 1 — maximal holes.
        for pool in rig.fleet.pools:
            for u in pool.units:
                if u.coord in ((0, 0), (1, 1)):
                    done.add(by_unit[u.uid])
        rig.drain()
        frag_before = rig.fleet.fragmentation("v5e-16")
        assert frag_before > 0.4
        migrated = defrag.sweep()
        assert migrated == 1
        assert len(rig.tracer.spans("schedule.defrag")) == 1
        assert rig.registry.get(
            "kftpu_scheduler_defrag_migrations_total").value() == 1
        # The migrated gang restarts (preemption semantics) and re-places
        # into the consolidated spot; fragmentation drops.
        rig.drain()
        rig.drain()
        assert rig.fleet.fragmentation("v5e-16") < frag_before
        jobs = [rig.job(f"j{i}") for i in range(8)]
        assert sum(j.status.preemptions for j in jobs) == 1
        events = [e for e in rig.api.list("Event", namespace="ml")
                  if e.reason == "DefragMigration"]
        assert len(events) == 1
        rig.close()

    def test_no_migration_below_threshold_or_without_gain(self):
        rig = Rig({"v5e-16": 4}, defrag=True)
        rig.api.create(make_job("a"))
        rig.drain()
        assert rig.defrag.sweep() == 0
        assert rig.scheduler.defrag_log == []
        rig.close()

    def test_fail_policy_gangs_never_migrated(self):
        done = set()
        rig = Rig({"v5e-16": 4}, pool_size=2, defrag=True,
                  outcome=lambda name: "Succeeded"
                  if name.rsplit("-worker-", 1)[0] in done else None)
        # Two fail-policy gangs, one per pool; finish nothing: then
        # finish fillers to fragment — candidates are all fail-policy.
        for i in range(4):
            rig.api.create(make_job(
                f"j{i}", policy="fail"))
        rig.drain()
        for i in (1, 2):
            done.add(f"j{i}")
        rig.drain()
        assert rig.defrag.sweep() == 0
        rig.close()


# --------------------------------------------------------------------------
# The storm bench (and the CI smoke built on it)
# --------------------------------------------------------------------------


class TestScheduleStorm:
    def test_scheduler_beats_fifo_deterministically(self):
        from kubeflow_tpu.scheduler.benchmark import (
            check_storm_gates,
            run_schedule_storm,
        )

        common = dict(num_jobs=30, seed=2,
                      fleet_capacity={"v5e-16": 8}, pool_size=4)
        fifo = run_schedule_storm(policy="fifo", **common)
        sched = run_schedule_storm(policy="priority", **common)
        for rep in (fifo, sched):
            check_storm_gates(rep)
            assert rep.converged and rep.accounting_exact
            assert rep.succeeded == rep.submitted
            assert rep.inversions == 0
        assert sched.utilization > fifo.utilization
        assert sched.ttp_ticks["high"]["p95"] \
            < fifo.ttp_ticks["high"]["p95"]
        # Same seed, same storm: replays are tick-deterministic.
        again = run_schedule_storm(policy="priority", **common)
        assert again.summary() == sched.summary()

    def test_storm_with_chaos_burst_keeps_accounting(self):
        from kubeflow_tpu.scheduler.benchmark import (
            check_storm_gates,
            run_schedule_storm,
        )

        rep = run_schedule_storm(
            num_jobs=20, policy="priority", seed=3,
            fleet_capacity={"v5e-16": 8}, pool_size=4,
            chaos_at_tick=4, chaos_preempts=2,
        )
        check_storm_gates(rep)
        assert rep.chaos_preemptions > 0
        assert rep.converged and rep.succeeded == rep.submitted

    def test_ci_schedule_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_schedule_smoke

        run_schedule_smoke(num_jobs=16)


class TestSchedulerWithLedger:
    def test_managed_types_bypass_ledger_so_preemption_still_works(self):
        """scheduler= and ledger= together (a sharded fleet deployment):
        scheduler-managed slice types must skip the ledger exactly like
        the local capacity count — victims hold ledger reservations
        until terminal, so gating on the ledger would park the
        high-priority gang before the preemption path ever ran."""
        from kubeflow_tpu.controlplane.ledger import (
            LedgerService,
            LocalLedgerClient,
        )
        import multiprocessing

        _client_end, serve_end = multiprocessing.Pipe()
        svc = LedgerService({"v5e-16": 2}, serve_end)
        ledger = LocalLedgerClient(svc)
        rig = Rig({"v5e-16": 2}, pool_size=2)
        rig.ctl.ledger = ledger
        rig.api.create(make_job("low", prio=0, n=2))
        rig.drain()
        rig.api.create(make_job("hi", prio=10, n=2))
        rig.drain()
        rig.drain()
        assert rig.job("hi").status.phase == "Running"
        assert rig.job("low").status.phase == "Pending"
        # The fleet, not the ledger, accounted the managed type.
        assert ledger.snapshot()["reservations"] == 0
        rig.close()
