"""Paged KV-cache block allocator (serving/blocks.py): exact alloc/free
accounting under the goodput-ledger discipline — every block freed
exactly once, no use-after-free across retire/admit churn, conservation
(allocated == freed + live, pool exactly partitioned) after every
operation. Plus the prefix-key derivation and the seeded session-replay
trace generator the affinity bench drives."""

import random

import pytest

from kubeflow_tpu.serving.blocks import (
    BlockAccountingError,
    BlocksExhausted,
    KVBlockAllocator,
    prefix_key,
)


class TestAllocFree:
    def test_alloc_free_round_trip(self):
        a = KVBlockAllocator(8, 16)
        got = a.alloc("s1", 40)            # ceil(40/16) = 3 blocks
        assert len(got) == 3
        assert a.blocks_live == 3 and a.blocks_free == 5
        assert a.table("s1") == got
        assert a.free("s1") == 3
        assert a.blocks_live == 0 and a.blocks_free == 8
        assert a.blocks_allocated_total == 3
        assert a.blocks_freed_total == 3
        a.check_conservation()

    def test_zero_token_request_pins_one_block(self):
        a = KVBlockAllocator(4, 16)
        assert a.blocks_for_tokens(0) == 1
        assert len(a.alloc("s", 0)) == 1

    def test_extend_grows_table(self):
        a = KVBlockAllocator(8, 16)
        a.alloc("s", 16)                    # 1 block
        assert a.extend("s", 16) == []      # already covered
        new = a.extend("s", 33)             # needs 3 total
        assert len(new) == 2
        assert len(a.table("s")) == 3
        a.check_conservation()

    def test_exhaustion_raises_and_changes_nothing(self):
        a = KVBlockAllocator(2, 16)
        a.alloc("big", 32)
        with pytest.raises(BlocksExhausted):
            a.alloc("more", 1)
        with pytest.raises(BlocksExhausted):
            a.extend("big", 48)
        assert a.blocks_live == 2 and a.blocks_free == 0
        assert a.table("more") is None
        a.check_conservation()

    def test_double_free_raises(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        a.free("s")
        with pytest.raises(BlockAccountingError, match="double free"):
            a.free("s")
        a.check_conservation()

    def test_free_unknown_sequence_raises(self):
        a = KVBlockAllocator(4, 16)
        with pytest.raises(BlockAccountingError):
            a.free("ghost")

    def test_use_after_free_raises(self):
        """A retired sequence's table is GONE: extend (the decode loop's
        growth path) on it is an accounting error, never a silent
        re-allocation over another sequence's rows."""
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        a.free("s")
        with pytest.raises(BlockAccountingError, match="use-after-free"):
            a.extend("s", 32)

    def test_double_alloc_raises(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        with pytest.raises(BlockAccountingError, match="double alloc"):
            a.alloc("s", 16)

    def test_freed_blocks_are_reusable_by_next_sequence(self):
        """The retire/admit handoff: blocks freed by one sequence back a
        fresh one immediately, and the id space never double-books."""
        a = KVBlockAllocator(2, 16)
        first = a.alloc("a", 32)
        a.free("a")
        second = a.alloc("b", 32)
        assert sorted(first) == sorted(second)
        a.check_conservation()


class TestConservationUnderChurn:
    def test_seeded_churn_conserves_after_every_op(self):
        """Random admit/extend/retire storm: the invariant (allocated ==
        freed + live, free list + tables partition the id space) must
        hold after EVERY operation, and the final drain returns the pool
        byte-exactly."""
        rng = random.Random(20260804)
        a = KVBlockAllocator(24, 8)
        live = {}
        for i in range(600):
            op = rng.random()
            if op < 0.45 or not live:
                sid = f"s{i}"
                tokens = rng.randrange(1, 80)
                try:
                    a.alloc(sid, tokens)
                    live[sid] = tokens
                except BlocksExhausted:
                    pass
            elif op < 0.70:
                sid = rng.choice(list(live))
                grown = live[sid] + rng.randrange(1, 32)
                try:
                    a.extend(sid, grown)
                    live[sid] = grown
                except BlocksExhausted:
                    pass
            else:
                sid = rng.choice(list(live))
                a.free(sid)
                del live[sid]
            a.check_conservation()
        for sid in list(live):
            a.free(sid)
        a.check_conservation()
        assert a.blocks_live == 0
        assert a.blocks_free == a.total_blocks
        assert a.blocks_allocated_total == a.blocks_freed_total
        assert a.high_water_blocks <= a.total_blocks

    def test_snapshot_shape(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 20)
        snap = a.snapshot()
        assert snap["kv_blocks_total"] == 4
        assert snap["kv_blocks_live"] == 2
        assert snap["kv_blocks_free"] == 2
        assert snap["kv_conservation_ok"] is True
        assert snap["kv_sequences_live"] == 1


class TestPrefixKey:
    def test_shared_head_shares_key(self):
        sys_prompt = list(range(100, 164))
        a = prefix_key(sys_prompt + [1, 2, 3])
        b = prefix_key(sys_prompt + [9, 9])
        assert a == b                       # same first 32 tokens
        assert a != prefix_key(list(range(200, 264)))

    def test_key_is_stable_and_tagged(self):
        assert prefix_key([1, 2, 3]) == prefix_key([1, 2, 3])
        assert prefix_key([1, 2, 3]).startswith("p:")


class TestSessionTrace:
    def test_same_seed_identical_trace(self):
        from kubeflow_tpu.tools.loadtest import gen_session_trace

        a = gen_session_trace(seed=7, rate_qps=20, duration_s=2.0)
        b = gen_session_trace(seed=7, rate_qps=20, duration_s=2.0)
        assert a == b
        assert a != gen_session_trace(seed=8, rate_qps=20, duration_s=2.0)

    def test_trace_shape_and_growth(self):
        from kubeflow_tpu.tools.loadtest import gen_session_trace

        trace = gen_session_trace(seed=3, sessions=4, rate_qps=30,
                                  duration_s=2.0, system_tokens=48,
                                  user_tokens=12)
        assert len(trace) == 60
        offsets = [e["t"] for e in trace]
        assert offsets == sorted(offsets)   # open-loop schedule
        by_session = {}
        for e in trace:
            assert e["gen_tokens"] >= 1
            assert e["prompt_tokens"] >= 48 + 12
            by_session.setdefault(e["session"], []).append(
                e["prompt_tokens"])
        assert len(by_session) == 4
        # Multi-turn: some session's prompt grows with history, and the
        # sliding-window cap bounds every prompt.
        assert any(p[0] < p[-1] for p in by_session.values())
        assert all(p <= 48 + 48 + 12 for ps in by_session.values()
                   for p in ps)

    def test_affinity_key_derivation_matches_lb(self):
        """Session-keyed bodies and long prompts key; short keyless
        prompts stay load-routed (the least-loaded contract holds for
        trivial traffic)."""
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        key = ServingLoadBalancer.affinity_key
        assert key({"session": "abc"}) == "s:abc"
        assert key({"tokens": list(range(32))}) == prefix_key(
            list(range(32)))
        assert key({"tokens": [1, 2, 3]}) is None
        assert key({"tokens": "nope"}) is None
        assert key({}) is None
