"""Paged KV-cache block allocator (serving/blocks.py): exact alloc/free
accounting under the goodput-ledger discipline — every block freed
exactly once, no use-after-free across retire/admit churn, conservation
(allocated == freed + live, pool exactly partitioned) after every
operation. Plus the prefix-key derivation and the seeded session-replay
trace generator the affinity bench drives."""

import random

import pytest

from kubeflow_tpu.serving.blocks import (
    BlockAccountingError,
    BlocksExhausted,
    KVBlockAllocator,
    prefix_key,
)


class TestAllocFree:
    def test_alloc_free_round_trip(self):
        a = KVBlockAllocator(8, 16)
        got = a.alloc("s1", 40)            # ceil(40/16) = 3 blocks
        assert len(got) == 3
        assert a.blocks_live == 3 and a.blocks_free == 5
        assert a.table("s1") == got
        assert a.free("s1") == 3
        assert a.blocks_live == 0 and a.blocks_free == 8
        assert a.blocks_allocated_total == 3
        assert a.blocks_freed_total == 3
        a.check_conservation()

    def test_zero_token_request_pins_one_block(self):
        a = KVBlockAllocator(4, 16)
        assert a.blocks_for_tokens(0) == 1
        assert len(a.alloc("s", 0)) == 1

    def test_extend_grows_table(self):
        a = KVBlockAllocator(8, 16)
        a.alloc("s", 16)                    # 1 block
        assert a.extend("s", 16) == []      # already covered
        new = a.extend("s", 33)             # needs 3 total
        assert len(new) == 2
        assert len(a.table("s")) == 3
        a.check_conservation()

    def test_exhaustion_raises_and_changes_nothing(self):
        a = KVBlockAllocator(2, 16)
        a.alloc("big", 32)
        with pytest.raises(BlocksExhausted):
            a.alloc("more", 1)
        with pytest.raises(BlocksExhausted):
            a.extend("big", 48)
        assert a.blocks_live == 2 and a.blocks_free == 0
        assert a.table("more") is None
        a.check_conservation()

    def test_double_free_raises(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        a.free("s")
        with pytest.raises(BlockAccountingError, match="double free"):
            a.free("s")
        a.check_conservation()

    def test_free_unknown_sequence_raises(self):
        a = KVBlockAllocator(4, 16)
        with pytest.raises(BlockAccountingError):
            a.free("ghost")

    def test_use_after_free_raises(self):
        """A retired sequence's table is GONE: extend (the decode loop's
        growth path) on it is an accounting error, never a silent
        re-allocation over another sequence's rows."""
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        a.free("s")
        with pytest.raises(BlockAccountingError, match="use-after-free"):
            a.extend("s", 32)

    def test_double_alloc_raises(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 16)
        with pytest.raises(BlockAccountingError, match="double alloc"):
            a.alloc("s", 16)

    def test_freed_blocks_are_reusable_by_next_sequence(self):
        """The retire/admit handoff: blocks freed by one sequence back a
        fresh one immediately, and the id space never double-books."""
        a = KVBlockAllocator(2, 16)
        first = a.alloc("a", 32)
        a.free("a")
        second = a.alloc("b", 32)
        assert sorted(first) == sorted(second)
        a.check_conservation()


class TestConservationUnderChurn:
    def test_seeded_churn_conserves_after_every_op(self):
        """Random admit/extend/retire storm: the invariant (allocated ==
        freed + live, free list + tables partition the id space) must
        hold after EVERY operation, and the final drain returns the pool
        byte-exactly."""
        rng = random.Random(20260804)
        a = KVBlockAllocator(24, 8)
        live = {}
        for i in range(600):
            op = rng.random()
            if op < 0.45 or not live:
                sid = f"s{i}"
                tokens = rng.randrange(1, 80)
                try:
                    a.alloc(sid, tokens)
                    live[sid] = tokens
                except BlocksExhausted:
                    pass
            elif op < 0.70:
                sid = rng.choice(list(live))
                grown = live[sid] + rng.randrange(1, 32)
                try:
                    a.extend(sid, grown)
                    live[sid] = grown
                except BlocksExhausted:
                    pass
            else:
                sid = rng.choice(list(live))
                a.free(sid)
                del live[sid]
            a.check_conservation()
        for sid in list(live):
            a.free(sid)
        a.check_conservation()
        assert a.blocks_live == 0
        assert a.blocks_free == a.total_blocks
        assert a.blocks_allocated_total == a.blocks_freed_total
        assert a.high_water_blocks <= a.total_blocks

    def test_snapshot_shape(self):
        a = KVBlockAllocator(4, 16)
        a.alloc("s", 20)
        snap = a.snapshot()
        assert snap["kv_blocks_total"] == 4
        assert snap["kv_blocks_live"] == 2
        assert snap["kv_blocks_free"] == 2
        assert snap["kv_conservation_ok"] is True
        assert snap["kv_sequences_live"] == 1


class TestCopyOnWriteSharing:
    """Refcounted COW prefix sharing (ISSUE 18): shared leading blocks
    map to the SAME physical ids, a write forks first, and conservation
    extends to prove free + unique live blocks partition the id space
    with refcounts summing to table references."""

    def test_shared_alloc_pins_physical_blocks_once(self):
        a = KVBlockAllocator(8, 16)
        t1 = a.alloc("s1", 48)              # 3 physical blocks
        t2 = a.alloc("s2", 48, shared=t1[:2])
        assert t2[:2] == t1[:2]             # same physical ids
        assert t2[2] not in t1
        assert a.blocks_live == 4           # 3 + 1 unique, not 6
        assert a.table_refs == 6
        assert a.blocks_shared == 2
        assert a.blocks_allocated_total == 4  # physical pops only
        assert a.shared_refs_total == 2
        a.check_conservation()

    def test_free_shared_reader_keeps_pages_live(self):
        """Retiring one reader of a shared prefix must not free pages
        its siblings still attend over."""
        a = KVBlockAllocator(8, 16)
        t1 = a.alloc("s1", 32)
        a.alloc("s2", 48, shared=t1)
        assert a.free("s2") == 1            # only its private tail block
        assert a.blocks_live == 2
        assert a.table("s1") == t1
        a.check_conservation()
        assert a.free("s1") == 2            # last reference frees
        assert a.blocks_free == a.total_blocks
        assert a.blocks_allocated_total == a.blocks_freed_total
        a.check_conservation()

    def test_double_free_of_shared_block_raises(self):
        """Forging a duplicate reference (the double-free-of-shared
        corruption) trips the refcount check instead of returning the
        block to the free list twice."""
        a = KVBlockAllocator(8, 16)
        t1 = a.alloc("s1", 16)
        a.alloc("s2", 16, shared=t1)
        a.free("s1")
        a.free("s2")                        # refcount hits 0, freed once
        with pytest.raises(BlockAccountingError, match="double free"):
            a.free("s2")
        a.check_conservation()

    def test_write_fork_under_shared_refcount_copies(self):
        a = KVBlockAllocator(8, 16)
        t1 = a.alloc("s1", 32)
        t2 = a.alloc("s2", 32, shared=t1)
        fork = a.write_fork("s2", 1)
        assert fork is not None
        old, new = fork
        assert old == t1[1] and new not in t1
        assert a.table("s2") == [t2[0], new]
        assert a.table("s1") == t1          # owner untouched
        assert a.cow_copies_total == 1
        assert a.blocks_shared == 1         # block 0 still shared
        a.check_conservation()

    def test_write_fork_exclusive_owner_is_noop(self):
        a = KVBlockAllocator(8, 16)
        t = a.alloc("s", 32)
        assert a.write_fork("s", 0) is None
        assert a.table("s") == t
        assert a.cow_copies_total == 0
        a.check_conservation()

    def test_write_fork_exhausted_raises(self):
        a = KVBlockAllocator(2, 16)
        t1 = a.alloc("s1", 16)
        a.alloc("s2", 32, shared=t1)        # pool now full
        with pytest.raises(BlocksExhausted):
            a.write_fork("s2", 0)
        a.check_conservation()

    def test_write_fork_unknown_sequence_raises(self):
        a = KVBlockAllocator(4, 16)
        with pytest.raises(BlockAccountingError):
            a.write_fork("ghost", 0)
        a.alloc("s", 16)
        with pytest.raises(BlockAccountingError, match="table"):
            a.write_fork("s", 5)

    def test_shared_alloc_of_free_block_raises(self):
        """A prefix reference on a block that is not live (registry
        staleness across retire) is an accounting error, never a silent
        alias of someone else's future allocation."""
        a = KVBlockAllocator(4, 16)
        t = a.alloc("s1", 16)
        a.free("s1")
        with pytest.raises(BlockAccountingError, match="not live"):
            a.alloc("s2", 16, shared=t)
        a.check_conservation()

    def test_retire_while_shared_churn_conserves(self):
        """Seeded storm over a shared-prefix family: admits referencing a
        live owner's head, COW forks, and retires in random order — the
        two-layer invariant must hold after EVERY operation and the pool
        drains exactly."""
        rng = random.Random(20260807)
        a = KVBlockAllocator(32, 8)
        owner = a.alloc("owner", 32)        # 4-block shared head
        live = {}
        for i in range(400):
            op = rng.random()
            if op < 0.40:
                sid = f"s{i}"
                k = rng.randrange(0, 5)
                tokens = 32 + rng.randrange(0, 40)
                try:
                    a.alloc(sid, tokens, shared=owner[:k])
                    live[sid] = tokens
                except BlocksExhausted:
                    pass
            elif op < 0.60 and live:
                sid = rng.choice(list(live))
                pos = rng.randrange(
                    0, a.blocks_for_tokens(live[sid]))
                try:
                    a.write_fork(sid, pos)
                except BlocksExhausted:
                    pass
            elif live:
                sid = rng.choice(list(live))
                a.free(sid)
                del live[sid]
            a.check_conservation()
        for sid in list(live):
            a.free(sid)
        a.free("owner")
        a.check_conservation()
        assert a.blocks_live == 0 and a.blocks_shared == 0
        assert a.blocks_free == a.total_blocks
        assert a.blocks_allocated_total == a.blocks_freed_total

    def test_snapshot_reports_sharing(self):
        a = KVBlockAllocator(8, 16)
        t1 = a.alloc("s1", 32)
        a.alloc("s2", 32, shared=t1)
        a.write_fork("s2", 1)
        snap = a.snapshot()
        assert snap["kv_blocks_shared"] == 1
        assert snap["kv_table_refs"] == 4
        assert snap["kv_blocks_live"] == 3
        assert snap["kv_cow_copies_total"] == 1
        assert snap["kv_shared_refs_total"] == 2
        assert snap["kv_conservation_ok"] is True


class TestPrefixKey:
    def test_shared_head_shares_key(self):
        sys_prompt = list(range(100, 164))
        a = prefix_key(sys_prompt + [1, 2, 3])
        b = prefix_key(sys_prompt + [9, 9])
        assert a == b                       # same first 32 tokens
        assert a != prefix_key(list(range(200, 264)))

    def test_key_is_stable_and_tagged(self):
        assert prefix_key([1, 2, 3]) == prefix_key([1, 2, 3])
        assert prefix_key([1, 2, 3]).startswith("p:")


class TestSessionTrace:
    def test_same_seed_identical_trace(self):
        from kubeflow_tpu.tools.loadtest import gen_session_trace

        a = gen_session_trace(seed=7, rate_qps=20, duration_s=2.0)
        b = gen_session_trace(seed=7, rate_qps=20, duration_s=2.0)
        assert a == b
        assert a != gen_session_trace(seed=8, rate_qps=20, duration_s=2.0)

    def test_trace_shape_and_growth(self):
        from kubeflow_tpu.tools.loadtest import gen_session_trace

        trace = gen_session_trace(seed=3, sessions=4, rate_qps=30,
                                  duration_s=2.0, system_tokens=48,
                                  user_tokens=12)
        assert len(trace) == 60
        offsets = [e["t"] for e in trace]
        assert offsets == sorted(offsets)   # open-loop schedule
        by_session = {}
        for e in trace:
            assert e["gen_tokens"] >= 1
            assert e["prompt_tokens"] >= 48 + 12
            by_session.setdefault(e["session"], []).append(
                e["prompt_tokens"])
        assert len(by_session) == 4
        # Multi-turn: some session's prompt grows with history, and the
        # sliding-window cap bounds every prompt.
        assert any(p[0] < p[-1] for p in by_session.values())
        assert all(p <= 48 + 48 + 12 for ps in by_session.values()
                   for p in ps)

    def test_affinity_key_derivation_matches_lb(self):
        """Session-keyed bodies and long prompts key; short keyless
        prompts stay load-routed (the least-loaded contract holds for
        trivial traffic)."""
        from kubeflow_tpu.serving.lb import ServingLoadBalancer

        key = ServingLoadBalancer.affinity_key
        assert key({"session": "abc"}) == "s:abc"
        assert key({"tokens": list(range(32))}) == prefix_key(
            list(range(32)))
        assert key({"tokens": [1, 2, 3]}) is None
        assert key({"tokens": "nope"}) is None
        assert key({}) is None
