"""tpuctl CLI contract tests, mirroring the reference's kfctl CI contracts:
apply -> ready, second apply idempotent (kfctl_second_apply.py:12-24),
delete leaves nothing (kfctl_delete_test.py:44-71)."""

import io
import json
import os
import sys

import pytest
import yaml

from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.tools.tpuctl import main

PLATFORM_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: PlatformConfig
metadata:
  name: kubeflow-tpu
spec:
  defaultSliceType: v5e-16
"""

JOB_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: TpuJob
metadata:
  name: train1
  namespace: ml
spec:
  sliceType: v5e-16
  model: llama-tiny
"""

PROFILE_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: Profile
metadata:
  name: ml
spec:
  owner: alice@corp.com
  tpuChipQuota: 64
"""


def _write(tmp_path, name, content):
    p = tmp_path / name
    p.write_text(content)
    return str(p)


def _run(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr().out
    return rc, out


class TestTpuctl:
    def test_apply_get_status(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)

        rc, out = _run(["--state-dir", state, "apply", "-f", pf, "-f", prof,
                        "-f", job], capsys)
        assert rc == 0
        assert "applied PlatformConfig/kubeflow-tpu" in out
        assert "applied TpuJob/train1" in out

        rc, out = _run(["--state-dir", state, "get", "TpuJob"], capsys)
        assert rc == 0
        assert "train1" in out and "Running" in out

        rc, out = _run(["--state-dir", state, "get", "Pod", "-n", "ml"],
                       capsys)
        assert out.count("train1-worker") == 4

        rc, out = _run(["--state-dir", state, "status"], capsys)
        data = json.loads(out)
        assert "tpujob-controller" in data["components"]
        assert data["resources"]["TpuJob"]["ml/train1"] == "Running"

    def test_second_apply_idempotent(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)
        _run(["--state-dir", state, "apply", "-f", pf, "-f", prof, "-f", job],
             capsys)
        before = yaml.safe_load_all(
            open(os.path.join(state, "state.yaml"))
        )
        rv_before = {
            (d.get("kind"), d.get("metadata", {}).get("name")):
            d.get("metadata", {}).get("resourceVersion")
            for d in before if d and d.get("kind") != "PlatformState"
        }
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", prof,
                      "-f", job], capsys)
        assert rc == 0
        after = yaml.safe_load_all(open(os.path.join(state, "state.yaml")))
        rv_after = {
            (d.get("kind"), d.get("metadata", {}).get("name")):
            d.get("metadata", {}).get("resourceVersion")
            for d in after if d and d.get("kind") != "PlatformState"
        }
        changed = {
            k for k in rv_before
            if rv_after.get(k) != rv_before[k]
        }
        assert changed == set(), f"second apply mutated: {changed}"

    def test_delete_leaves_nothing(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        _run(["--state-dir", state, "apply", "-f", pf, "-f", prof, "-f", job],
             capsys)
        rc, out = _run(["--state-dir", state, "delete", "-f", job], capsys)
        assert rc == 0
        rc, out = _run(["--state-dir", state, "get", "Pod", "-n", "ml"],
                       capsys)
        assert "train1-worker" not in out
        rc, out = _run(["--state-dir", state, "get", "TpuJob", "-n", "ml"],
                       capsys)
        assert "train1" not in out

    def test_get_yaml_output(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        _run(["--state-dir", state, "apply", "-f", pf, "-f", prof], capsys)
        rc, out = _run(["--state-dir", state, "get", "Profile", "-o", "yaml"],
                       capsys)
        docs = list(yaml.safe_load_all(out))
        assert docs[0]["spec"]["owner"] == "alice@corp.com"

    def test_metrics_endpoint(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        _run(["--state-dir", state, "apply", "-f", pf], capsys)
        rc, out = _run(["--state-dir", state, "metrics"], capsys)
        assert rc == 0
        assert "# TYPE kftpu_tpujob_reconcile_total counter" in out


SERVING_INT8_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: Serving
metadata:
  name: llm8b
  namespace: ml
spec:
  model: llama3-8b
  sliceType: v5e-8
  maxLen: 512
  maxBatch: 32
  quantize: int8
  prefillBuckets: [128]
  replicas: 2
"""


class TestServingCrThroughTpuctl:
    def test_apply_driven_serving_requests_int8(self, tmp_path, capsys):
        """VERDICT r4 'done' criterion: a tpuctl-applied Serving CR can
        switch on the engine's int8 path — YAML camelCase -> serde ->
        controller -> KFTPU_SERVING_* env, end to end."""
        sd = str(tmp_path / "state")
        _run(["--state-dir", sd, "apply",
              "-f", _write(tmp_path, "p.yaml", PLATFORM_YAML),
              "-f", _write(tmp_path, "pr.yaml", PROFILE_YAML),
              "-f", _write(tmp_path, "s.yaml", SERVING_INT8_YAML)], capsys)
        pf = Platform.load(sd)
        sv = pf.api.get("Serving", "llm8b", "ml")
        assert sv.spec.quantize == "int8"
        assert sv.spec.prefill_buckets == [128]
        assert sv.spec.replicas == 2
        for i in range(2):
            pod = pf.api.get("Pod", f"llm8b-serving-{i}", "ml")
            env = {e.name: e.value for e in pod.spec.containers[0].env}
            assert env["KFTPU_SERVING_QUANTIZE"] == "int8"
            assert env["KFTPU_SERVING_PREFILL_BUCKETS"] == "128"
            assert env["KFTPU_SERVING_MAX_BATCH"] == "32"


class TestTpuctlTrace:
    def test_trace_timeline_for_completed_job(self, tmp_path, capsys):
        """ISSUE 4 acceptance: `tpuctl trace` on an applied TpuJob prints
        the causal write→reconcile timeline, and the reconcile span
        durations sum consistently with (i.e. fit inside) the observed
        convergence window."""
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", prof,
                      "-f", job], capsys)
        assert rc == 0

        rc, out = _run(["--state-dir", state, "trace", "TpuJob/train1",
                        "-n", "ml"], capsys)
        assert rc == 0
        assert "TRACE TpuJob/ml/train1" in out
        assert "create TpuJob ml/train1" in out
        assert "reconcile tpujob ml/train1" in out
        assert "links=" in out          # write-RV -> reconcile span links

        # Machine-readable form: the span durations must be consistent —
        # total reconcile time fits inside the timeline window.
        rc, out = _run(["--state-dir", state, "trace", "TpuJob/train1",
                        "-n", "ml", "-o", "json"], capsys)
        assert rc == 0
        spans = json.loads(out)
        assert spans
        t0 = min(s["start_unix"] for s in spans)
        t_end = max(s["start_unix"] + max(s["duration_s"], 0)
                    for s in spans)
        recons = [s for s in spans if s["name"] == "reconcile"
                  and s["attrs"].get("name") == "train1"]
        assert recons
        total_reconcile = sum(s["duration_s"] for s in recons)
        assert 0 < total_reconcile <= (t_end - t0) + 1e-9
        # Causality: at least one reconcile links back to a write span
        # present in the same dump, sharing its trace id.
        by_id = {s["span_id"]: s for s in spans}
        linked = [s for s in recons if s["links"]]
        assert linked
        src = by_id.get(linked[0]["links"][0][1])
        assert src is not None and src["name"].startswith("apiserver.")
        assert src["trace_id"] == linked[0]["trace_id"]

    def test_trace_unknown_object_fails(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        _run(["--state-dir", state, "apply", "-f", pf], capsys)
        rc = main(["--state-dir", state, "trace", "TpuJob/nope"])
        assert rc == 1

    def test_trace_without_state_fails(self, tmp_path, capsys):
        rc = main(["--state-dir", str(tmp_path / "empty"), "trace",
                   "TpuJob/x"])
        assert rc == 1


class TestTpuctlTop:
    def test_top_summarizes_live_scrape(self, capsys):
        """`tpuctl top` scrapes a LIVE /metrics endpoint and prints
        per-controller reconcile p50/p95/p99 estimated from histogram
        buckets."""
        from kubeflow_tpu.controlplane.benchmark import run_controlplane_sweep
        from kubeflow_tpu.utils.monitoring import (
            MetricsHttpServer,
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        rep = run_controlplane_sweep(num_jobs=6, num_namespaces=2,
                                     registry=reg)
        assert rep.all_succeeded
        srv = MetricsHttpServer(reg, port=0, host="127.0.0.1")
        try:
            rc, out = _run(
                ["top", "--url", f"http://127.0.0.1:{srv.port}/metrics"],
                capsys)
        finally:
            srv.stop()
        assert rc == 0
        assert "CONTROLLER" in out and "P99(ms)" in out
        assert "tpujob" in out and "fake-kubelet" in out
        # Reconcile counts in the table match the sweep's executed total.
        counts = [int(line.split()[1]) for line in out.splitlines()[1:]
                  if line.strip()]
        assert sum(counts) == rep.reconciles

    def test_top_bad_url_fails(self, capsys):
        rc = main(["top", "--url", "http://127.0.0.1:1/metrics"])
        assert rc == 1

    def test_top_shows_autoscaler_decisions(self, capsys):
        """A scrape carrying kftpu_autoscaler_replicas{reason} gets the
        autoscale actuation section appended to the table (ISSUE 7)."""
        from kubeflow_tpu.controlplane.api import (
            AutoscaleSpec,
            ObjectMeta,
            Serving,
            ServingSpec,
        )
        from kubeflow_tpu.controlplane.controllers import ServingAutoscaler
        from kubeflow_tpu.controlplane.runtime import (
            ControllerManager,
            InMemoryApiServer,
        )
        from kubeflow_tpu.utils.monitoring import (
            MetricsHttpServer,
            MetricsRegistry,
        )
        from kubeflow_tpu.utils.tracing import Tracer

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api, reg)
        asc = ServingAutoscaler(
            api, reg, tracer=Tracer(),
            scrape=lambda a: {"queued": 2, "p95_queue_wait_s": 0.9,
                              "p50_queue_wait_s": 0.9})
        mgr.register(asc)
        api.create(Serving(
            metadata=ObjectMeta(name="llm", namespace="team-a"),
            spec=ServingSpec(model="llama-tiny", replicas=1,
                             autoscale=AutoscaleSpec(
                                 min_replicas=1, max_replicas=4,
                                 target_queue_wait_s=0.1))))
        sv = api.get("Serving", "llm", "team-a")
        sv.status.endpoints = ["e0:80"]
        api.update_status(sv)
        # through the manager so the reconcile-duration histogram the
        # top table keys on records alongside the decision counter
        mgr.run_until_idle()
        mgr.close()
        srv = MetricsHttpServer(reg, port=0, host="127.0.0.1")
        try:
            rc, out = _run(
                ["top", "--url", f"http://127.0.0.1:{srv.port}/metrics"],
                capsys)
        finally:
            srv.stop()
        assert rc == 0
        assert "AUTOSCALE REASON" in out
        assert "queue-wait-above-target" in out
        # 1 -> 4 replicas: 3 added under that reason
        line = [l for l in out.splitlines()
                if l.startswith("queue-wait-above-target")][0]
        assert line.split()[-1] == "3"


class TestTpuctlLogs:
    def test_logs_for_job_gang(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", prof,
                      "-f", job], capsys)
        assert rc == 0

        # FakeKubelet pods have no process: the command reports phases and
        # any termination message instead of file contents.
        rc, out = _run(["--state-dir", state, "logs", "train1", "-n", "ml"],
                       capsys)
        assert rc == 0
        assert out.count("==> ml/train1-worker") == 4
        assert "no log file" in out

        # A pod with the ProcessKubelet's log annotation streams the file.
        logf = tmp_path / "w0.log"
        logf.write_text("step 1 loss 5.0\nstep 2 loss 4.2\n")
        platform = Platform.load(state)
        pod = platform.api.get("Pod", "train1-worker-0", "ml")
        pod.metadata.annotations["tpu.kubeflow.org/log-path"] = str(logf)
        platform.api.update(pod)
        platform.save(state)
        rc, out = _run(["--state-dir", state, "logs", "train1-worker-0",
                        "-n", "ml"], capsys)
        assert rc == 0
        assert "step 2 loss 4.2" in out

    def test_logs_unknown_name_fails(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        _run(["--state-dir", state, "apply", "-f", pf], capsys)
        rc, _ = _run(["--state-dir", state, "logs", "nope", "-n", "ml"],
                     capsys)
        assert rc == 1


SCHED_PLATFORM_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: PlatformConfig
metadata:
  name: kubeflow-tpu
spec:
  components:
    - name: tpujob-controller
      params:
        fleet: "v5e-16=1"
    - name: fake-kubelet
"""

HI_JOB_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: TpuJob
metadata:
  name: running
  namespace: ml
spec:
  sliceType: v5e-16
  priority: 10
"""

QUEUED_JOB_YAML = """
apiVersion: tpu.kubeflow.org/v1alpha1
kind: TpuJob
metadata:
  name: waiting
  namespace: ml
spec:
  sliceType: v5e-16
  priority: 3
"""


class TestTpuctlQueue:
    """`tpuctl queue` (ISSUE 8): pending gangs with priority, requested
    slices, blocking reason and time-in-queue."""

    def _setup(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", SCHED_PLATFORM_YAML)
        hi = _write(tmp_path, "hi.yaml", HI_JOB_YAML)
        lo = _write(tmp_path, "lo.yaml", QUEUED_JOB_YAML)
        # The priority-10 gang takes the single slice (it applies first);
        # the priority-3 gang parks Unschedulable — it may NOT preempt a
        # higher-priority gang.
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", hi],
                     capsys)
        assert rc == 0
        rc, _ = _run(["--state-dir", state, "apply", "-f", lo], capsys)
        assert rc == 0
        return state

    def test_queue_table(self, tmp_path, capsys):
        state = self._setup(tmp_path, capsys)
        rc, out = _run(["--state-dir", state, "queue"], capsys)
        assert rc == 0
        assert "NAME" in out and "PRIORITY" in out and "REASON" in out
        assert "waiting" in out and "running" not in out
        assert "Unschedulable" in out and "v5e-16x1" in out

    def test_queue_json(self, tmp_path, capsys):
        state = self._setup(tmp_path, capsys)
        rc, out = _run(["--state-dir", state, "queue", "-o", "json"],
                       capsys)
        assert rc == 0
        rows = json.loads(out)
        assert len(rows) == 1
        row = rows[0]
        assert row["name"] == "waiting"
        assert row["priority"] == 3
        assert row["slices"] == "v5e-16x1"
        assert row["reason"] == "Unschedulable"
        assert "no adjacent" in row["message"]
        assert row["queued_seconds"] >= 0.0

    def test_queue_empty(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", SCHED_PLATFORM_YAML)
        _run(["--state-dir", state, "apply", "-f", pf], capsys)
        rc, out = _run(["--state-dir", state, "queue"], capsys)
        assert rc == 0
        assert "queue empty" in out
        rc, out = _run(["--state-dir", state, "queue", "-o", "json"],
                       capsys)
        assert json.loads(out) == []


class TestCrossShardQuantileMerge:
    """`tpuctl top --url` sums histogram buckets across shard scrapes.
    Regression (ISSUE 10): quantiles computed from the SUMMED buckets
    must equal `quantile_from_buckets` over one merged exposition — and
    both must match a single histogram that saw every observation."""

    def _scrape(self, observations):
        from kubeflow_tpu.utils.monitoring import (
            MetricsRegistry,
            parse_exposition,
        )

        registry = MetricsRegistry()
        h = registry.histogram("kftpu_reconcile_duration_seconds",
                               "d", labels=("controller", "result"))
        for ctl, v in observations:
            h.observe(v, controller=ctl, result="ok")
        return parse_exposition(registry.render())

    def test_summed_buckets_match_merged_exposition(self):
        from kubeflow_tpu.tools.tpuctl import _hist_series
        from kubeflow_tpu.utils.monitoring import (
            MetricsRegistry,
            quantile_from_buckets,
        )

        shard_a = [("tpujob", v) for v in
                   (0.0001, 0.0002, 0.004, 0.04, 0.9)]
        shard_b = [("tpujob", v) for v in (0.0003, 0.02, 0.02, 2.0)]
        samples = self._scrape(shard_a) + self._scrape(shard_b)
        merged = _hist_series(samples, "kftpu_reconcile_duration_seconds",
                              "controller")["tpujob"]
        # Ground truth: ONE histogram that saw every observation.
        truth_reg = MetricsRegistry()
        truth = truth_reg.histogram("t", "t")
        for _, v in shard_a + shard_b:
            truth.observe(v)
        assert merged[-1][1] == len(shard_a) + len(shard_b)
        for q in (0.5, 0.9, 0.95, 0.99):
            assert quantile_from_buckets(merged, q) == pytest.approx(
                truth.quantile(q))

    def test_single_shard_merge_is_identity(self):
        from kubeflow_tpu.tools.tpuctl import _hist_series
        from kubeflow_tpu.utils.monitoring import (
            MetricsRegistry,
            quantile_from_buckets,
        )

        obs = [("tpujob", 0.003), ("tpujob", 0.05)]
        samples = self._scrape(obs)
        merged = _hist_series(samples, "kftpu_reconcile_duration_seconds",
                              "controller")["tpujob"]
        # One scrape "merged" must be the identity: every quantile
        # equals the source histogram's own estimate exactly.
        truth = MetricsRegistry().histogram("t", "t")
        for _, v in obs:
            truth.observe(v)
        for q in (0.25, 0.5, 0.75, 0.95):
            assert quantile_from_buckets(merged, q) == truth.quantile(q)
        assert merged[-1][1] == 2

    def test_empty_bucket_and_zero_observation_shards(self):
        from kubeflow_tpu.tools.tpuctl import _hist_series
        from kubeflow_tpu.utils.monitoring import quantile_from_buckets

        # One shard observed nothing (no series at all), another one
        # value far into the tail: empty interleaved bands must not
        # corrupt the estimate.
        samples = self._scrape([]) + self._scrape([("tpujob", 1.7)])
        series = _hist_series(samples, "kftpu_reconcile_duration_seconds",
                              "controller")
        merged = series["tpujob"]
        assert merged[-1][1] == 1
        v = quantile_from_buckets(merged, 0.95)
        assert 1.0 <= v <= 2.5          # inside the containing band
        # Aggregating across DIFFERENT controllers never mixes rows.
        samples = self._scrape([("a", 0.001)]) + self._scrape(
            [("b", 4.0)])
        series = _hist_series(samples, "kftpu_reconcile_duration_seconds",
                              "controller")
        assert quantile_from_buckets(series["a"], 0.5) < 0.01
        assert quantile_from_buckets(series["b"], 0.5) > 1.0


class TestTraceRotation:
    """trace.jsonl rotation (ISSUE 10): Platform.save rolls the span
    file to trace.jsonl.1 past the byte cap, and `tpuctl trace` reads
    both generations."""

    def test_rotate_then_trace_reads_both_generations(self, tmp_path,
                                                      capsys):
        from kubeflow_tpu.controlplane.platform import TRACE_FILE
        from kubeflow_tpu.utils.tracing import Tracer

        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        prof = _write(tmp_path, "profile.yaml", PROFILE_YAML)
        job = _write(tmp_path, "job.yaml", JOB_YAML)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", prof,
                      "-f", job], capsys)
        assert rc == 0
        trace_path = os.path.join(state, TRACE_FILE)
        spans_before = len(Tracer.load_jsonl(trace_path))
        assert spans_before > 0
        # Force a rollover: cap far below the current size.
        assert Tracer.rotate_jsonl(trace_path, max_bytes=64)
        assert os.path.exists(trace_path + ".1")
        assert not os.path.exists(trace_path)
        # The next save appends to a FRESH current generation.
        rc, _ = _run(["--state-dir", state, "status"], capsys)
        # (status doesn't save; run a no-op apply which does)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf], capsys)
        assert rc == 0
        # Both generations feed one timeline.
        rc, out = _run(["--state-dir", state, "trace", "TpuJob/train1",
                        "-n", "ml"], capsys)
        assert rc == 0
        assert "create TpuJob ml/train1" in out      # lives in .1 now

    def test_rotate_keeps_single_generation(self, tmp_path):
        from kubeflow_tpu.utils.tracing import Tracer

        p = str(tmp_path / "t.jsonl")
        for gen in ("one", "two", "three"):
            with open(p, "w") as f:
                f.write(json.dumps({"gen": gen}) * 40 + "\n")
            assert Tracer.rotate_jsonl(p, max_bytes=16)
        # Only .1 survives — single-generation rollover, bounded disk.
        assert sorted(os.listdir(tmp_path)) == ["t.jsonl.1"]
        with open(p + ".1") as f:
            assert "three" in f.read()
        assert Tracer.generations(p) == [p + ".1"]
        # Under the cap: no-op.
        with open(p, "w") as f:
            f.write("{}\n")
        assert not Tracer.rotate_jsonl(p, max_bytes=1 << 20)


class TestTpuctlGoodput:
    """`tpuctl goodput` (ISSUE 10): the fleet scoreboard with per-job
    drill-down, conservation-gated."""

    def _apply(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", SCHED_PLATFORM_YAML)
        hi = _write(tmp_path, "hi.yaml", HI_JOB_YAML)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf, "-f", hi],
                     capsys)
        assert rc == 0
        return state

    def test_goodput_table_and_json(self, tmp_path, capsys):
        state = self._apply(tmp_path, capsys)
        rc, out = _run(["--state-dir", state, "goodput"], capsys)
        assert rc == 0
        assert "FLEET GOODPUT" in out
        assert "productive" in out and "idle_free" in out
        assert "conservation OK" in out
        assert "ml/running" in out
        rc, out = _run(["--state-dir", state, "goodput", "-o", "json"],
                       capsys)
        assert rc == 0
        snap = json.loads(out)
        assert snap["conserved"] is True
        assert (sum(snap["categories_ticks"].values())
                == snap["tracked_ticks"])
        assert snap["tracked_ticks"] > 0
        assert "ml/running" in snap["jobs"]

    def test_goodput_accumulates_across_invocations(self, tmp_path,
                                                    capsys):
        state = self._apply(tmp_path, capsys)
        rc, out = _run(["--state-dir", state, "goodput", "-o", "json"],
                       capsys)
        first = json.loads(out)["tracked_ticks"]
        # goodput doesn't save; apply does — persist, then read again.
        pf = _write(tmp_path, "platform.yaml", SCHED_PLATFORM_YAML)
        rc, _ = _run(["--state-dir", state, "apply", "-f", pf], capsys)
        assert rc == 0
        rc, out = _run(["--state-dir", state, "goodput", "-o", "json"],
                       capsys)
        again = json.loads(out)
        # The persisted ledger carried over and kept growing; the gap
        # BETWEEN invocations contributed nothing is implied by both
        # stints being millisecond-scale (vs a multi-second test run).
        assert again["tracked_ticks"] > 0
        assert again["conserved"] is True
        assert first > 0

    def test_goodput_off_without_capacity(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", PLATFORM_YAML)
        _run(["--state-dir", state, "apply", "-f", pf], capsys)
        rc = main(["--state-dir", state, "goodput"])
        assert rc == 1


class TestQueueAgeFooter:
    def test_queue_table_has_age_footer(self, tmp_path, capsys):
        state = str(tmp_path / "state")
        pf = _write(tmp_path, "platform.yaml", SCHED_PLATFORM_YAML)
        hi = _write(tmp_path, "hi.yaml", HI_JOB_YAML)
        lo = _write(tmp_path, "lo.yaml", QUEUED_JOB_YAML)
        _run(["--state-dir", state, "apply", "-f", pf, "-f", hi], capsys)
        _run(["--state-dir", state, "apply", "-f", lo], capsys)
        rc, out = _run(["--state-dir", state, "queue"], capsys)
        assert rc == 0
        assert "QUEUE AGE: 1 pending" in out
        assert "p50" in out and "max" in out


class TestLint:
    """`tpuctl lint` forwards onto the static analyzer (ISSUE 16)."""

    def test_lint_clean_package_exits_zero(self, capsys):
        rc, out = _run(["lint"], capsys)
        assert rc == 0
        assert "0 finding(s)" in out

    def test_lint_json_shape(self, capsys):
        rc, out = _run(["lint", "--json"], capsys)
        assert rc == 0
        doc = json.loads(out)
        assert doc["findings"] == []
        assert all(f["reason"] for f in doc["suppressed"])

    def test_lint_dirty_path_exits_one(self, tmp_path, capsys):
        bad = tmp_path / "chaos"
        bad.mkdir()
        (bad / "soak.py").write_text(
            "import time\n\ndef t():\n    return time.time()\n")
        rc, out = _run(["lint", str(tmp_path)], capsys)
        assert rc == 1
        assert "KF101" in out
