import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch, top2_gating


class TestTop2Gating:
    def test_shapes_and_dispatch_bounds(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, capacity_factor=1.25)
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        combine, dispatch, aux = top2_gating(logits, cfg)
        C = cfg.capacity(T)
        assert combine.shape == (T, E, C)
        assert dispatch.shape == (T, E, C)
        # Each token dispatched to at most 2 (expert, slot) pairs.
        per_token = dispatch.sum(axis=(1, 2))
        assert (per_token <= 2).all()
        # Each (expert, slot) holds at most one token — no collisions.
        per_slot = dispatch.sum(axis=0)
        assert (per_slot <= 1).all()
        # Combine weights per token sum to 1 for fully-routed tokens.
        w = combine.sum(axis=(1, 2))
        routed = per_token == 2
        np.testing.assert_allclose(np.asarray(w[routed]), 1.0, atol=1e-6)
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        T, E = 32, 4
        # All tokens prefer expert 0 → overflow must be dropped.
        logits = jnp.zeros((T, E)).at[:, 0].set(10.0)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=0.25)
        C = cfg.capacity(T)
        _, dispatch, _ = top2_gating(logits, cfg)
        assert dispatch[:, 0].sum() <= C

    def test_capacity_tile_rounding(self):
        cfg = Top2GateConfig(num_experts=8, capacity_factor=1.0)
        assert cfg.capacity(100) % 4 == 0


class TestMoeDispatch:
    def test_identity_experts_preserve_tokens(self):
        T, M, E = 64, 16, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, M))
        logits = jax.random.normal(jax.random.PRNGKey(2), (T, E))
        out, aux = moe_dispatch(x, logits, lambda e_in: e_in, cfg)
        # With identity experts and generous capacity, output == input for
        # every routed token (combine weights sum to 1).
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    def test_grad_flows_through_router(self):
        T, M, E = 32, 8, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(3), (T, M))
        w = jax.random.normal(jax.random.PRNGKey(4), (M, E)) * 0.1

        def loss(w):
            out, aux = moe_dispatch(x, x @ w, lambda e: e * 2.0, cfg)
            return out.sum() + 0.01 * aux

        g = jax.grad(loss)(w)
        assert jnp.isfinite(g).all()
        assert float(jnp.abs(g).sum()) > 0

    def test_jitter_changes_routing_stats(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, jitter_eps=0.5)
        logits = jax.random.normal(jax.random.PRNGKey(5), (T, E)) * 0.01
        c0, _, _ = top2_gating(logits, cfg)  # no rng → deterministic
        c1, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(6))
        c2, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(7))
        assert not np.allclose(np.asarray(c1), np.asarray(c2))
        assert not np.allclose(np.asarray(c0), np.asarray(c1))


class TestGroupedDispatch:
    def test_grouped_matches_single_group_when_balanced(self):
        """Grouped dispatch changes capacity locality, not routing math: on
        a load-balanced router the outputs must match ungrouped."""
        import dataclasses

        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 256, 16, 4
        x = jax.random.normal(jax.random.key(0), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(1), (T, E), jnp.float32)

        def expert_fn(e_in):
            return e_in * 2.0

        # Generous capacity: nothing drops in either layout.
        cfg1 = Top2GateConfig(num_experts=E, capacity_factor=8.0,
                              group_size=0, dispatch="einsum")
        cfgG = dataclasses.replace(cfg1, group_size=64)
        out1, aux1 = moe_dispatch(x, logits, expert_fn, cfg1)
        outG, auxG = moe_dispatch(x, logits, expert_fn, cfgG)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(outG),
                                   rtol=1e-5, atol=1e-5)
        # aux is per-group statistics under grouping (GShard computes the
        # balance loss within each group): same scale, not bit-identical.
        np.testing.assert_allclose(float(aux1), float(auxG), rtol=0.05)

    def test_grouped_capacity_is_per_group(self):
        """Per-group capacity drops tokens locally — a hot expert in one
        group cannot consume another group's budget."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 64, 8, 4
        x = jnp.ones((T, M), jnp.float32)
        # All tokens want expert 0 hard.
        logits = jnp.tile(jnp.array([10.0, 0.0, -10.0, -10.0]), (T, 1))
        cfg = Top2GateConfig(num_experts=E, capacity_factor=1.0,
                             min_capacity=4, group_size=16,
                             dispatch="einsum")

        def expert_fn(e_in):
            return e_in

        out, _ = moe_dispatch(x, logits, expert_fn, cfg)
        # Survivors (nonzero rows) exist in EVERY group, not just the first.
        surv = (jnp.abs(out).sum(-1) > 0).reshape(4, 16)
        assert bool(surv.any(axis=1).all())

    def test_non_divisible_tokens_still_group(self):
        """T not divisible by group_size must pick the largest divisor, not
        silently fall back to the quadratic single-group path."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 320, 8, 4          # 320 % 256 != 0; largest div <= 256: 160
        x = jax.random.normal(jax.random.key(0), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(1), (T, E), jnp.float32)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=8.0,
                             group_size=256, dispatch="einsum")
        out, aux = moe_dispatch(x, logits, lambda e: e, cfg)
        assert out.shape == (T, M)
        assert np.isfinite(float(aux))
        # Matches the explicitly-grouped result at the chosen divisor.
        import dataclasses

        out160, _ = moe_dispatch(
            x, logits, lambda e: e,
            dataclasses.replace(cfg, group_size=160),
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(out160),
                                   rtol=1e-5, atol=1e-5)


class TestGatherDispatch:
    """Index-gather dispatch (r4): must replicate the einsum path's
    routing semantics exactly while spending no MXU flops on routing."""

    def _data(self, T=128, M=16, E=4, seed=0):
        x = jax.random.normal(jax.random.key(seed), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(seed + 1), (T, E),
                                   jnp.float32)
        return x, logits

    def test_matches_einsum_no_drops(self):
        x, logits = self._data()
        base = dict(num_experts=4, capacity_factor=8.0, group_size=0)

        def expert_fn(e_in):
            return e_in * 2.0 + 1.0 * (jnp.abs(e_in) > 0)

        oe, ae = moe_dispatch(x, logits, expert_fn,
                              Top2GateConfig(**base, dispatch="einsum"))
        og, ag = moe_dispatch(x, logits, expert_fn,
                              Top2GateConfig(**base, dispatch="gather"))
        np.testing.assert_allclose(np.asarray(oe), np.asarray(og),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(ae), float(ag), rtol=1e-6)

    def test_matches_einsum_with_capacity_drops(self):
        x, logits = self._data()
        # Skew routing hard so capacity drops engage.
        logits = logits.at[:, 0].add(6.0)
        base = dict(num_experts=4, capacity_factor=0.5, min_capacity=4,
                    group_size=0)
        oe, _ = moe_dispatch(x, logits, lambda e: e,
                             Top2GateConfig(**base, dispatch="einsum"))
        og, _ = moe_dispatch(x, logits, lambda e: e,
                             Top2GateConfig(**base, dispatch="gather"))
        np.testing.assert_allclose(np.asarray(oe), np.asarray(og),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_einsum(self):
        x, logits = self._data(T=64)
        base = dict(num_experts=4, capacity_factor=2.0, group_size=0)

        def loss(mode, x, logits):
            out, aux = moe_dispatch(
                x, logits, lambda e: jnp.tanh(e),
                Top2GateConfig(**base, dispatch=mode))
            return (out ** 2).sum() + 0.1 * aux

        ge = jax.grad(lambda *a: loss("einsum", *a), argnums=(0, 1))(
            x, logits)
        gg = jax.grad(lambda *a: loss("gather", *a), argnums=(0, 1))(
            x, logits)
        for a, b in zip(ge, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_auto_uses_gather_off_mesh(self):
        from kubeflow_tpu.parallel.moe import _expert_axis_sharded

        assert _expert_axis_sharded() is False

    def test_auto_uses_einsum_under_ep_mesh(self):
        from kubeflow_tpu.parallel.context import parallel_context
        from kubeflow_tpu.parallel.moe import _expert_axis_sharded
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh

        mesh = make_host_local_mesh(AxisSpec(dp=-1, ep=2))
        with parallel_context(mesh=mesh):
            assert _expert_axis_sharded() is True

    def test_mixtral_trains_with_gather(self):
        from kubeflow_tpu.models import get_model
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
        from kubeflow_tpu.train import TrainConfig, Trainer

        model, _ = get_model("mixtral-tiny")
        mesh = make_host_local_mesh(AxisSpec(dp=-1))
        trainer = Trainer(
            model, TrainConfig(task="lm", aux_loss_weight=0.02), mesh)
        rng = np.random.default_rng(0)
        batch = trainer.shard_batch({"inputs": jnp.asarray(
            rng.integers(1, 250, size=(8, 17)), jnp.int32)})
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for _ in range(6):
            state, metrics = trainer.step(state, batch,
                                          rng=jax.random.PRNGKey(2))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses[-1])

    def test_grouped_gather_matches_grouped_einsum(self):
        """group_size must mean the same thing under both mechanisms:
        per-group capacity, a hot expert in one group cannot consume
        another group's budget."""
        T, M, E = 256, 16, 4
        x = jax.random.normal(jax.random.key(3), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(4), (T, E), jnp.float32)
        # skew so per-group drops actually engage
        logits = logits.at[:, 1].add(4.0)
        base = dict(num_experts=E, capacity_factor=0.75, min_capacity=4,
                    group_size=64)
        oe, ae = moe_dispatch(x, logits, lambda e: e * 2.0,
                              Top2GateConfig(**base, dispatch="einsum"))
        og, ag = moe_dispatch(x, logits, lambda e: e * 2.0,
                              Top2GateConfig(**base, dispatch="gather"))
        np.testing.assert_allclose(np.asarray(oe), np.asarray(og),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(ae), float(ag), rtol=1e-6)


class TestGatherCustomVjp:
    """The inverse-map custom VJPs (_gather_in/_combine_out turn the
    backward row scatter-adds into row-gathers) must be gradient-identical
    to plain autodiff of the same forward — including capacity drops
    (trash-row padding), empty slots (the w_s == 0 compare trick), and
    grouped dispatch."""

    def _plain_gather_in(self, x, slot_tok, slot_valid, d1, d2):
        return jnp.take(x, slot_tok, axis=0) * slot_valid[:, None]

    def _plain_combine_out(self, y, g1, g2, d1, d2, slot_tok):
        yp = jnp.concatenate([y, jnp.zeros((1, y.shape[1]), y.dtype)])
        return (
            g1[:, None] * jnp.take(yp, d1, axis=0).astype(jnp.float32)
            + g2[:, None] * jnp.take(yp, d2, axis=0).astype(jnp.float32)
        )

    @pytest.mark.parametrize("skew,cf,group", [
        (0.0, 8.0, 0),     # no drops, single group
        (6.0, 0.5, 0),     # heavy drops via skewed routing
        (4.0, 0.75, 32),   # grouped dispatch with per-group drops
    ])
    def test_matches_plain_autodiff(self, skew, cf, group, monkeypatch):
        from kubeflow_tpu.parallel import moe as moe_mod

        T, M, E = 128, 16, 4
        x = jax.random.normal(jax.random.key(7), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(8), (T, E), jnp.float32)
        logits = logits.at[:, 0].add(skew)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=cf,
                             min_capacity=4, group_size=group,
                             dispatch="gather")

        def loss(x, logits):
            out, aux = moe_dispatch(x, logits, jnp.tanh, cfg)
            return (out ** 2).sum() + 0.1 * aux

        g_custom = jax.grad(loss, argnums=(0, 1))(x, logits)
        monkeypatch.setattr(moe_mod, "_gather_in", self._plain_gather_in)
        monkeypatch.setattr(moe_mod, "_combine_out",
                            self._plain_combine_out)
        g_plain = jax.grad(loss, argnums=(0, 1))(x, logits)
        for a, b, name in zip(g_custom, g_plain, ("dx", "dlogits")):
            # atol must absorb f32 re-association noise on dropped-token
            # logits (scatter-add vs gather backward); real VJP bugs show
            # up at the gradient's own magnitude, orders above this.
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=5e-6,
                err_msg=f"{name} (skew={skew}, cf={cf}, group={group})",
            )
