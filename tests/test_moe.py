import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch, top2_gating


class TestTop2Gating:
    def test_shapes_and_dispatch_bounds(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, capacity_factor=1.25)
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        combine, dispatch, aux = top2_gating(logits, cfg)
        C = cfg.capacity(T)
        assert combine.shape == (T, E, C)
        assert dispatch.shape == (T, E, C)
        # Each token dispatched to at most 2 (expert, slot) pairs.
        per_token = dispatch.sum(axis=(1, 2))
        assert (per_token <= 2).all()
        # Each (expert, slot) holds at most one token — no collisions.
        per_slot = dispatch.sum(axis=0)
        assert (per_slot <= 1).all()
        # Combine weights per token sum to 1 for fully-routed tokens.
        w = combine.sum(axis=(1, 2))
        routed = per_token == 2
        np.testing.assert_allclose(np.asarray(w[routed]), 1.0, atol=1e-6)
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        T, E = 32, 4
        # All tokens prefer expert 0 → overflow must be dropped.
        logits = jnp.zeros((T, E)).at[:, 0].set(10.0)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=0.25)
        C = cfg.capacity(T)
        _, dispatch, _ = top2_gating(logits, cfg)
        assert dispatch[:, 0].sum() <= C

    def test_capacity_tile_rounding(self):
        cfg = Top2GateConfig(num_experts=8, capacity_factor=1.0)
        assert cfg.capacity(100) % 4 == 0


class TestMoeDispatch:
    def test_identity_experts_preserve_tokens(self):
        T, M, E = 64, 16, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, M))
        logits = jax.random.normal(jax.random.PRNGKey(2), (T, E))
        out, aux = moe_dispatch(x, logits, lambda e_in: e_in, cfg)
        # With identity experts and generous capacity, output == input for
        # every routed token (combine weights sum to 1).
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    def test_grad_flows_through_router(self):
        T, M, E = 32, 8, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(3), (T, M))
        w = jax.random.normal(jax.random.PRNGKey(4), (M, E)) * 0.1

        def loss(w):
            out, aux = moe_dispatch(x, x @ w, lambda e: e * 2.0, cfg)
            return out.sum() + 0.01 * aux

        g = jax.grad(loss)(w)
        assert jnp.isfinite(g).all()
        assert float(jnp.abs(g).sum()) > 0

    def test_jitter_changes_routing_stats(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, jitter_eps=0.5)
        logits = jax.random.normal(jax.random.PRNGKey(5), (T, E)) * 0.01
        c0, _, _ = top2_gating(logits, cfg)  # no rng → deterministic
        c1, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(6))
        c2, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(7))
        assert not np.allclose(np.asarray(c1), np.asarray(c2))
        assert not np.allclose(np.asarray(c0), np.asarray(c1))
