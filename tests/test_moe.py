import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch, top2_gating


class TestTop2Gating:
    def test_shapes_and_dispatch_bounds(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, capacity_factor=1.25)
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        combine, dispatch, aux = top2_gating(logits, cfg)
        C = cfg.capacity(T)
        assert combine.shape == (T, E, C)
        assert dispatch.shape == (T, E, C)
        # Each token dispatched to at most 2 (expert, slot) pairs.
        per_token = dispatch.sum(axis=(1, 2))
        assert (per_token <= 2).all()
        # Each (expert, slot) holds at most one token — no collisions.
        per_slot = dispatch.sum(axis=0)
        assert (per_slot <= 1).all()
        # Combine weights per token sum to 1 for fully-routed tokens.
        w = combine.sum(axis=(1, 2))
        routed = per_token == 2
        np.testing.assert_allclose(np.asarray(w[routed]), 1.0, atol=1e-6)
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        T, E = 32, 4
        # All tokens prefer expert 0 → overflow must be dropped.
        logits = jnp.zeros((T, E)).at[:, 0].set(10.0)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=0.25)
        C = cfg.capacity(T)
        _, dispatch, _ = top2_gating(logits, cfg)
        assert dispatch[:, 0].sum() <= C

    def test_capacity_tile_rounding(self):
        cfg = Top2GateConfig(num_experts=8, capacity_factor=1.0)
        assert cfg.capacity(100) % 4 == 0


class TestMoeDispatch:
    def test_identity_experts_preserve_tokens(self):
        T, M, E = 64, 16, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, M))
        logits = jax.random.normal(jax.random.PRNGKey(2), (T, E))
        out, aux = moe_dispatch(x, logits, lambda e_in: e_in, cfg)
        # With identity experts and generous capacity, output == input for
        # every routed token (combine weights sum to 1).
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    def test_grad_flows_through_router(self):
        T, M, E = 32, 8, 4
        cfg = Top2GateConfig(num_experts=E, capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(3), (T, M))
        w = jax.random.normal(jax.random.PRNGKey(4), (M, E)) * 0.1

        def loss(w):
            out, aux = moe_dispatch(x, x @ w, lambda e: e * 2.0, cfg)
            return out.sum() + 0.01 * aux

        g = jax.grad(loss)(w)
        assert jnp.isfinite(g).all()
        assert float(jnp.abs(g).sum()) > 0

    def test_jitter_changes_routing_stats(self):
        T, E = 64, 8
        cfg = Top2GateConfig(num_experts=E, jitter_eps=0.5)
        logits = jax.random.normal(jax.random.PRNGKey(5), (T, E)) * 0.01
        c0, _, _ = top2_gating(logits, cfg)  # no rng → deterministic
        c1, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(6))
        c2, _, _ = top2_gating(logits, cfg, rng=jax.random.PRNGKey(7))
        assert not np.allclose(np.asarray(c1), np.asarray(c2))
        assert not np.allclose(np.asarray(c0), np.asarray(c1))


class TestGroupedDispatch:
    def test_grouped_matches_single_group_when_balanced(self):
        """Grouped dispatch changes capacity locality, not routing math: on
        a load-balanced router the outputs must match ungrouped."""
        import dataclasses

        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 256, 16, 4
        x = jax.random.normal(jax.random.key(0), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(1), (T, E), jnp.float32)

        def expert_fn(e_in):
            return e_in * 2.0

        # Generous capacity: nothing drops in either layout.
        cfg1 = Top2GateConfig(num_experts=E, capacity_factor=8.0,
                              group_size=0)
        cfgG = dataclasses.replace(cfg1, group_size=64)
        out1, aux1 = moe_dispatch(x, logits, expert_fn, cfg1)
        outG, auxG = moe_dispatch(x, logits, expert_fn, cfgG)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(outG),
                                   rtol=1e-5, atol=1e-5)
        # aux is per-group statistics under grouping (GShard computes the
        # balance loss within each group): same scale, not bit-identical.
        np.testing.assert_allclose(float(aux1), float(auxG), rtol=0.05)

    def test_grouped_capacity_is_per_group(self):
        """Per-group capacity drops tokens locally — a hot expert in one
        group cannot consume another group's budget."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 64, 8, 4
        x = jnp.ones((T, M), jnp.float32)
        # All tokens want expert 0 hard.
        logits = jnp.tile(jnp.array([10.0, 0.0, -10.0, -10.0]), (T, 1))
        cfg = Top2GateConfig(num_experts=E, capacity_factor=1.0,
                             min_capacity=4, group_size=16)

        def expert_fn(e_in):
            return e_in

        out, _ = moe_dispatch(x, logits, expert_fn, cfg)
        # Survivors (nonzero rows) exist in EVERY group, not just the first.
        surv = (jnp.abs(out).sum(-1) > 0).reshape(4, 16)
        assert bool(surv.any(axis=1).all())

    def test_non_divisible_tokens_still_group(self):
        """T not divisible by group_size must pick the largest divisor, not
        silently fall back to the quadratic single-group path."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.parallel.moe import Top2GateConfig, moe_dispatch

        T, M, E = 320, 8, 4          # 320 % 256 != 0; largest div <= 256: 160
        x = jax.random.normal(jax.random.key(0), (T, M), jnp.float32)
        logits = jax.random.normal(jax.random.key(1), (T, E), jnp.float32)
        cfg = Top2GateConfig(num_experts=E, capacity_factor=8.0,
                             group_size=256)
        out, aux = moe_dispatch(x, logits, lambda e: e, cfg)
        assert out.shape == (T, M)
        assert np.isfinite(float(aux))
        # Matches the explicitly-grouped result at the chosen divisor.
        import dataclasses

        out160, _ = moe_dispatch(
            x, logits, lambda e: e,
            dataclasses.replace(cfg, group_size=160),
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(out160),
                                   rtol=1e-5, atol=1e-5)
