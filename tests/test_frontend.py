"""Central hub frontend: HTML pages + combined REST surface over HTTP.

The Selenium-free functional flow the round-1 verdict prescribed for the
L3 plane, extended to the pages: login-header -> create workgroup ->
spawn TPU notebook -> appears in dashboard resources -> delete — entirely
over HTTP against one hub server.
"""

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.controlplane.controllers import (
    NotebookController,
    ProfileController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.kfam import AccessManagement
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.webapps.dashboard import DashboardApi
from kubeflow_tpu.webapps.frontend import serve_hub
from kubeflow_tpu.webapps.jwa import NotebookWebApp

HDR = "x-goog-authenticated-user-email"
ALICE = {"headers": {HDR: "alice@corp"}}


def _req(base, path, method="GET", body=None, user="alice@corp"):
    req = urllib.request.Request(
        base + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={HDR: user, "Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        ctype = resp.headers["Content-Type"]
        raw = resp.read()
    return ctype, raw


@pytest.fixture()
def hub():
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    mgr = ControllerManager(api)
    mgr.register(ProfileController(api, reg))
    mgr.register(NotebookController(api, reg))
    mgr.register(TpuJobController(api, reg))
    am = AccessManagement(api, reg)
    jwa = NotebookWebApp(api, reg)
    dashboard = DashboardApi(am)
    server = serve_hub(api, dashboard, jwa,
                       user_id_header="x-goog-authenticated-user-email")
    yield api, mgr, server
    server.stop()


class TestHubPages:
    def test_pages_render_html(self, hub):
        _, _, server = hub
        base = f"http://127.0.0.1:{server.port}"
        ctype, raw = _req(base, "/")
        assert ctype.startswith("text/html")
        page = raw.decode()
        assert 'id="resources"' in page and 'id="ns"' in page
        ctype, raw = _req(base, "/spawner")
        assert ctype.startswith("text/html")
        assert 'id="spawn"' in raw.decode()

    def test_full_flow_over_http(self, hub):
        api, mgr, server = hub
        base = f"http://127.0.0.1:{server.port}"

        # 1. Onboard: create the workgroup (profile) for alice.
        _, raw = _req(base, "/api/workgroup/create", "POST",
                      {"namespace": "alice"})
        mgr.run_until_idle()          # profile controller provisions the ns

        # 2. Spawn a TPU notebook through the spawner API.
        _, raw = _req(base, "/api/namespaces/alice/notebooks", "POST",
                      {"name": "nb1", "image": "kubeflow-tpu/jupyter:latest",
                       "tpuSlice": "v5e-8"})
        assert json.loads(raw)["success"] is True
        mgr.run_until_idle()

        # 3. Dashboard resources endpoint sees it with a phase.
        _, raw = _req(base, "/api/resources/alice")
        res = json.loads(raw)["resources"]
        assert [i["name"] for i in res["Notebook"]] == ["nb1"]
        assert res["TpuJob"] == []

        # 4. Delete through the spawner API; resource disappears.
        _req(base, "/api/namespaces/alice/notebooks/nb1", "DELETE")
        _, raw = _req(base, "/api/resources/alice")
        assert json.loads(raw)["resources"]["Notebook"] == []

    def test_resources_requires_authz(self, hub):
        api, mgr, server = hub
        base = f"http://127.0.0.1:{server.port}"
        _req(base, "/api/workgroup/create", "POST", {"namespace": "alice"})
        mgr.run_until_idle()
        with pytest.raises(urllib.error.HTTPError) as e:
            _req(base, "/api/resources/alice", user="mallory@corp")
        assert e.value.code == 403

    def test_notebook_name_validation_blocks_markup(self, hub):
        """DNS-1123 server-side validation: the stored-XSS vector (markup in
        resource names) dies at create time."""
        api, mgr, server = hub
        base = f"http://127.0.0.1:{server.port}"
        _req(base, "/api/workgroup/create", "POST", {"namespace": "alice"})
        mgr.run_until_idle()
        for bad in ("<img src=x>", "UPPER", "end-", "-start", "a" * 64):
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(base, "/api/namespaces/alice/notebooks", "POST",
                     {"name": bad})
            assert e.value.code == 400, bad
