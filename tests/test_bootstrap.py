"""Deployment REST plane (controlplane/bootstrap.py): the kfctl-server
surface — async create, polled status, idempotent re-apply, delete+GC
(reference bootstrap/cmd/bootstrap/app/router.go:275-405,
kfctlServer.go:43-330)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.controlplane.bootstrap import DeploymentServer

PREFIX = "/kfctl/apps/v1beta1"


def _req(port, method, path, body=None, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.load(r)


def _wait_phase(port, name, want, deadline_s=60):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        _, body = _req(port, "GET", f"{PREFIX}/get/{name}")
        if body["phase"] in want:
            return body
        time.sleep(0.1)
    raise AssertionError(f"{name} never reached {want}: {body}")


@pytest.fixture()
def server(tmp_path):
    srv = DeploymentServer(state_dir=str(tmp_path / "deployments")).start()
    yield srv
    srv.stop()


class TestDeploymentLifecycle:
    def test_create_poll_ready_with_resources(self, server, tmp_path):
        status, body = _req(server.port, "POST", f"{PREFIX}/create", {
            "name": "dev",
            "spec": {},
            "resources": [{
                "kind": "Profile",
                "metadata": {"name": "team-a"},
                "spec": {"owner": "alice@example.com"},
            }],
        })
        assert status == 202 and body["phase"] == "Pending"
        got = _wait_phase(server.port, "dev", {"Ready", "Failed"})
        assert got["phase"] == "Ready", got
        assert "tpujob-controller" in got["components"]
        assert got["error"] == ""
        # the deployment persisted in tpuctl's state layout and the
        # applied Profile reconciled into a namespace
        from kubeflow_tpu.controlplane.platform import Platform

        pf = Platform.load(str(tmp_path / "deployments" / "dev"))
        assert pf.api.try_get("Profile", "team-a") is not None
        assert pf.api.try_get("Namespace", "team-a") is not None

    def test_second_create_is_idempotent_reapply(self, server):
        _req(server.port, "POST", f"{PREFIX}/create",
             {"name": "dev", "spec": {}})
        _wait_phase(server.port, "dev", {"Ready"})
        status, body = _req(server.port, "POST", f"{PREFIX}/create",
                            {"name": "dev", "spec": {}})
        assert status == 202
        got = _wait_phase(server.port, "dev", {"Ready", "Failed"})
        assert got["phase"] == "Ready"

    def test_bad_resource_surfaces_failed(self, server):
        _req(server.port, "POST", f"{PREFIX}/create", {
            "name": "broken",
            "resources": [{"kind": "NoSuchKind", "metadata": {"name": "x"}}],
        })
        got = _wait_phase(server.port, "broken", {"Ready", "Failed"})
        assert got["phase"] == "Failed"
        assert got["error"]

    def test_list_and_delete_gc(self, server, tmp_path):
        _req(server.port, "POST", f"{PREFIX}/create",
             {"name": "dev", "spec": {}})
        _wait_phase(server.port, "dev", {"Ready"})
        _, listing = _req(server.port, "GET", f"{PREFIX}/list")
        assert [d["name"] for d in listing["deployments"]] == ["dev"]
        assert (tmp_path / "deployments" / "dev").is_dir()
        status, body = _req(server.port, "DELETE", f"{PREFIX}/delete/dev")
        assert body["deleted"] == "dev"
        assert not (tmp_path / "deployments" / "dev").exists()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(server.port, "GET", f"{PREFIX}/get/dev")
        assert ei.value.code == 404

    def test_invalid_names_rejected(self, server):
        for bad in ("", "../etc", ".hidden"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(server.port, "POST", f"{PREFIX}/create", {"name": bad})
            assert ei.value.code == 400


class TestDeployPage:
    """The click-to-deploy form (the reference SPA's job,
    gcp-click-to-deploy/src/DeployForm.tsx): served from the deployment
    server itself over the same REST surface."""

    def _page(self, port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            assert r.status == 200
            assert "text/html" in r.headers["Content-Type"]
            return r.read().decode()

    def test_form_covers_the_create_contract(self, server):
        from kubeflow_tpu.controlplane.platform import DEFAULT_COMPONENTS

        html = self._page(server.port)
        assert '<form id="deploy">' in html
        assert 'id="name"' in html and 'id="slice"' in html
        for comp in DEFAULT_COMPONENTS:
            assert f'value="{comp}"' in html
        # The script posts to the same prefix the REST tests exercise.
        assert f"{PREFIX}/create" in html
        assert f"{PREFIX}/list" in html

    def test_form_component_subset_round_trips(self, server):
        """What the form submits (name + spec.components subset) must be
        honoured by the engine: only the picked components come up."""
        _req(server.port, "POST", f"{PREFIX}/create", {
            "name": "subset",
            "spec": {"components": [
                {"name": "tpujob-controller", "enabled": True},
                {"name": "kfam", "enabled": True},
            ]},
        })
        body = _wait_phase(server.port, "subset", {"Ready", "Failed"})
        assert body["phase"] == "Ready", body["error"]
        assert sorted(body["components"]) == ["kfam", "tpujob-controller"]
