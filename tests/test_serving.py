import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import Llama, LlamaConfig
from kubeflow_tpu.serving import ServingConfig, ServingEngine


@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig.tiny(max_seq_len=128)
    model = Llama(cfg)
    params = {
        "params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
        )["params"]
    }
    return model, params


def greedy_reference(model, params, prompt, n_new):
    """Generate by full re-forward each step — the semantic ground truth."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray([toks]))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


class TestServingEngine:
    def test_greedy_matches_full_reforward(self, model_and_params):
        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=2, max_len=128))
        prompt = [3, 14, 15, 92, 65]
        rid = engine.submit(prompt, max_new_tokens=8)
        results = engine.run()
        assert len(results) == 1
        ref = greedy_reference(model, params, prompt, 8)
        assert results[0].tokens == ref
        assert results[0].prompt_len == len(prompt)

    def test_continuous_batching_isolation(self, model_and_params):
        """Requests sharing a batch must produce the same tokens as when
        run alone — slots must not leak into each other."""
        model, params = model_and_params
        prompts = [[1, 2, 3], [50, 60, 70, 80, 90, 100, 7], [9] * 20]
        solo = []
        for p in prompts:
            eng = ServingEngine(model, params,
                                ServingConfig(max_batch=1, max_len=128))
            eng.submit(p, max_new_tokens=6)
            solo.append(eng.run()[0].tokens)

        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=3, max_len=128))
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        batched = {r.request_id: r.tokens for r in eng.run()}
        for rid, expect in zip(rids, solo):
            assert batched[rid] == expect

    def test_staggered_admission(self, model_and_params):
        """More requests than slots: later requests admit as slots free."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        rids = [eng.submit([i + 1, i + 2], max_new_tokens=3 + i)
                for i in range(5)]
        results = eng.run()
        assert len(results) == 5
        for i, rid in enumerate(rids):
            assert len(eng.result(rid).tokens) == 3 + i

    def test_eos_stops_early(self, model_and_params):
        model, params = model_and_params
        ref = greedy_reference(model, params, [5, 6, 7], 8)
        eos = ref[2]  # force stop at third token
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        eng.submit([5, 6, 7], max_new_tokens=8, eos_token=eos)
        res = eng.run()[0]
        assert res.finished_reason == "eos"
        assert res.tokens == ref[:3]

    def test_temperature_sampling_varies(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        a = eng.submit([1, 2, 3], max_new_tokens=12, temperature=2.0)
        b = eng.submit([1, 2, 3], max_new_tokens=12, temperature=2.0)
        eng.run()
        assert eng.result(a).tokens != eng.result(b).tokens

    def test_rejects_oversized_prompt(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=64))
        with pytest.raises(ValueError):
            eng.submit(list(range(64)))
        with pytest.raises(ValueError):
            eng.submit([])

    def test_latency_metrics_recorded(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        eng.submit([4, 5], max_new_tokens=4)
        res = eng.run()[0]
        assert res.latency_s > 0
        assert 0 < res.ttft_s <= res.latency_s
        assert eng.tokens_generated == 4


class TestServingScannedModel:
    def test_scanned_layers_cache_layout(self):
        cfg = LlamaConfig.tiny(max_seq_len=128, scan_layers=True, num_layers=2)
        model = Llama(cfg)
        params = {
            "params": model.init(
                jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
            )["params"]
        }
        prompt = [3, 14, 15]
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        eng.submit(prompt, max_new_tokens=5)
        out = eng.run()[0].tokens
        ref = greedy_reference(model, params, prompt, 5)
        assert out == ref
