import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import Llama, LlamaConfig
from kubeflow_tpu.serving import ServingConfig, ServingEngine, ServingServer
from kubeflow_tpu.topology.mesh import AxisSpec, make_host_local_mesh


@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig.tiny(max_seq_len=128)
    model = Llama(cfg)
    params = {
        "params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
        )["params"]
    }
    return model, params


def greedy_reference(model, params, prompt, n_new):
    """Generate by full re-forward each step — the semantic ground truth."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray([toks]))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def assert_greedy_tie_robust(model, params, prompt, generated):
    """Teacher-forced greedy check that tolerates bf16 logit ties.

    Prompt [3, 14, 15] hits an exact bf16 logit tie at its first decode
    step (tokens 157/215 — noted in PR 7): the engine's compiled decode
    path and the full-reforward reference legitimately break it in
    different orders, and once the prefixes diverge, follow-on steps sit
    within one bf16 ulp of each other (the two programs only agree to
    bf16 precision). Instead of pinning one arbitrary winner, re-forward
    the ENGINE'S OWN prefix at every step and assert its token's logit
    is within bf16 rounding of the reference max — a real engine-state
    bug picks tokens whole logit-gaps below the max, far outside one
    ulp."""
    toks = list(prompt)
    for tok in generated:
        logits = model.apply(params, jnp.asarray([toks]))[0, -1]
        top = float(logits[int(jnp.argmax(logits))])
        ulp = 2.0 ** -8 * max(1.0, abs(top))   # bf16: 8 mantissa bits
        assert float(logits[tok]) >= top - ulp, (
            f"engine token {tok} (logit {logits[tok]}) is not within a "
            f"bf16 ulp of the reference max {top} at prefix {toks}"
        )
        toks.append(tok)


class TestServingEngine:
    def test_greedy_matches_full_reforward(self, model_and_params):
        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=2, max_len=128))
        prompt = [3, 14, 15, 92, 65]
        rid = engine.submit(prompt, max_new_tokens=8)
        results = engine.run()
        assert len(results) == 1
        ref = greedy_reference(model, params, prompt, 8)
        assert results[0].tokens == ref
        assert results[0].prompt_len == len(prompt)

    def test_continuous_batching_isolation(self, model_and_params):
        """Requests sharing a batch must produce the same tokens as when
        run alone — slots must not leak into each other."""
        model, params = model_and_params
        prompts = [[1, 2, 3], [50, 60, 70, 80, 90, 100, 7], [9] * 20]
        solo = []
        for p in prompts:
            eng = ServingEngine(model, params,
                                ServingConfig(max_batch=1, max_len=128))
            eng.submit(p, max_new_tokens=6)
            solo.append(eng.run()[0].tokens)

        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=3, max_len=128))
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        batched = {r.request_id: r.tokens for r in eng.run()}
        for rid, expect in zip(rids, solo):
            assert batched[rid] == expect

    def test_staggered_admission(self, model_and_params):
        """More requests than slots: later requests admit as slots free."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        rids = [eng.submit([i + 1, i + 2], max_new_tokens=3 + i)
                for i in range(5)]
        results = eng.run()
        assert len(results) == 5
        for i, rid in enumerate(rids):
            assert len(eng.result(rid).tokens) == 3 + i

    def test_eos_stops_early(self, model_and_params):
        model, params = model_and_params
        ref = greedy_reference(model, params, [5, 6, 7], 8)
        eos = ref[2]  # force stop at third token
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        eng.submit([5, 6, 7], max_new_tokens=8, eos_token=eos)
        res = eng.run()[0]
        assert res.finished_reason == "eos"
        assert res.tokens == ref[:3]

    def test_temperature_sampling_varies(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        a = eng.submit([1, 2, 3], max_new_tokens=12, temperature=2.0)
        b = eng.submit([1, 2, 3], max_new_tokens=12, temperature=2.0)
        eng.run()
        assert eng.result(a).tokens != eng.result(b).tokens

    def test_top_k_one_matches_greedy(self, model_and_params):
        """top_k=1 collapses sampling to argmax regardless of temperature:
        the whole engine path (prefill first token + chunked decode) must
        be greedy against the reference — tie-robustly, because prompt
        [3, 14, 15]'s first decode step holds an exact bf16 logit tie
        that the two compiled programs break in different orders (the
        PR-7 known-red; see assert_greedy_tie_robust)."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        prompt = [3, 14, 15]
        eng.submit(prompt, max_new_tokens=6, temperature=1.7, top_k=1)
        res = eng.run()[0]
        assert len(res.tokens) == 6
        assert_greedy_tie_robust(model, params, prompt, res.tokens)

    def test_tiny_top_p_matches_greedy(self, model_and_params):
        """top_p -> 0 keeps only the head of the nucleus (the first
        candidate always survives), i.e. argmax."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        prompt = [5, 6, 7, 8]
        eng.submit(prompt, max_new_tokens=5, temperature=2.0, top_p=1e-6)
        res = eng.run()[0]
        assert res.tokens == greedy_reference(model, params, prompt, 5)

    def test_greedy_rows_unaffected_by_sampling_neighbours(
            self, model_and_params):
        """A greedy request sharing the batch with a top-k sampler must
        still produce the greedy tokens (the cond takes the restricted
        branch for the whole batch; the per-row where protects temp=0)."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        prompt = [9, 10, 11]
        g = eng.submit(prompt, max_new_tokens=6)
        eng.submit([1, 2, 3], max_new_tokens=6, temperature=1.5, top_k=4)
        eng.run()
        ref = greedy_reference(model, params, prompt, 6)
        assert eng.result(g).tokens == ref

    def test_greedy_logprobs_match_reforward(self, model_and_params):
        """Per-token logprobs are the raw-model log-softmax at each
        generated token — pinned against a full re-forward each step."""
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=1, max_len=128, logprobs=True))
        prompt = [3, 14, 15, 92]
        eng.submit(prompt, max_new_tokens=5)
        res = eng.run()[0]
        assert len(res.logprobs) == len(res.tokens)
        toks = list(prompt)
        for tok, lp in zip(res.tokens, res.logprobs):
            logits = model.apply(params, jnp.asarray([toks]))[0, -1]
            ref = jax.nn.log_softmax(logits.astype(jnp.float32))[tok]
            assert lp == pytest.approx(float(ref), abs=5e-2), (tok, lp)
            assert lp <= 0.0
            toks.append(tok)

    def test_logprobs_off_by_default(self, model_and_params):
        """Default engines skip the logprob math (it costs decode
        throughput): results carry zeros and the HTTP layer omits the
        key (tested in TestServingServer via the enabled engine)."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        eng.submit([1, 2, 3], max_new_tokens=3)
        res = eng.run()[0]
        assert res.logprobs == [0.0] * len(res.tokens)

    def test_rejects_oversized_prompt(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=64))
        with pytest.raises(ValueError):
            eng.submit(list(range(64)))
        with pytest.raises(ValueError):
            eng.submit([])

    def test_latency_metrics_recorded(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        eng.submit([4, 5], max_new_tokens=4)
        res = eng.run()[0]
        assert res.latency_s > 0
        assert 0 < res.ttft_s <= res.latency_s
        assert eng.tokens_generated == 4


class TestChunkedPrefill:
    """Prompts longer than the largest prefill bucket stream through
    _extend_step in bucket-width chunks (vLLM-style chunked prefill):
    the submit cap is max_len-1, not the bucket table."""

    def _engine(self, model, params, **kw):
        kw.setdefault("prefill_buckets", (16, 32))
        return ServingEngine(model, params,
                             ServingConfig(max_batch=2, max_len=128, **kw))

    def test_long_prompt_matches_reforward(self, model_and_params):
        model, params = model_and_params
        eng = self._engine(model, params)
        prompt = [(7 * i + 3) % 250 for i in range(70)]  # 70 > bucket 32
        eng.submit(prompt, max_new_tokens=6)
        res = eng.run()[0]
        assert res.tokens == greedy_reference(model, params, prompt, 6)
        assert res.prompt_len == 70

    def test_exact_multiple_of_bucket(self, model_and_params):
        model, params = model_and_params
        eng = self._engine(model, params)
        prompt = [(3 * i + 1) % 250 for i in range(64)]  # 2 full chunks
        eng.submit(prompt, max_new_tokens=4)
        res = eng.run()[0]
        assert res.tokens == greedy_reference(model, params, prompt, 4)

    def test_long_and_short_share_a_batch(self, model_and_params):
        """A chunked-prefill request and a grouped-prefill request decode
        together without corrupting each other's slots."""
        model, params = model_and_params
        long_p = [(5 * i + 2) % 250 for i in range(50)]
        short_p = [9, 10, 11]
        eng = self._engine(model, params)
        a = eng.submit(long_p, max_new_tokens=5)
        b = eng.submit(short_p, max_new_tokens=5)
        eng.run()
        assert eng.result(a).tokens == greedy_reference(
            model, params, long_p, 5)
        assert eng.result(b).tokens == greedy_reference(
            model, params, short_p, 5)

    def test_submit_caps_at_max_len(self, model_and_params):
        model, params = model_and_params
        eng = self._engine(model, params)
        eng.submit(list(range(100)), max_new_tokens=1)   # > bucket: fine
        with pytest.raises(ValueError, match="prompt length"):
            eng.submit(list(range(128)))                  # >= max_len
        eng.run()

    def test_partial_tail_near_cache_end(self):
        """Regression: a bucket-padded final chunk would
        dynamic-update-slice past max_seq_len, which JAX silently CLAMPS
        — overwriting earlier rows. The final chunk must slide back to
        full width instead (max_seq_len=48, bucket 32, prompt 40:
        ceil(40/32)*32 = 64 > 48)."""
        from kubeflow_tpu.models import Llama, LlamaConfig

        cfg = LlamaConfig.tiny(max_seq_len=48)
        model = Llama(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]}
        eng = ServingEngine(model, params, ServingConfig(
            max_batch=2, max_len=48, prefill_buckets=(16, 32)))
        prompt = [(13 * i + 7) % 250 for i in range(40)]
        eng.submit(prompt, max_new_tokens=4)
        res = eng.run()[0]
        assert res.tokens == greedy_reference(model, params, prompt, 4)

    def test_int8_kv_long_prompt(self):
        """Chunked prefill through an int8 KV cache stays token-exact
        against the bf16 full-reforward reference (greedy; the tiny
        model's margins tolerate the cache quantization)."""
        from kubeflow_tpu.models import Llama, LlamaConfig

        cfg = LlamaConfig.tiny(max_seq_len=128, kv_cache_dtype="int8")
        model = Llama(cfg)
        ref_model = Llama(LlamaConfig.tiny(max_seq_len=128))
        params = {"params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]}
        eng = self._engine(model, params)
        prompt = [(11 * i + 5) % 250 for i in range(40)]
        eng.submit(prompt, max_new_tokens=4)
        res = eng.run()[0]
        assert res.tokens == greedy_reference(ref_model, params, prompt, 4)


class TestSampleLogits:
    """Unit tier for the on-device sampler: crafted logits, many draws."""

    @pytest.fixture(scope="class")
    def eng(self, model_and_params):
        model, params = model_and_params
        return ServingEngine(model, params,
                             ServingConfig(max_batch=1, max_len=128))

    def _draws(self, eng, logits, samp, n=64):
        out = []
        for i in range(n):
            toks, _ = eng._sample_logits(
                jnp.asarray(logits), jax.random.PRNGKey(i),
                jnp.asarray(samp, jnp.float32))
            out.append(int(toks[0]))
        return out

    def test_top_k_support(self, eng):
        logits = np.array([[5.0, 4.9, 4.8, -2.0, -3.0, -50.0]])
        draws = self._draws(eng, logits, [[3.0, 3.0, 1.0]])
        assert set(draws) <= {0, 1, 2}
        assert len(set(draws)) > 1  # hot temperature really samples

    def test_top_p_support(self, eng):
        # softmax ~ [0.64, 0.24, 0.09, ...]: nucleus at 0.5 is {0} (mass
        # before token 1 is 0.64 >= 0.5), at 0.7 it is {0, 1}.
        logits = np.array([[4.0, 3.0, 2.0, -1.0, -1.0, -1.0]])
        assert set(self._draws(eng, logits, [[1.0, 0.0, 0.5]])) == {0}
        draws = self._draws(eng, logits, [[1.0, 0.0, 0.7]])
        assert set(draws) <= {0, 1} and len(set(draws)) == 2

    def test_combined_top_k_top_p(self, eng):
        # top_k=2 cuts to {0,1}; renormalised p ~ [0.73, 0.27] so
        # top_p=0.9 keeps both; both should appear at temp 1.
        logits = np.array([[4.0, 3.0, 2.9, 2.8, -1.0, -1.0]])
        draws = self._draws(eng, logits, [[1.0, 2.0, 0.9]])
        assert set(draws) == {0, 1}

    def test_per_row_independence(self, eng):
        """Rows carry independent settings: greedy / top-k / plain-temp
        rows in one batch each honour their own mode."""
        logits = np.tile(
            np.array([[1.0, 5.0, 4.95, 4.9, -9.0, -9.0]]), (3, 1))
        samp = [[0.0, 0.0, 1.0],    # greedy -> always 1
                [2.0, 2.0, 1.0],    # top-k 2 -> {1, 2}
                [5.0, 0.0, 1.0]]    # hot plain -> anything but -9 rows
        rows = [set() for _ in range(3)]
        for i in range(64):
            toks, _ = eng._sample_logits(
                jnp.asarray(logits), jax.random.PRNGKey(i),
                jnp.asarray(samp, jnp.float32))
            toks = np.asarray(toks)
            for r in range(3):
                rows[r].add(int(toks[r]))
        assert rows[0] == {1}
        assert rows[1] <= {1, 2} and len(rows[1]) == 2
        assert len(rows[2]) >= 3

    def test_plain_row_keeps_full_vocab_in_mixed_batch(self, eng):
        """A plain-temperature row co-batched with a top-k row must still
        sample the FULL vocab, not the top-``sample_candidates`` set the
        restricted branch works over (regression: batch composition must
        not change a request's distribution)."""
        V = 128  # > sample_candidates (64)
        logits = np.zeros((2, V), np.float32)
        logits[0, :64] = 2.0   # plain row: candidate set would be 0..63,
        # but the e^0 tail keeps ~40% mass at temp 5
        logits[1, 0] = 5.0
        samp = [[5.0, 0.0, 1.0],   # hot plain row
                [1.0, 2.0, 1.0]]   # top-k row forces the restricted branch
        draws = set()
        for i in range(64):
            toks, _ = eng._sample_logits(
                jnp.asarray(logits), jax.random.PRNGKey(i),
                jnp.asarray(samp, jnp.float32))
            draws.add(int(np.asarray(toks)[0]))
        assert any(t >= 64 for t in draws), draws


class TestChunkedDecode:
    def test_chunked_matches_single_step(self, model_and_params):
        """decode_chunk>1 (lax.scan on device) is a dispatch optimisation,
        not a semantic change: greedy output identical to chunk=1."""
        model, params = model_and_params
        prompts = [[3, 14, 15, 92], [7, 8, 9]]
        want, got = [], []
        for chunk in (1, 4):
            eng = ServingEngine(
                model, params,
                ServingConfig(max_batch=2, max_len=128, decode_chunk=chunk),
            )
            rids = [eng.submit(p, max_new_tokens=7) for p in prompts]
            eng.run()
            (want if chunk == 1 else got).extend(
                eng.result(r).tokens for r in rids
            )
        assert got == want

    def test_eos_mid_chunk_trims(self, model_and_params):
        model, params = model_and_params
        ref = greedy_reference(model, params, [5, 6, 7], 8)
        eos = ref[2]
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=1, max_len=128, decode_chunk=4),
        )
        eng.submit([5, 6, 7], max_new_tokens=8, eos_token=eos)
        res = eng.run()[0]
        assert res.finished_reason == "eos"
        assert res.tokens == ref[:3]

    def test_admission_after_chunk_completion(self, model_and_params):
        """Slots freed mid-chunk must re-admit cleanly (cache row reset by
        prefill) — more requests than slots with chunked decode."""
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128, decode_chunk=4),
        )
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8], [9, 10]]
        solo = []
        for p in prompts:
            ref = ServingEngine(model, params,
                                ServingConfig(max_batch=1, max_len=128))
            ref.submit(p, max_new_tokens=5)
            solo.append(ref.run()[0].tokens)
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run()
        assert [eng.result(r).tokens for r in rids] == solo


class TestPipelinedDecode:
    """run()'s in-flight dispatch pipeline (ServingConfig.pipeline_depth)
    is a latency optimisation, not a semantic change."""

    def test_pipelined_matches_sync(self, model_and_params):
        model, params = model_and_params
        prompts = [[3, 14, 15, 92], [7, 8, 9], [1, 2], [4, 4, 4]]
        outs = []
        for depth in (1, 2, 3):
            eng = ServingEngine(
                model, params,
                ServingConfig(max_batch=2, max_len=128, decode_chunk=3,
                              pipeline_depth=depth),
            )
            rids = [eng.submit(p, max_new_tokens=7) for p in prompts]
            eng.run()
            outs.append([eng.result(r).tokens for r in rids])
        assert outs[1] == outs[0]
        assert outs[2] == outs[0]

    def test_midbatch_admission_not_starved(self, model_and_params):
        """A slot freed while another slot keeps decoding must be refilled
        from the queue during the run, not after the whole batch ends
        (continuous batching under pipelining)."""
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128, decode_chunk=2,
                          pipeline_depth=2),
        )
        admissions = []
        orig = eng._prefill_group

        def spy(bucket, group):
            admissions.append([i for i, _ in group])
            orig(bucket, group)

        eng._prefill_group = spy
        long = eng.submit([1, 2, 3], max_new_tokens=24)
        short = eng.submit([4, 5], max_new_tokens=2)
        queued = eng.submit([6, 7], max_new_tokens=2)
        eng.run()
        for rid, n in ((long, 24), (short, 2), (queued, 2)):
            assert len(eng.result(rid).tokens) == n
        # The queued request must have been admitted in its own later wave
        # (slot freed by `short` mid-run), i.e. >= 2 admission events.
        assert len(admissions) >= 2
        # And the long request's stream stays correct despite the flush.
        ref = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        ref.submit([1, 2, 3], max_new_tokens=24)
        assert eng.result(long).tokens == ref.run()[0].tokens


class TestMoEServing:
    def test_mixtral_generates(self):
        """The engine is model-generic: the MoE family (top-2 routing,
        per-layer losses collection) serves through the same cache/decode
        path as dense Llama."""
        from kubeflow_tpu.models import Mixtral, MixtralConfig

        m = Mixtral(MixtralConfig.tiny(scan_layers=False))
        params = {"params": m.init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )["params"]}
        eng = ServingEngine(
            m, params,
            ServingConfig(max_batch=2, max_len=64, decode_chunk=4,
                          prefill_buckets=(8,)),
        )
        eng.warmup(8)
        rids = [eng.submit([1, 2, 3], max_new_tokens=5) for _ in range(3)]
        eng.run()
        outs = [eng.result(r).tokens for r in rids]
        assert all(len(t) == 5 for t in outs)
        # identical prompts, greedy -> identical continuations
        assert outs[0] == outs[1] == outs[2]


class TestQuantizedServing:
    def test_int8_weights_quantized_and_logits_close(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=1, max_len=128, quantize="int8",
                          quantize_min_size=64),
        )
        kernels = [
            x for x in jax.tree.leaves(eng.params)
            if x.dtype == jnp.int8
        ]
        assert kernels, "no leaf was quantized"
        # Dequantised weights must reconstruct the original logits to
        # int8 granularity: compare a forward pass through the
        # dequantised tree against the pristine params (deterministic —
        # unlike greedy token comparison on a random-init model).
        deq = eng._materialize(eng.params)
        tokens = jnp.asarray([[3, 14, 15, 92]], jnp.int32)
        got = model.apply({"params": deq["params"]}, tokens)
        want = model.apply(params, tokens)
        g = np.asarray(got, np.float32)
        w = np.asarray(want, np.float32)
        denom = np.maximum(np.abs(w).max(), 1e-6)
        assert np.abs(g - w).max() / denom < 0.05, (
            np.abs(g - w).max(), denom
        )
        # And generation runs end-to-end on the quantized engine.
        rid = eng.submit([3, 14, 15, 92], max_new_tokens=8)
        eng.run()
        assert len(eng.result(rid).tokens) == 8

    def test_rejects_unknown_scheme(self, model_and_params):
        model, params = model_and_params
        import pytest as _pytest
        with _pytest.raises(ValueError, match="quantize"):
            ServingEngine(model, params,
                          ServingConfig(max_batch=1, max_len=128,
                                        quantize="fp4"))


class TestShardedServing:
    def test_sharded_engine_matches_unsharded(self, model_and_params,
                                              devices8):
        """tp-sharded KV heads + dp-sharded slots must be a pure relayout:
        greedy tokens identical to the single-device engine."""
        model, params = model_and_params
        mesh = make_host_local_mesh(AxisSpec(dp=4, tp=2))
        prompts = [[3, 14, 15, 92], [7, 8], [100] * 11]

        plain = ServingEngine(model, params,
                              ServingConfig(max_batch=4, max_len=128))
        rids = [plain.submit(p, max_new_tokens=6) for p in prompts]
        plain.run()
        want = [plain.result(r).tokens for r in rids]

        sharded = ServingEngine(
            model, params, ServingConfig(max_batch=4, max_len=128), mesh=mesh
        )
        rids = [sharded.submit(p, max_new_tokens=6) for p in prompts]
        sharded.run()
        got = [sharded.result(r).tokens for r in rids]
        assert got == want

        # The layout is real: KV cache heads sharded over tp, slots over dp.
        kv = [l for l in jax.tree.leaves(sharded._cache)
              if l.dtype != jnp.int32][0]
        spec = kv.sharding.spec
        assert spec[kv.ndim - 2] == "tp"

    def test_params_land_in_logical_shardings(self, model_and_params,
                                              devices8):
        model, params = model_and_params
        mesh = make_host_local_mesh(AxisSpec(dp=4, tp=2))
        eng = ServingEngine(
            model, params, ServingConfig(max_batch=4, max_len=128), mesh=mesh
        )
        # q_proj kernel is ("embed","heads","head_dim"): heads on tp.
        k = eng.params["params"]["layer_0"]["attn"]["q_proj"]["kernel"]
        assert k.sharding.spec[1] == "tp", k.sharding.spec


class TestServingServer:
    def test_http_generate_roundtrip(self, model_and_params):
        """Mirror of the reference serving probe (test_tf_serving.py:60-156):
        start the server, wait healthy, query generate over HTTP, assert the
        tokens match the engine's ground truth."""
        model, params = model_and_params
        engine = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128, logprobs=True))
        server = ServingServer(engine, model_name="llama-test").start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            health = json.load(urllib.request.urlopen(f"{base}/healthz"))
            assert health["ok"] is True

            models = json.load(urllib.request.urlopen(f"{base}/v1/models"))
            assert models["models"][0]["name"] == "llama-test"

            prompt = [3, 14, 15, 92, 65]
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(
                    {"tokens": prompt, "max_new_tokens": 6}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(req))
            ref = greedy_reference(model, params, prompt, 6)
            assert out["tokens"] == ref
            assert out["prompt_len"] == len(prompt)
            assert out["latency_s"] >= out["ttft_s"] > 0
            assert len(out["logprobs"]) == len(out["tokens"])
            assert all(lp <= 0.0 for lp in out["logprobs"])

            # Sampling controls ride the same surface: top_k=1 at hot
            # temperature must still reproduce the greedy tokens.
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({
                    "tokens": prompt, "max_new_tokens": 6,
                    "temperature": 1.8, "top_k": 1, "top_p": 0.95,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(req))
            assert out["tokens"] == ref
        finally:
            server.stop()

    def test_http_streaming_generate(self, model_and_params):
        """stream=true returns NDJSON token deltas followed by a done
        chunk; concatenated deltas equal the non-streaming result."""
        model, params = model_and_params
        engine = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128, decode_chunk=2,
                          logprobs=True),
        )
        server = ServingServer(engine, model_name="llama-test").start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            prompt = [3, 14, 15, 92, 65]
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({
                    "tokens": prompt, "max_new_tokens": 6, "stream": True,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            chunks = []
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == "application/x-ndjson"
                for line in r:
                    chunks.append(json.loads(line))
            toks = [t for c in chunks if "tokens" in c for t in c["tokens"]]
            lps = [l for c in chunks if "tokens" in c for l in c["logprobs"]]
            done = chunks[-1]
            assert done.get("done") is True
            assert done["prompt_len"] == len(prompt)
            assert toks == greedy_reference(model, params, prompt, 6)
            assert len(lps) == len(toks) and all(l <= 0.0 for l in lps)
            # at least one token delta preceded the done chunk (chunk
            # COUNT is thread-scheduling dependent, so don't pin it)
            assert sum(1 for c in chunks if "tokens" in c) >= 1
        finally:
            server.stop()

    def test_text_in_text_out_with_tokenizer(self, model_and_params, tmp_path):
        """A server-side tokenizer enables the {"text": ...} surface: text
        prompts encode, responses carry decoded text."""
        from tokenizers import Tokenizer, models as tok_models
        from tokenizers import pre_tokenizers

        vocab = {"<unk>": 0, "hello": 1, "tpu": 2}
        vocab.update({f"w{i}": 3 + i for i in range(60)})
        tok = Tokenizer(tok_models.WordLevel(vocab, unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.Whitespace()
        tok_file = tmp_path / "tokenizer.json"
        tok.save(str(tok_file))

        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=2, max_len=128))
        server = ServingServer(
            engine, tokenizer=Tokenizer.from_file(str(tok_file)),
        ).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(
                    {"text": "hello tpu", "max_new_tokens": 4}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(req))
            assert out["prompt_len"] == 2          # "hello tpu" -> [1, 2]
            assert out["tokens"] == greedy_reference(
                model, params, [1, 2], 4
            )
            assert isinstance(out["text"], str)
        finally:
            server.stop()

    def test_text_without_tokenizer_is_400(self, model_and_params):
        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=1, max_len=64))
        server = ServingServer(engine).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/generate",
                data=json.dumps({"text": "hi"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req)
            assert exc.value.code == 400
        finally:
            server.stop()

    def test_streaming_submission_error_is_400(self, model_and_params):
        """Validation failures must be the same HTTP 400 for stream=true —
        not a 200 with an error chunk."""
        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=1, max_len=32,
                                             prefill_buckets=(8,)))
        server = ServingServer(engine).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/generate",
                data=json.dumps({
                    "tokens": list(range(50)), "stream": True,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req)
            assert exc.value.code == 400
        finally:
            server.stop()

    def test_oversized_prompt_rejected_not_fatal(self, model_and_params):
        """A prompt the cache cannot hold (>= max_len) must 400 — and must
        NOT kill the engine driver (the server stays serviceable).
        Bucket-exceeding prompts are NOT oversized anymore: they take the
        chunked-prefill path (TestChunkedPrefill)."""
        model, params = model_and_params
        engine = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128,
                          prefill_buckets=(16, 32)),
        )
        server = ServingServer(engine).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({"tokens": list(range(1, 130))}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 400

            # Server still healthy and serving after the bad request.
            ok = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(
                    {"tokens": [1, 2, 3], "max_new_tokens": 2}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(ok))
            assert len(out["tokens"]) == 2
            health = json.load(urllib.request.urlopen(f"{base}/healthz"))
            assert health["ok"] is True
        finally:
            server.stop()

    def test_http_bad_request(self, model_and_params):
        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=1, max_len=64))
        server = ServingServer(engine).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/generate",
                data=json.dumps({"tokens": "nope"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 400
        finally:
            server.stop()


class TestServingScannedModel:
    def test_scanned_layers_cache_layout(self):
        cfg = LlamaConfig.tiny(max_seq_len=128, scan_layers=True, num_layers=2)
        model = Llama(cfg)
        params = {
            "params": model.init(
                jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
            )["params"]
        }
        prompt = [3, 14, 15]
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128))
        eng.submit(prompt, max_new_tokens=5)
        out = eng.run()[0].tokens
        ref = greedy_reference(model, params, prompt, 5)
        assert out == ref


class TestTrainServeHandoff:
    def test_server_loads_trained_checkpoint(self, tmp_path, monkeypatch):
        """The full platform loop: train a job (writes orbax checkpoints),
        then stand up serving FROM that checkpoint and assert the served
        params are the trained ones, not a fresh init."""
        import os

        from kubeflow_tpu.train import runner
        from kubeflow_tpu.serving.server import build_server, env_config

        ckpt = str(tmp_path / "ckpt")
        for k in list(os.environ):
            if k.startswith("KFTPU_"):
                monkeypatch.delenv(k)
        for k, v in {
            "KFTPU_MODEL": "llama-tiny", "KFTPU_TRAIN_STEPS": "3",
            "KFTPU_BATCH_PER_HOST": "8", "KFTPU_SEQ_LEN": "16",
            "KFTPU_MESH": json.dumps({"dp": -1}),
            "KFTPU_CHECKPOINT_DIR": ckpt,
            "KFTPU_CHECKPOINT_EVERY": "1",
            "KFTPU_TERMINATION_LOG": str(tmp_path / "t.json"),
        }.items():
            monkeypatch.setenv(k, v)
        assert runner.run(runner.env_config()) == 0

        monkeypatch.setenv("KFTPU_SERVING_MODEL", "llama-tiny")
        monkeypatch.setenv("KFTPU_SERVING_CHECKPOINT_DIR", ckpt)
        monkeypatch.setenv("KFTPU_SERVING_MAX_LEN", "64")
        monkeypatch.setenv("KFTPU_SERVING_HOST", "127.0.0.1")
        monkeypatch.setenv("KFTPU_SERVING_PORT", "0")
        server = build_server(env_config())

        # Params must match the checkpoint, not a fresh init.
        from kubeflow_tpu.train.checkpoint import CheckpointService

        svc = CheckpointService(ckpt)
        saved = svc.restore_raw_latest()
        svc.close()
        leaf_saved = jax.tree.leaves(saved["params"])[0]
        leaf_served = jax.tree.leaves(server.engine.params["params"])[0]
        np.testing.assert_allclose(
            np.asarray(leaf_served, np.float32),
            np.asarray(leaf_saved, np.float32), rtol=1e-2, atol=1e-2,
        )

        # And it generates.
        server.start()
        try:
            import urllib.request

            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/generate",
                data=json.dumps(
                    {"tokens": [3, 5, 7], "max_new_tokens": 4}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(req))
            assert len(out["tokens"]) == 4
        finally:
            server.stop()

    def test_engine_knobs_from_env(self, monkeypatch):
        """KFTPU_SERVING_QUANTIZE / PARAM_DTYPE / PREFILL_BUCKETS /
        PIPELINE_DEPTH reach the engine's ServingConfig — the CRD-to-engine
        path that makes int8 switchable from a Serving CR."""
        import os

        from kubeflow_tpu.serving.server import build_server, env_config

        for k in list(os.environ):
            if k.startswith("KFTPU_SERVING"):
                monkeypatch.delenv(k)
        monkeypatch.setenv("KFTPU_SERVING_MODEL", "llama-tiny")
        monkeypatch.setenv("KFTPU_SERVING_MAX_LEN", "64")
        monkeypatch.setenv("KFTPU_SERVING_HOST", "127.0.0.1")
        monkeypatch.setenv("KFTPU_SERVING_PORT", "0")
        monkeypatch.setenv("KFTPU_SERVING_QUANTIZE", "int8")
        monkeypatch.setenv("KFTPU_SERVING_PARAM_DTYPE", "float32")
        monkeypatch.setenv("KFTPU_SERVING_PREFILL_BUCKETS", "16,32")
        monkeypatch.setenv("KFTPU_SERVING_PIPELINE_DEPTH", "1")
        cfg = env_config()
        assert cfg["quantize"] == "int8"
        assert cfg["prefill_buckets"] == [16, 32]
        server = build_server(cfg)
        assert server.engine.cfg.quantize == "int8"
        assert server.engine.cfg.param_dtype == "float32"
        assert server.engine.cfg.prefill_buckets == (16, 32)
        assert server.engine.cfg.pipeline_depth == 1
        # defaults survive when env is absent
        for k in ("KFTPU_SERVING_QUANTIZE", "KFTPU_SERVING_PARAM_DTYPE",
                  "KFTPU_SERVING_PREFILL_BUCKETS",
                  "KFTPU_SERVING_PIPELINE_DEPTH"):
            monkeypatch.delenv(k)
        cfg = env_config()
        assert cfg["quantize"] == "" and cfg["prefill_buckets"] == []

    def test_missing_checkpoint_fails_loudly(self, tmp_path, monkeypatch):
        from kubeflow_tpu.serving.server import build_server, env_config

        monkeypatch.setenv("KFTPU_SERVING_MODEL", "llama-tiny")
        monkeypatch.setenv("KFTPU_SERVING_CHECKPOINT_DIR",
                           str(tmp_path / "empty"))
        with pytest.raises(RuntimeError, match="no checkpoint"):
            build_server(env_config())


class TestBatchedPrefill:
    def test_group_admission_single_dispatch(self, model_and_params):
        """Simultaneous same-bucket admissions prefill in one compiled call
        (k-padded), and produce the same tokens as solo runs."""
        model, params = model_and_params
        prompts = [[1, 2, 3], [4, 5, 6, 7], [8, 9]]
        solo = []
        for p in prompts:
            ref = ServingEngine(model, params,
                                ServingConfig(max_batch=1, max_len=128))
            ref.submit(p, max_new_tokens=5)
            solo.append(ref.run()[0].tokens)

        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=4, max_len=128))
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run()
        assert [eng.result(r).tokens for r in rids] == solo
        # 3 admissions pad to one k=4 group on the 32-token bucket: exactly
        # one prefill program, compiled once.
        assert set(eng._prefill_fns) == {(32, 4)}
        assert eng._prefill_fns[(32, 4)]._cache_size() == 1

    def test_mixed_buckets_group_separately(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=4, max_len=128))
        rids = [eng.submit([1] * 5, max_new_tokens=3),
                eng.submit([2] * 40, max_new_tokens=3)]
        eng.run()
        assert {(32, 1), (64, 1)} == set(eng._prefill_fns)
        assert all(len(eng.result(r).tokens) == 3 for r in rids)

    def test_non_pow2_max_batch_k_capped(self, model_and_params):
        """max_batch 6: a 6-admission burst must pad to k=6 (the warmup-
        compiled cap), never to an uncompiled k=8 beyond the slot count."""
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=6, max_len=128))
        eng.warmup(8)
        assert {k for (_, k) in eng._prefill_fns} == {1, 2, 4, 6}
        rids = [eng.submit([i + 1, i + 2], max_new_tokens=2)
                for i in range(6)]
        eng.run()
        assert all(len(eng.result(r).tokens) == 2 for r in rids)
        assert (32, 6) in eng._prefill_fns
        assert not any(k > 6 for (_, k) in eng._prefill_fns)


class TestScanLayoutHandoff:
    def test_scanned_checkpoint_serves_unrolled(self, tmp_path, monkeypatch):
        """Serving decode builds the model UNROLLED (a scanned stacked KV
        cache pays a whole-layer-cache slice+writeback per scan step;
        BASELINE.md measures +18% gen tok/s), while training prefers
        scan_layers=True for O(1) compile. A checkpoint trained scanned
        must restore into the unrolled server via models/layout.py."""
        import os

        from kubeflow_tpu.train import runner
        from kubeflow_tpu.serving.server import build_server, env_config

        ckpt = str(tmp_path / "ckpt")
        for k in list(os.environ):
            if k.startswith("KFTPU_"):
                monkeypatch.delenv(k)
        for k, v in {
            "KFTPU_MODEL": "llama-tiny", "KFTPU_TRAIN_STEPS": "2",
            "KFTPU_MODEL_KW": json.dumps({"scan_layers": True}),
            "KFTPU_BATCH_PER_HOST": "8", "KFTPU_SEQ_LEN": "16",
            "KFTPU_MESH": json.dumps({"dp": -1}),
            "KFTPU_CHECKPOINT_DIR": ckpt,
            "KFTPU_CHECKPOINT_EVERY": "1",
            "KFTPU_TERMINATION_LOG": str(tmp_path / "t.json"),
        }.items():
            monkeypatch.setenv(k, v)
        assert runner.run(runner.env_config()) == 0

        # The checkpoint really is in the scanned layout.
        from kubeflow_tpu.train.checkpoint import CheckpointService

        svc = CheckpointService(ckpt)
        saved = svc.restore_raw_latest()
        svc.close()
        assert "layers" in saved["params"]

        monkeypatch.setenv("KFTPU_SERVING_MODEL", "llama-tiny")
        monkeypatch.setenv("KFTPU_SERVING_CHECKPOINT_DIR", ckpt)
        monkeypatch.setenv("KFTPU_SERVING_MAX_LEN", "64")
        monkeypatch.setenv("KFTPU_SERVING_HOST", "127.0.0.1")
        monkeypatch.setenv("KFTPU_SERVING_PORT", "0")
        server = build_server(env_config())
        served = server.engine.params["params"]
        assert "layers" not in served and "layer_0" in served
        # Adapted params carry the trained values, not a fresh init.
        import numpy as np

        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(
                served["layer_0"])[0], np.float32),
            np.asarray(jax.tree.leaves(
                jax.tree.map(lambda x: x[0], saved["params"]["layers"])
            )[0], np.float32),
            # bf16 serving cast of the f32-trained params
            rtol=1e-2, atol=1e-2,
        )
        # And it decodes.
        eng = server.engine
        eng.warmup(8)
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run()
        assert len(eng.result(rid).tokens) == 4


class TestLayoutHelpers:
    def test_round_trip(self):
        from kubeflow_tpu.models.layout import (
            adapt_layout,
            to_layer_layout,
            to_scanned_layout,
        )

        scanned = {
            "embed": jnp.ones((4, 3)),
            "layers": {"w": jnp.arange(12.0).reshape(3, 4),
                       "b": jnp.arange(6.0).reshape(3, 2)},
        }
        unrolled = to_layer_layout(scanned, 3)
        assert "layers" not in unrolled
        assert set(k for k in unrolled if k.startswith("layer_")) == {
            "layer_0", "layer_1", "layer_2"}
        np.testing.assert_array_equal(
            unrolled["layer_1"]["w"], scanned["layers"]["w"][1])
        back = to_scanned_layout(unrolled, 3)
        jax.tree.map(np.testing.assert_array_equal, back, scanned)
        # adapt_layout is idempotent in either direction
        assert adapt_layout(unrolled, 3, scanned=False) is unrolled
        jax.tree.map(
            np.testing.assert_array_equal,
            adapt_layout(scanned, 3, scanned=True), scanned)


class TestKvCacheQuantization:
    """int8 KV cache (LlamaConfig.kv_cache_dtype): per-(slot, position,
    kv-head) absmax scales halve the decode KV footprint. Prefill attends
    the live k/v, so only decode reads dequantized rows."""

    def _engine(self, kv_dtype):
        from kubeflow_tpu.models import Llama, LlamaConfig

        m = Llama(LlamaConfig.tiny(kv_cache_dtype=kv_dtype))
        params = {"params": m.init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )["params"]}
        return ServingEngine(
            m, params,
            ServingConfig(max_batch=2, max_len=64, decode_chunk=4,
                          prefill_buckets=(8,)),
        )

    def test_cache_leaves_are_int8_with_scales(self):
        eng = self._engine("int8")
        leaves = jax.tree_util.tree_flatten_with_path(eng._cache)[0]
        dtypes = {jax.tree_util.keystr(p): l.dtype for p, l in leaves}
        kv = [d for k, d in dtypes.items()
              if "cached_key" in k or "cached_value" in k]
        assert kv and all(d == jnp.int8 for d in kv)
        scales = [d for k, d in dtypes.items() if "scale" in k]
        assert scales and all(d == jnp.float32 for d in scales)

    def test_greedy_decode_matches_bf16_cache(self):
        """Same prompt, greedy: the int8 cache must reproduce the exact
        token sequence of the unquantized cache on the tiny model (absmax
        per-row int8 keeps attention outputs within ~0.5% — far inside
        the tiny model's greedy logit gaps)."""
        out = {}
        for kv in ("", "int8"):
            eng = self._engine(kv)
            eng.warmup(8)
            rid = eng.submit([3, 1, 4, 1, 5], max_new_tokens=8)
            eng.run()
            out[kv] = eng.result(rid).tokens
        assert len(out["int8"]) == 8
        assert out["int8"] == out[""]

    def test_spec_knob_reaches_the_model(self, monkeypatch):
        """Serving CR quantize_kv -> KFTPU_SERVING_QUANTIZE_KV ->
        build_server -> model config."""
        import os

        from kubeflow_tpu.serving.server import build_server, env_config

        for k in list(os.environ):
            if k.startswith("KFTPU_SERVING"):
                monkeypatch.delenv(k)
        monkeypatch.setenv("KFTPU_SERVING_MODEL", "llama-tiny")
        monkeypatch.setenv("KFTPU_SERVING_MAX_LEN", "64")
        monkeypatch.setenv("KFTPU_SERVING_HOST", "127.0.0.1")
        monkeypatch.setenv("KFTPU_SERVING_PORT", "0")
        monkeypatch.setenv("KFTPU_SERVING_QUANTIZE_KV", "int8")
        server = build_server(env_config())
        assert server.engine.model.cfg.kv_cache_dtype == "int8"


class TestDecodeStaging:
    """Chunk-staged decode (LlamaConfig.decode_staging): k/v write at the
    chunk-step column, one flush per chunk. Must be token-identical to the
    classic per-step writes across multi-chunk generations, alone and
    composed with the int8 KV cache."""

    def _tokens(self, staging, kv_dtype, chunk=4, n=11):
        from kubeflow_tpu.models import Llama, LlamaConfig

        m = Llama(LlamaConfig.tiny(
            kv_cache_dtype=kv_dtype,
            decode_staging=chunk if staging else 0,
        ))
        params = {"params": m.init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )["params"]}
        eng = ServingEngine(
            m, params,
            ServingConfig(max_batch=2, max_len=64, decode_chunk=chunk,
                          prefill_buckets=(8,)),
        )
        eng.warmup(8)
        rids = [eng.submit([3, 1, 4, 1, 5], max_new_tokens=n),
                eng.submit([2, 7, 1], max_new_tokens=n)]
        eng.run()
        return [eng.result(r).tokens for r in rids]

    @pytest.mark.parametrize("kv_dtype", ["", "int8"])
    def test_staged_matches_unstaged(self, kv_dtype):
        # n=11 with chunk=4 crosses two flush boundaries mid-generation.
        want = self._tokens(False, kv_dtype)
        got = self._tokens(True, kv_dtype)
        assert all(len(t) == 11 for t in got)
        assert got == want

    def test_chunk_longer_than_staging_refused(self):
        from kubeflow_tpu.models import Llama, LlamaConfig

        m = Llama(LlamaConfig.tiny(decode_staging=2))
        params = {"params": m.init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )["params"]}
        with pytest.raises(ValueError, match="decode_staging"):
            ServingEngine(m, params,
                          ServingConfig(max_batch=2, max_len=64,
                                        decode_chunk=4))


class TestPagedKV:
    """ISSUE 12: the paged KV-block allocator as the engine's admission
    ledger — capacity bounded by total blocks against actual request
    demand, mid-step retire/refill, exact conservation."""

    def test_block_gated_admission_and_midstep_refill(
            self, model_and_params):
        """kv_blocks=2 with 1-block requests on a 3-slot engine: only two
        sequences admit despite three free slots; the third claims its
        block table mid-run when a retirement frees it — and every token
        stays correct."""
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=3, max_len=128,
                          kv_block_size=16, kv_blocks=2))
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8]]
        # Unequal decode lengths: the short one retires while the long
        # one is mid-decode, so the queued request's admission is
        # genuinely mid-step.
        ns = [3, 7, 3]
        rids = [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, ns)]
        eng._admit()
        assert eng.active_slots == 2          # slot free, blocks not
        assert eng.queued == 1
        assert eng.blocks.blocks_free == 0
        eng.run()
        for rid, p, n in zip(rids, prompts, ns):
            ref = ServingEngine(model, params,
                                ServingConfig(max_batch=1, max_len=128))
            ref.submit(p, max_new_tokens=n)
            assert eng.result(rid).tokens == ref.run()[0].tokens
        # The third admission happened while others were mid-decode.
        assert eng.admissions_midstep >= 1
        eng.blocks.check_conservation()
        assert eng.blocks.blocks_live == 0
        assert eng.blocks.blocks_allocated_total == \
            eng.blocks.blocks_freed_total == 3

    def test_demand_exceeding_pool_rejected_at_submit(
            self, model_and_params):
        """A request whose KV demand could NEVER fit the pool is a 400
        at the front door, not a queue-forever."""
        model, params = model_and_params
        eng = ServingEngine(
            model, params,
            ServingConfig(max_batch=2, max_len=128,
                          kv_block_size=16, kv_blocks=1))
        with pytest.raises(ValueError, match="KV demand"):
            eng.submit(list(range(1, 20)), max_new_tokens=4)
        # A fitting request still serves.
        eng.submit([1, 2, 3], max_new_tokens=2)
        assert len(eng.run()[0].tokens) == 2

    def test_load_reports_blocks_rate_and_resident_prefixes(
            self, model_and_params):
        """load() carries the paged-KV occupancy, the continuous-batching
        slot-free rate, and resident-prefix hints — the cache-affine
        dispatch inputs the LB ingests."""
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        model, params = model_and_params
        reg = MetricsRegistry()
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128),
                            registry=reg)
        for _ in range(3):
            eng.submit([9, 8, 7, 6, 5, 4, 3, 2], max_new_tokens=2,
                       session="conv-42")
        eng.run()
        load = eng.load()
        assert load["kv_blocks_total"] == eng.blocks.total_blocks
        assert load["kv_blocks_live"] == 0        # drained
        assert load["kv_block_size"] == 16
        assert load["slot_free_rate"] >= 0.0
        assert load["resident_prefixes"], "retired prefixes must hint"
        # Session keys hint too (the LB re-learns lost pins from these).
        assert "s:conv-42" in load["resident_prefixes"]
        assert reg.gauge(
            "kftpu_serving_kv_blocks_total",
            "KV-cache blocks in the pool").value() == float(
                eng.blocks.total_blocks)
        eng.blocks.check_conservation()


class TestBoundedAdmission:
    """ISSUE 7: bounded engine admission. A full queue fails FAST at
    submit (EngineOverloaded -> HTTP 429 + Retry-After) and never
    disturbs requests already admitted or queued."""

    def test_max_queue_overflow_raises(self, model_and_params):
        from kubeflow_tpu.serving import EngineOverloaded

        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128,
                                          max_queue=2),
                            registry=MetricsRegistry())
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.submit([4, 5, 6], max_new_tokens=4)
        with pytest.raises(EngineOverloaded) as ei:
            eng.submit([7, 8, 9], max_new_tokens=4)
        assert ei.value.retry_after_s >= 1.0
        assert eng.shed_total == 1
        assert eng.metrics_requests.value(outcome="shed") == 1.0
        assert eng.metrics_requests.value(outcome="admitted") == 2.0

    def test_overflow_never_poisons_admitted_requests(self, model_and_params):
        """The two admitted requests must decode token-exact despite the
        overflow between them and the run."""
        from kubeflow_tpu.serving import EngineOverloaded

        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128,
                                          max_queue=2))
        # NOTE: [3, 14, 15] is unusable here — its first decode step has
        # an exact bf16 logit tie (tokens 157/215) that the engine and the
        # full-reforward reference break differently.
        prompts = [[4, 5, 6, 7], [50, 60, 70]]
        rids = [eng.submit(p, max_new_tokens=4) for p in prompts]
        with pytest.raises(EngineOverloaded):
            eng.submit([9, 9, 9], max_new_tokens=4)
        results = {r.request_id: r.tokens for r in eng.run()}
        assert len(results) == 2
        for rid, p in zip(rids, prompts):
            assert results[rid] == greedy_reference(model, params, p, 4)
        # queue drained: the engine sheds nothing at rest
        assert eng.queued == 0
        assert eng.load()["queued"] == 0

    def test_zero_max_queue_is_unbounded(self, model_and_params):
        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128))
        for i in range(20):                  # far past any plausible bound
            eng.submit([i + 1], max_new_tokens=1)
        assert eng.queued == 20

    def test_load_snapshot_shape(self, model_and_params):
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=2, max_len=128,
                                          max_queue=8),
                            registry=MetricsRegistry())
        eng.submit([1, 2], max_new_tokens=2)
        load = eng.load()
        assert load["queued"] == 1
        assert load["active_slots"] == 0
        assert load["free_slots"] == 2
        assert load["max_batch"] == 2 and load["max_queue"] == 8
        eng.run()
        load = eng.load()
        assert load["queued"] == 0
        # queue waits observed at admission feed the percentiles
        assert load["p50_queue_wait_s"] >= 0.0
        assert eng.metrics_queue_wait.count() == 1

    def test_server_maps_overload_to_429_with_retry_after(
            self, model_and_params):
        """Slot held + queue full -> a third HTTP request gets 429 and a
        Retry-After hint; the held and queued requests still finish."""
        import threading

        model, params = model_and_params
        engine = ServingEngine(model, params,
                               ServingConfig(max_batch=1, max_len=128,
                                             max_queue=1))
        server = ServingServer(engine, model_name="llama-test").start()
        base = f"http://127.0.0.1:{server.port}"

        def fire(prompt, out):
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({"tokens": prompt,
                                 "max_new_tokens": 120}).encode(),
                headers={"Content-Type": "application/json"})
            out.append(json.load(urllib.request.urlopen(req, timeout=120)))

        import time as _time
        a_out, b_out = [], []
        try:
            ta = threading.Thread(target=fire, args=([3, 14, 15], a_out))
            ta.start()
            deadline = _time.time() + 30
            while engine.active_slots < 1:       # A holds the only slot
                assert _time.time() < deadline
                _time.sleep(0.002)
            tb = threading.Thread(target=fire, args=([4, 5, 6], b_out))
            tb.start()
            while engine.queued < 1:             # B waits in the queue
                assert _time.time() < deadline
                _time.sleep(0.002)
            # C: queue full -> 429, Retry-After integer >= 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                req = urllib.request.Request(
                    f"{base}/v1/generate",
                    data=json.dumps({"tokens": [7, 8, 9],
                                     "max_new_tokens": 4}).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            assert "full" in json.load(ei.value)["error"]
            # the shed request poisoned nothing: A and B complete
            ta.join(timeout=120)
            tb.join(timeout=120)
            assert len(a_out) == 1 and len(b_out) == 1
            assert len(a_out[0]["tokens"]) == 120
            assert len(b_out[0]["tokens"]) == 120
            # /healthz carries the load snapshot the LB/autoscaler read
            health = json.load(urllib.request.urlopen(f"{base}/healthz"))
            assert health["load"]["queued"] == 0
            assert health["load"]["max_queue"] == 1
            assert health["load"]["shed_total"] == 1
        finally:
            server.stop()

    def test_load_percentiles_decay_when_idle(self, model_and_params):
        """The load() ring is time-windowed: an idle engine must stop
        reporting its last burst's tail, or the autoscaler could never
        scale the burst's replicas back down (the quiet branch needs the
        signal to actually go quiet)."""
        import time as _time

        from kubeflow_tpu.serving.engine import LOAD_WINDOW_S

        model, params = model_and_params
        eng = ServingEngine(model, params,
                            ServingConfig(max_batch=1, max_len=128,
                                          max_queue=4))
        now = _time.monotonic()
        eng._recent_queue_waits.append((now - LOAD_WINDOW_S - 1.0, 0.5))
        assert eng.load()["p95_queue_wait_s"] == 0.0   # stale: ignored
        eng._recent_queue_waits.append((now, 0.25))
        assert eng.load()["p95_queue_wait_s"] == 0.25  # fresh: counted
