"""Tests for the runtime lock-order tracer and workqueue oracle
(ISSUE 16, utils/locktrace.py)."""

import threading

from kubeflow_tpu.utils import locktrace
from kubeflow_tpu.utils.locktrace import (
    LockTraceRegistry,
    TracedLock,
    TracedRLock,
    WorkqueueOracle,
)


def _acquire_pair(first, second):
    with first:
        with second:
            pass


class TestLockOrderGraph:
    def test_opposite_order_pair_is_a_cycle(self):
        reg = LockTraceRegistry()
        a = TracedLock("a", registry=reg)
        b = TracedLock("b", registry=reg)
        # Thread 1 takes a->b, thread 2 takes b->a: the classic
        # inversion. Sequential execution suffices — the GRAPH has the
        # cycle even though no deadlock fired this run.
        _acquire_pair(a, b)
        t = threading.Thread(target=_acquire_pair, args=(b, a))
        t.start()
        t.join()
        cycles = reg.cycles()
        assert cycles == [["a", "b", "a"]]

    def test_consistent_order_is_clean(self):
        reg = LockTraceRegistry()
        a = TracedLock("a", registry=reg)
        b = TracedLock("b", registry=reg)
        for _ in range(3):
            _acquire_pair(a, b)
        t = threading.Thread(target=_acquire_pair, args=(a, b))
        t.start()
        t.join()
        assert reg.cycles() == []
        assert reg.edges() == {("a", "b"): 4}
        assert reg.acquisitions() == {"a": 4, "b": 4}

    def test_three_lock_cycle_detected(self):
        reg = LockTraceRegistry()
        a = TracedLock("a", registry=reg)
        b = TracedLock("b", registry=reg)
        c = TracedLock("c", registry=reg)
        _acquire_pair(a, b)
        _acquire_pair(b, c)
        _acquire_pair(c, a)
        cycles = reg.cycles()
        assert len(cycles) == 1
        # Canonicalized: one cycle, not three rotations of it.
        assert set(cycles[0]) == {"a", "b", "c"}

    def test_rlock_reentry_no_self_edge(self):
        reg = LockTraceRegistry()
        r = TracedRLock("r", registry=reg)
        with r:
            with r:        # re-entry: must not trace a second acquire
                pass
        assert reg.edges() == {}
        assert reg.acquisitions() == {"r": 1}
        assert reg.cycles() == []

    def test_long_hold_recorded_with_stack(self):
        reg = LockTraceRegistry()
        reg.long_hold_threshold_s = 0.0   # everything is "long"
        lk = TracedLock("hot", registry=reg)
        with lk:
            pass
        holds = reg.long_holds()
        assert len(holds) == 1
        name, held_s, stack = holds[0]
        assert name == "hot"
        assert held_s >= 0.0
        assert stack   # the release stack names the holder

    def test_factories_respect_enable_flag(self):
        was = locktrace.enabled()
        try:
            locktrace.disable()
            assert isinstance(locktrace.lock("x"),
                              type(threading.Lock()))
            locktrace.enable()
            assert isinstance(locktrace.lock("x"), TracedLock)
            assert isinstance(locktrace.rlock("x"), TracedRLock)
        finally:
            if was:
                locktrace.enable(reset=False)
            else:
                locktrace.disable()
            locktrace.registry().reset()


class TestWorkqueueOracle:
    def test_bracketed_reconciles_clean(self):
        o = WorkqueueOracle()
        for i in range(5):
            o.enter("tpujob", ("ns", f"j{i}"))
            o.exit("tpujob", ("ns", f"j{i}"))
        assert o.clean()
        s = o.summary()
        assert s["entries"] == 5
        assert s["violations"] == []
        assert s["inflight_now"] == 0

    def test_same_key_different_controllers_ok(self):
        o = WorkqueueOracle()
        o.enter("tpujob", ("ns", "j"))
        o.enter("study", ("ns", "j"))    # distinct queue — fine
        o.exit("tpujob", ("ns", "j"))
        o.exit("study", ("ns", "j"))
        assert o.clean()

    def test_injected_double_dispatch_caught(self):
        """The fault the oracle exists for: two workers concurrently
        in-flight on the same (controller, key)."""
        o = WorkqueueOracle()
        first_in = threading.Event()
        release = threading.Event()

        def worker_one():
            o.enter("tpujob", ("ns", "dup"))
            first_in.set()
            release.wait(timeout=5)
            o.exit("tpujob", ("ns", "dup"))

        t = threading.Thread(target=worker_one)
        t.start()
        assert first_in.wait(timeout=5)
        o.enter("tpujob", ("ns", "dup"))   # second dispatch, same key
        release.set()
        t.join()
        o.exit("tpujob", ("ns", "dup"))
        assert not o.clean()
        v = o.summary()["violations"]
        assert len(v) == 1
        assert v[0]["controller"] == "tpujob"
        assert v[0]["key"] == ["ns", "dup"]
        assert v[0]["first_thread"] != v[0]["second_thread"]
        assert v[0]["first_stack"] and v[0]["second_stack"]


class TestViolationsHelper:
    def test_clean_summary_empty(self):
        assert locktrace.violations(
            {"cycles": [], "leaked_threads": [],
             "oracle": {"violations": []}}) == []

    def test_each_problem_class_rendered(self):
        out = locktrace.violations({
            "cycles": [["a", "b", "a"]],
            "leaked_threads": ["pool-worker-3"],
            "oracle": {"violations": [{
                "controller": "tpujob", "key": ["ns", "j"],
                "first_thread": 1, "second_thread": 2,
            }]},
        })
        assert len(out) == 3
        assert any("a -> b -> a" in line for line in out)
        assert any("pool-worker-3" in line for line in out)
        assert any("double-dispatch" in line for line in out)


class TestReport:
    def test_report_shape(self):
        reg = locktrace.registry()
        reg.reset()
        rep = locktrace.report()
        assert set(rep) == {"enabled", "cycles", "long_holds",
                            "acquisitions", "edges"}
        assert rep["cycles"] == []
