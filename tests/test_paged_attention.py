"""ISSUE 18: physically paged HBM.

Two layers of coverage:

- ``TestPagedOps``: the block-gather kernel in isolation — layout
  contract (logical position -> pool row, scratch redirection),
  scatter/gather round trip, page copy, and the exactness contract
  (paged_decode_attention bitwise-equal to the dense reference when the
  gathered span equals the dense span).
- ``TestDenseVsPagedTokens`` / ``TestCopyOnWriteServing``: the engine
  end to end — same trace + same seed on a dense-cache engine and a
  paged-pool engine must emit byte-identical tokens (the gate the
  serving8b bench leg and CI paged-smoke reuse), copy-on-write prefix
  sharing must be non-vacuous (shared refs AND forks actually happen)
  with the two-layer conservation invariant clean afterwards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import Llama, LlamaConfig
from kubeflow_tpu.ops.attention import mha_reference
from kubeflow_tpu.ops.paged_attention import (
    copy_block,
    gather_kv_pages,
    paged_decode_attention,
    physical_rows,
    pool_shape,
    scatter_kv_rows,
    scratch_block_id,
)
from kubeflow_tpu.serving import ServingConfig, ServingEngine

BS = 8                       # kv block size used throughout
MAX_LEN = 64
KV_BLOCKS = 4 * (MAX_LEN // BS)   # enough for max_batch=4 full slots


@pytest.fixture(scope="module")
def tiny_params():
    """Params are shared dense/paged — paging changes only cache vars."""
    model = Llama(LlamaConfig.tiny(max_seq_len=128))
    return {
        "params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
        )["params"]
    }


def make_engine(params, paged, model_kw=None, serve_kw=None):
    mc = dict(max_seq_len=128)
    mc.update(model_kw or {})
    if paged:
        mc.update(paged_kv_blocks=KV_BLOCKS, paged_kv_block_size=BS)
    model = Llama(LlamaConfig.tiny(**mc))
    sc = dict(max_batch=4, max_len=MAX_LEN)
    sc.update(serve_kw or {})
    if paged:
        sc.update(kv_blocks=KV_BLOCKS, kv_block_size=BS)
    return ServingEngine(model, params, ServingConfig(**sc))


def run_trace(eng, prompts, n_new=8):
    rids = [eng.submit(list(p), max_new_tokens=n_new) for p in prompts]
    results = {r.request_id: r.tokens for r in eng.run()}
    return [results[r] for r in rids]


MIXED_TRACE = [
    [7, 3, 9, 1, 4],
    [2] * 17,
    [250, 100, 3],
    [11, 22, 33, 44, 55, 66, 77],
]


class TestPagedOps:
    def test_pool_shape_and_scratch(self):
        assert pool_shape(32, 8, 2, 16) == (33, 8, 2, 16)
        assert pool_shape(32, 8, 2, 16, trailing=1) == (33, 8, 2, 1)
        assert scratch_block_id(32) == 32

    def test_physical_rows_layout_and_redirects(self):
        # Slot 0 owns physical blocks [5, 2]; slot 1 only [7].
        scratch = scratch_block_id(8)
        tables = jnp.asarray([[5, 2], [7, scratch]], jnp.int32)
        positions = jnp.asarray([[0, 3, 4, 7], [1, 4, 9, 0]], jnp.int32)
        valid = jnp.asarray(
            [[True, True, True, True], [True, True, True, False]])
        rows = physical_rows(tables, positions, 4, num_blocks=8,
                             valid=valid)
        srow = scratch * 4
        # p // bs picks the table column, p % bs the in-page offset.
        assert rows[0].tolist() == [5 * 4 + 0, 5 * 4 + 3, 2 * 4 + 0,
                                    2 * 4 + 3]
        # Slot 1: position 4 falls on its scratch-padded column, position
        # 9 is past the table width, position 0 is masked invalid — all
        # three must redirect to the scratch page, never another slot's.
        assert rows[1].tolist() == [7 * 4 + 1, srow, srow, srow]

    def test_scatter_gather_round_trip(self):
        rng = np.random.default_rng(0)
        pool = jnp.zeros(pool_shape(6, 4, 2, 3), jnp.float32)
        tables = jnp.asarray([[4, 1], [0, 3]], jnp.int32)
        positions = jnp.tile(jnp.arange(8)[None, :], (2, 1))
        vals = jnp.asarray(rng.normal(size=(2, 8, 2, 3)), jnp.float32)
        rows = physical_rows(tables, positions, 4, num_blocks=6)
        pool = scatter_kv_rows(pool, rows, vals)
        out = gather_kv_pages(pool, tables, 4)
        # Gather reproduces dense position order exactly.
        np.testing.assert_array_equal(np.asarray(out), np.asarray(vals))

    def test_copy_block_copies_one_page(self):
        pool = jnp.arange(6 * 4 * 2 * 3, dtype=jnp.float32).reshape(
            pool_shape(5, 4, 2, 3))
        out = copy_block(pool, 1, 3)
        np.testing.assert_array_equal(np.asarray(out[3]),
                                      np.asarray(pool[1]))
        for b in (0, 1, 2, 4, 5):
            np.testing.assert_array_equal(np.asarray(out[b]),
                                          np.asarray(pool[b]))

    def test_paged_decode_matches_dense_reference_bitwise(self):
        """Exactness contract: gathered attention == dense attention on
        the same logical KV, even with junk in unused pool pages."""
        rng = np.random.default_rng(1)
        B, S, H, Hkv, D, bs, nblk = 2, 1, 4, 2, 16, 4, 6
        L = 2 * bs
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, L, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, L, Hkv, D)), jnp.float32)
        # Junk-filled pool: only the tabled pages get real rows.
        kp = jnp.asarray(rng.normal(size=pool_shape(nblk, bs, Hkv, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.normal(size=pool_shape(nblk, bs, Hkv, D)),
                         jnp.float32)
        tables = jnp.asarray([[5, 0], [2, 4]], jnp.int32)
        positions = jnp.tile(jnp.arange(L)[None, :], (B, 1))
        rows = physical_rows(tables, positions, bs, num_blocks=nblk)
        kp = scatter_kv_rows(kp, rows, k)
        vp = scatter_kv_rows(vp, rows, v)
        # Mid-page live lengths: junk PAST the query position must mask.
        q_pos = jnp.asarray([[5], [L - 1]], jnp.int32)
        out = paged_decode_attention(q, kp, vp, tables, q_pos, bs)
        mask = (jnp.arange(L)[None, None, :] <= q_pos[:, :, None])
        ref = mha_reference(q, k, v, mask=mask[:, None, :, :])
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestDenseVsPagedTokens:
    """Satellite 3: same trace, same seed, dense cache vs paged pool —
    byte-identical output tokens at a batch point both reach."""

    def test_mixed_trace_token_exact(self, tiny_params):
        dense = make_engine(tiny_params, paged=False)
        paged = make_engine(tiny_params, paged=True)
        assert run_trace(dense, MIXED_TRACE) == \
            run_trace(paged, MIXED_TRACE)
        paged.blocks.check_conservation()
        assert paged.blocks.blocks_live == 0

    def test_int8_kv_staged_chunked_token_exact(self, tiny_params):
        """The SERVING8B config shape: int8 KV + decode staging +
        decode_chunk>1 + pipelined dispatch, dense vs paged."""
        mkw = dict(kv_cache_dtype="int8", decode_staging=4)
        skw = dict(decode_chunk=4, pipeline_depth=2)
        dense = make_engine(tiny_params, False, mkw, skw)
        paged = make_engine(tiny_params, True, mkw, skw)
        assert run_trace(dense, MIXED_TRACE) == \
            run_trace(paged, MIXED_TRACE)
        paged.blocks.check_conservation()

    def test_chunked_prefill_token_exact(self, tiny_params):
        """Prompt longer than the largest prefill bucket exercises the
        paged _extend_step path."""
        skw = dict(prefill_buckets=(16, 32))
        long_prompt = [(5 * i + 2) % 250 for i in range(50)]
        trace = [long_prompt, [4, 5, 6]]
        dense = make_engine(tiny_params, False, serve_kw=skw)
        paged = make_engine(tiny_params, True, serve_kw=skw)
        assert run_trace(dense, trace, n_new=6) == \
            run_trace(paged, trace, n_new=6)
        paged.blocks.check_conservation()

    def test_pool_governs_real_memory(self, tiny_params):
        """The tentpole's point: the paged cache leaves are sized by the
        pool (kv_blocks + scratch), NOT by max_batch * max_len — so
        shrinking kv_blocks shrinks actual HBM."""
        paged = make_engine(tiny_params, paged=True)
        leaves = [l for l in jax.tree_util.tree_leaves(paged._cache)
                  if l.ndim == 4]
        assert leaves, "no pool leaves found"
        assert all(l.shape[0] == KV_BLOCKS + 1 and l.shape[1] == BS
                   for l in leaves)
        # The dense cache materialises max_batch x model.max_seq_len
        # rows per layer regardless of how many are live.
        dense = make_engine(tiny_params, paged=False)
        dl = [l for l in jax.tree_util.tree_leaves(dense._cache)
              if l.ndim == 4]
        assert all(l.shape[:2] == (4, 128) for l in dl)

    def test_geometry_validation(self, tiny_params):
        params = tiny_params
        model = Llama(LlamaConfig.tiny(
            max_seq_len=128, paged_kv_blocks=KV_BLOCKS,
            paged_kv_block_size=BS))
        with pytest.raises(ValueError, match="divisible"):
            ServingEngine(model, params, ServingConfig(
                max_batch=4, max_len=60,       # 60 % 8 != 0
                kv_blocks=KV_BLOCKS, kv_block_size=BS))
        with pytest.raises(ValueError, match="kv_block_size"):
            ServingEngine(model, params, ServingConfig(
                max_batch=4, max_len=MAX_LEN,
                kv_blocks=KV_BLOCKS, kv_block_size=16))
        with pytest.raises(ValueError, match="paged_kv_blocks"):
            ServingEngine(model, params, ServingConfig(
                max_batch=4, max_len=MAX_LEN,
                kv_blocks=KV_BLOCKS // 2, kv_block_size=BS))


class TestCopyOnWriteServing:
    """COW prefix sharing through the live engine: matching prompts map
    to the SAME physical pages; the first decode write into a shared
    page forks it; tokens stay byte-identical to dense throughout."""

    def test_identical_prompts_share_fork_and_stay_exact(self, tiny_params):
        # 17 tokens with BS=8: blocks 0-1 fully shared, block 2 is a
        # shared PARTIAL tail — every sharer's first decode write lands
        # in it and must fork.
        trace = [[(7 * i + 3) % 250 for i in range(17)]] * 4
        dense = make_engine(tiny_params, paged=False)
        paged = make_engine(tiny_params, paged=True)
        assert run_trace(dense, trace, n_new=10) == \
            run_trace(paged, trace, n_new=10)
        # Non-vacuity: sharing AND forking actually happened.
        assert paged.blocks.shared_refs_total >= 3, "no blocks shared"
        assert paged.blocks.cow_copies_total >= 3, "no COW fork happened"
        paged.blocks.check_conservation()
        assert paged.blocks.blocks_live == 0
        assert paged.blocks.blocks_free == KV_BLOCKS

    def test_block_aligned_prefix_shares_without_fork(self, tiny_params):
        """Prompts that agree on exactly the first block but then
        diverge: the shared page is never written past (each sequence's
        private tail starts in its own fresh block), so sharing needs no
        fork and the idempotent prefill rewrite is exempt from COW."""
        head = [9, 8, 7, 6, 5, 4, 3, 2]            # exactly one block
        trace = [head + [100 + i] for i in range(3)]
        paged = make_engine(tiny_params, paged=True)
        dense = make_engine(tiny_params, paged=False)
        assert run_trace(dense, trace, n_new=6) == \
            run_trace(paged, trace, n_new=6)
        assert paged.blocks.shared_refs_total >= 2
        assert paged.blocks.cow_copies_total == 0
        paged.blocks.check_conservation()

    def test_sharing_lifts_effective_batch(self, tiny_params):
        """At fixed kv_blocks, a prefix-heavy trace admits sequences a
        no-sharing pool could not hold simultaneously — COW lifts
        effective batch (the bench COW leg's claim, engine-level)."""
        # Pool of 12 blocks; each request demands 3 blocks (17 prompt +
        # 6 new = 23 tokens -> ceil(23/8) = 3). Without sharing, 4
        # concurrent sequences need 12 blocks; WITH sharing the 2 fully
        # shared head blocks are counted once.
        prompt = [(3 * i + 1) % 250 for i in range(17)]
        model = Llama(LlamaConfig.tiny(
            max_seq_len=128, paged_kv_blocks=9, paged_kv_block_size=BS))
        eng = ServingEngine(model, tiny_params, ServingConfig(
            max_batch=4, max_len=MAX_LEN, kv_blocks=9, kv_block_size=BS))
        for _ in range(4):
            eng.submit(list(prompt), max_new_tokens=6)
        eng._admit()
        # 4 sequences x 3 blocks = 12 table refs on only 9 physical
        # blocks, minus fork reserve — sharing made >9 refs admissible.
        assert eng.active_slots >= 3
        assert eng.blocks.table_refs > eng.blocks.blocks_live
        res = eng.run()
        assert len(res) == 4 and all(len(r.tokens) == 6 for r in res)
        eng.blocks.check_conservation()
        assert eng.blocks.blocks_live == 0

    def test_load_and_metrics_report_paging(self, tiny_params):
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        reg = MetricsRegistry()
        model = Llama(LlamaConfig.tiny(
            max_seq_len=128, paged_kv_blocks=KV_BLOCKS,
            paged_kv_block_size=BS))
        eng = ServingEngine(model, tiny_params, ServingConfig(
            max_batch=4, max_len=MAX_LEN, kv_blocks=KV_BLOCKS,
            kv_block_size=BS), registry=reg)
        trace = [[(7 * i + 3) % 250 for i in range(17)]] * 3
        run_trace(eng, trace, n_new=6)
        load = eng.load()
        assert load["kv_paged"] is True
        assert load["kv_blocks_shared"] == 0           # drained
        assert load["kv_cow_copies_total"] >= 2
        assert load["kv_table_refs"] == 0
        assert reg.counter(
            "kftpu_serving_kv_cow_copies_total",
            "Copy-on-write block forks").value() >= 2.0
        assert reg.gauge(
            "kftpu_serving_kv_blocks_shared",
            "KV blocks referenced by more than one sequence",
        ).value() == 0.0
        snap = eng.blocks.snapshot()
        assert snap["kv_conservation_ok"] is True
