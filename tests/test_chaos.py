"""Chaos-injection layer + backoff workqueue (kubeflow_tpu.chaos).

Everything here is seeded and sleep-free: faults are a pure function of
(seed, call sequence), and all waiting is fast-forwarded through
``run_until_idle(include_timers_within=...)``.
"""

import pytest

from kubeflow_tpu.chaos import (
    ChaosApiServer,
    FaultSpec,
    SlicePreemptor,
    TransientApiError,
    run_soak,
)
from kubeflow_tpu.controlplane.api import ObjectMeta, TpuJob, TpuJobSpec
from kubeflow_tpu.controlplane.api.types import MeshAxesSpec
from kubeflow_tpu.controlplane.controllers import FakeKubelet, TpuJobController
from kubeflow_tpu.controlplane.controllers.tpujob import (
    JOB_LABEL,
    PREEMPTION_MESSAGE,
)
from kubeflow_tpu.controlplane.runtime import (
    ConflictError,
    Controller,
    ControllerManager,
    ExponentialBackoffLimiter,
    InMemoryApiServer,
    NotFoundError,
    Result,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def _job(name="train", ns="chaos", **spec_kw):
    spec_kw.setdefault("backoff_seconds", 0.0)
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type="v5e-16", mesh=MeshAxesSpec(dp=-1),
                        **spec_kw),
    )


# --------------------------------------------------------------------------
# Exponential backoff limiter
# --------------------------------------------------------------------------

class TestBackoffLimiter:
    def test_exact_doubling_without_jitter(self):
        lim = ExponentialBackoffLimiter(base_delay=0.01, max_delay=1.0,
                                        jitter=0.0)
        delays = [lim.next_delay("k") for _ in range(10)]
        assert delays[:7] == [0.01 * 2 ** i for i in range(7)]
        assert delays[7] == delays[8] == delays[9] == 1.0  # capped

    def test_monotone_jittered_capped(self):
        """Property: delays are in [raw*(1-j), raw], monotone
        non-decreasing until the cap, and never exceed the cap."""
        base, cap, j = 0.05, 5.0, 0.2
        lim = ExponentialBackoffLimiter(base_delay=base, max_delay=cap,
                                        jitter=j, seed=7)
        delays = [lim.next_delay("k") for _ in range(16)]
        raws = [min(base * 2 ** i, cap) for i in range(16)]
        for d, raw in zip(delays, raws):
            assert raw * (1 - j) <= d <= raw
        pre_cap = sum(1 for r in raws if r < cap)
        for i in range(pre_cap):
            assert delays[i + 1] >= delays[i], (i, delays)
        assert max(delays) <= cap

    def test_reset_on_success(self):
        lim = ExponentialBackoffLimiter(base_delay=0.01, max_delay=1.0,
                                        jitter=0.0)
        for _ in range(5):
            lim.next_delay("k")
        assert lim.failures("k") == 5
        assert lim.tracked_keys() == 1
        lim.forget("k")
        assert lim.failures("k") == 0
        assert lim.tracked_keys() == 0
        assert lim.next_delay("k") == 0.01  # back at the base band

    def test_per_key_isolation(self):
        lim = ExponentialBackoffLimiter(base_delay=0.01, max_delay=1.0,
                                        jitter=0.0)
        for _ in range(6):
            lim.next_delay("hot")
        assert lim.next_delay("cold") == 0.01

    def test_deterministic_given_seed(self):
        mk = lambda: ExponentialBackoffLimiter(seed=42)  # noqa: E731
        a, b = mk(), mk()
        assert [a.next_delay("k") for _ in range(12)] == \
               [b.next_delay("k") for _ in range(12)]

    def test_jitter_validation(self):
        with pytest.raises(ValueError, match="jitter"):
            ExponentialBackoffLimiter(jitter=0.8)


# --------------------------------------------------------------------------
# Workqueue backoff semantics in the manager
# --------------------------------------------------------------------------

class _Scripted(Controller):
    """Raises the scripted exceptions in order, then reconciles clean."""

    NAME = "scripted"
    WATCH_KINDS = ("TpuJob",)

    def __init__(self, api, registry, script):
        super().__init__(api, registry)
        self.script = list(script)
        self.clean_reconciles = 0

    def reconcile(self, namespace, name):
        if self.script:
            raise self.script.pop(0)
        self.clean_reconciles += 1
        return Result()


class _RecordingLimiter(ExponentialBackoffLimiter):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.delays = []

    def next_delay(self, key):
        d = super().next_delay(key)
        self.delays.append(d)
        return d


def _scripted_world(script, *, limiter=None):
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    limiter = limiter or _RecordingLimiter(
        base_delay=0.001, max_delay=0.1, jitter=0.0)
    mgr = ControllerManager(api, reg, limiter=limiter)
    ctl = _Scripted(api, reg, script)
    mgr.register(ctl)
    return api, mgr, ctl, limiter


class TestWorkqueueBackoff:
    def test_error_backoff_grows_then_resets(self):
        api, mgr, ctl, lim = _scripted_world(
            [RuntimeError("boom")] * 3)
        api.create(_job())
        mgr.run_until_idle(include_timers_within=5.0)
        assert ctl.clean_reconciles >= 1
        assert ctl.metrics_retries.value(reason="error") == 3
        # Exponential growth, then failure count forgotten on success.
        assert lim.delays == [0.001, 0.002, 0.004]
        assert lim.failures(("scripted", ("chaos", "train"))) == 0

    def test_not_found_is_retried_not_dropped(self):
        """A NotFound raised mid-reconcile (dependent race / injected
        fault) must requeue with backoff — the old kernel dropped the key
        as 'gone' and the object was never reconciled again."""
        api, mgr, ctl, _ = _scripted_world(
            [NotFoundError("injected"), NotFoundError("injected")])
        api.create(_job())
        mgr.run_until_idle(include_timers_within=5.0)
        assert ctl.clean_reconciles >= 1
        assert ctl.metrics_retries.value(reason="not_found") == 2

    def test_conflict_storm_backs_off_instead_of_spinning(self):
        """Transient conflicts requeue immediately (informer dance); a key
        that KEEPS losing the write race is parked on a backoff timer
        instead of spinning the queue hot."""
        api, mgr, ctl, _ = _scripted_world([ConflictError("stale")] * 50)
        api.create(_job())
        grace = ControllerManager.CONFLICT_IMMEDIATE_RETRIES
        # Without the backoff fallback this would burn all 50 conflicts as
        # immediate requeues; with it the key parks after the grace burst.
        done = mgr.run_until_idle(max_iterations=30)
        assert done == grace + 1
        assert ctl.metrics_retries.value(reason="conflict") == grace + 1
        # The parked key resumes from the timer and eventually succeeds.
        mgr.run_until_idle(include_timers_within=60.0)
        assert ctl.clean_reconciles >= 1

    def test_queue_gauges_exported(self):
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api, reg)
        rendered = reg.render()
        assert "kftpu_workqueue_depth" in rendered
        assert "kftpu_workqueue_backoff_pending" in rendered
        assert "kftpu_workqueue_failing_keys" in rendered
        assert mgr.is_idle()

    def test_retry_metrics_per_controller(self):
        api, mgr, ctl, _ = _scripted_world([RuntimeError("x")])
        api.create(_job())
        mgr.run_until_idle(include_timers_within=5.0)
        reg_lines = ctl.metrics_retries.render()
        assert any("kftpu_scripted_retries_total" in l for l in reg_lines)


# --------------------------------------------------------------------------
# Chaos API server
# --------------------------------------------------------------------------

def _driven_ops(chaos):
    """A fixed op sequence hammered against a chaos server; returns the
    outcome tally. Ops that fault are swallowed — the tally IS the fault
    record."""
    outcomes = []
    for i in range(60):
        try:
            chaos.create(_job(name=f"j{i:02d}"))
            outcomes.append("create-ok")
        except Exception as e:  # noqa: BLE001
            outcomes.append(type(e).__name__)
    for i in range(60):
        try:
            j = chaos.inner.get("TpuJob", f"j{i:02d}", "chaos")
            j.spec.max_restarts = i
            chaos.update(j)
            outcomes.append("update-ok")
        except Exception as e:  # noqa: BLE001
            outcomes.append(type(e).__name__)
    return outcomes


class TestChaosApiServer:
    RULES = {
        "update:*": FaultSpec(conflict_rate=0.3, transient_rate=0.1),
        "create:*": FaultSpec(transient_rate=0.2),
    }

    def test_seeded_faults_are_reproducible(self):
        runs = []
        for _ in range(2):
            chaos = ChaosApiServer(InMemoryApiServer(), seed=11,
                                   rules=dict(self.RULES),
                                   registry=MetricsRegistry())
            runs.append((_driven_ops(chaos), dict(chaos.injected)))
        assert runs[0] == runs[1]
        assert runs[0][1]  # something was actually injected

    def test_different_seeds_differ(self):
        tallies = []
        for seed in (1, 2):
            chaos = ChaosApiServer(InMemoryApiServer(), seed=seed,
                                   rules=dict(self.RULES),
                                   registry=MetricsRegistry())
            tallies.append(_driven_ops(chaos))
        assert tallies[0] != tallies[1]

    def test_verb_banding(self):
        """Conflicts only hit updates; not-founds only reads/deletes;
        try_get (the informer-cache read) is never injected."""
        chaos = ChaosApiServer(
            InMemoryApiServer(), seed=0,
            rules={"*:*": FaultSpec(conflict_rate=0.5, not_found_rate=0.5)},
            registry=MetricsRegistry(),
        )
        job = chaos.inner.create(_job())
        for _ in range(20):
            assert chaos.try_get("TpuJob", "train", "chaos") is not None
        with pytest.raises(NotFoundError, match="chaos"):
            for _ in range(50):
                chaos.get("TpuJob", "train", "chaos")
        with pytest.raises(ConflictError, match="chaos"):
            for _ in range(50):
                job = chaos.inner.get("TpuJob", "train", "chaos")
                chaos.update(job)
        assert all(not k.startswith("create") for k in chaos.injected)

    def test_quiesce_and_resume(self):
        chaos = ChaosApiServer(
            InMemoryApiServer(), seed=0,
            rules={"create:*": FaultSpec(transient_rate=1.0)},
            registry=MetricsRegistry(),
        )
        chaos.quiesce()
        chaos.create(_job())          # no fault while quiesced
        chaos.resume()
        with pytest.raises(TransientApiError):
            chaos.create(_job(name="other"))

    def test_rule_specificity(self):
        chaos = ChaosApiServer(
            InMemoryApiServer(), seed=0,
            rules={
                "*:*": FaultSpec(transient_rate=1.0),
                "create:TpuJob": FaultSpec(),   # exact rule wins: no faults
            },
            registry=MetricsRegistry(),
        )
        chaos.create(_job())  # does not raise

    def test_rates_validation(self):
        with pytest.raises(ValueError, match="sum"):
            FaultSpec(conflict_rate=0.7, transient_rate=0.7)


# --------------------------------------------------------------------------
# Slice preemption + restart policy (no API chaos: deterministic)
# --------------------------------------------------------------------------

def _gang_world(*, capacity=None, outcome=None):
    api = InMemoryApiServer()
    reg = MetricsRegistry()
    mgr = ControllerManager(api, reg)
    ctl = TpuJobController(api, reg, capacity=capacity, hbm_check=False)
    mgr.register(ctl)
    kubelet = FakeKubelet(api, reg, outcome=outcome)
    mgr.register(kubelet)
    return api, reg, mgr, ctl, kubelet


class TestSlicePreemption:
    def test_preemption_restarts_without_consuming_budget(self):
        api, reg, mgr, ctl, _ = _gang_world()
        api.create(_job(max_restarts=2))
        mgr.run_until_idle()
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Running"

        pre = SlicePreemptor(api, seed=3, registry=reg)
        assert pre.preempt(job) > 0
        mgr.run_until_idle(include_timers_within=60.0)

        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Running"       # rescheduled
        assert job.status.preemptions == 1
        assert job.status.restarts == 0            # budget untouched
        # The new gang carries a bumped restart generation.
        pods = api.list("Pod", namespace="chaos",
                        label_selector={JOB_LABEL: "train"})
        assert pods and all(
            p.metadata.labels["restart-generation"] == "1" for p in pods
        )
        assert ctl.metrics_restarts.value(reason="preempted") == 1

    def test_preemption_policy_fail(self):
        api, reg, mgr, _, _ = _gang_world()
        api.create(_job(preemption_policy="fail"))
        mgr.run_until_idle()
        pre = SlicePreemptor(api, seed=3, registry=reg)
        pre.preempt(api.get("TpuJob", "train", "chaos"))
        mgr.run_until_idle(include_timers_within=60.0)
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Failed"
        assert job.status.preemptions == 0

    def test_worker_failure_still_consumes_budget(self):
        """A plain worker crash (no preemption marker) keeps the original
        max_restarts accounting."""
        api, reg, mgr, _, kubelet = _gang_world()
        api.create(_job(max_restarts=2))
        mgr.run_until_idle()
        pod = api.list("Pod", namespace="chaos")[0]
        pod.status.phase = "Failed"
        pod.status.message = "exit code 137"
        api.update_status(pod)
        mgr.run_until_idle(include_timers_within=60.0)
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.restarts == 1
        assert job.status.preemptions == 0

    def test_capacity_reclaim_parks_job_until_restore(self):
        capacity = {"v5e-16": 1}
        api, reg, mgr, _, _ = _gang_world(capacity=capacity)
        api.create(_job())
        mgr.run_until_idle()
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Running"

        pre = SlicePreemptor(api, seed=0, capacity=capacity, registry=reg)
        pre.preempt(job)
        assert capacity["v5e-16"] == 0             # slice reclaimed
        mgr.run_until_idle()
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Pending"        # parked: no capacity
        cond = {c.type: c for c in job.status.conditions}["Admitted"]
        assert cond.reason == "InsufficientCapacity"

        assert pre.restore_capacity() == {"v5e-16": 1}
        mgr.run_until_idle(include_timers_within=10.0)
        job = api.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Running"        # rescheduled
        assert job.status.preemptions == 1

    def test_interrupted_teardown_still_restarts_whole_gang(self):
        """A transient API error mid-teardown (after the restart commit)
        must not downgrade the all-or-nothing gang restart: the retried
        reconcile has to tear down the SURVIVING old-generation workers
        too, even though the recreate pass already ran over them."""

        class OneShotDeleteFail:
            """Fails exactly the first delete, then passes through."""

            def __init__(self, inner):
                self.inner = inner
                self.fails_left = 1

            def delete(self, *a, **kw):
                if self.fails_left:
                    self.fails_left -= 1
                    raise TransientApiError("injected: teardown interrupted")
                return self.inner.delete(*a, **kw)

            def __getattr__(self, name):
                return getattr(self.inner, name)

        inner = InMemoryApiServer()
        flaky = OneShotDeleteFail(inner)
        reg = MetricsRegistry()
        mgr = ControllerManager(flaky, reg)
        mgr.register(TpuJobController(flaky, reg, hbm_check=False))
        mgr.register(FakeKubelet(inner, reg))
        inner.create(_job(max_restarts=2))
        mgr.run_until_idle()
        assert inner.get("TpuJob", "train", "chaos").status.phase == "Running"

        pod = inner.list("Pod", namespace="chaos")[0]
        pod.status.phase = "Failed"
        pod.status.message = "exit code 137"
        inner.update_status(pod)
        mgr.run_until_idle(include_timers_within=60.0)

        assert flaky.fails_left == 0               # the fault actually fired
        job = inner.get("TpuJob", "train", "chaos")
        assert job.status.phase == "Running"
        assert job.status.restarts == 1
        pods = inner.list("Pod", namespace="chaos",
                          label_selector={JOB_LABEL: "train"})
        assert len(pods) == 4
        # EVERY worker is generation 1 — no old-generation survivor kept
        # running past the restart.
        for p in pods:
            assert p.metadata.labels["restart-generation"] == "1", \
                p.metadata.name
            env = {e.name: e.value for e in p.spec.containers[0].env}
            assert env["KFTPU_RESTART_COUNT"] == "1", p.metadata.name

    def test_preempt_random_skips_terminal_jobs(self):
        api, reg, mgr, _, _ = _gang_world()
        pre = SlicePreemptor(api, seed=0, registry=reg)
        assert pre.preempt_random() is None         # empty world
        api.create(_job())
        mgr.run_until_idle()
        assert pre.preempt_random() == "chaos/train"

    def test_preemption_marker_is_the_contract(self):
        api, reg, mgr, _, _ = _gang_world()
        api.create(_job())
        mgr.run_until_idle()
        pre = SlicePreemptor(api, seed=0, registry=reg)
        pre.preempt(api.get("TpuJob", "train", "chaos"), slice_id=0)
        failed = [p for p in api.list("Pod", namespace="chaos")
                  if p.status.phase == "Failed"]
        assert failed
        assert all(p.status.message == PREEMPTION_MESSAGE for p in failed)


# --------------------------------------------------------------------------
# The full seeded soak (the CI chaos-smoke contract)
# --------------------------------------------------------------------------

class TestChaosSoak:
    def test_soak_converges_under_conflicts_and_preemption(self):
        rep = run_soak(num_jobs=4, seed=20260803)
        assert rep.converged, rep.stuck_jobs()
        assert rep.all_succeeded, rep.phases
        assert rep.availability == 1.0
        assert rep.retries_total > 0
        assert any(k.endswith(":conflict") for k in rep.injected)
        assert rep.preemptions >= 1

    def test_soak_other_seed(self):
        rep = run_soak(num_jobs=3, seed=7, conflict_rate=0.3,
                       transient_rate=0.08)
        assert rep.converged, rep.stuck_jobs()
        assert rep.all_succeeded, rep.phases
        assert rep.availability == 1.0

    def test_ci_chaos_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_chaos_smoke

        run_chaos_smoke(seed=20260803)  # raises GateFailure on failure

    def test_soak_reports_latency_percentiles(self):
        """ISSUE 4: the soak's JSON now decomposes latency, not just
        throughput — reconcile + queue-wait percentiles present."""
        rep = run_soak(num_jobs=2, seed=11, fault_rounds=4, max_rounds=40)
        assert rep.converged
        for pcts in (rep.reconcile_latency_s, rep.queue_wait_s):
            assert {"p50", "p95", "p99"} <= set(pcts)
            assert 0 <= pcts["p50"] <= pcts["p95"] <= pcts["p99"]

    def test_soak_parallel_workers_converges(self):
        """ISSUE 5: the soak hunts races in the worker pool — injected
        conflicts/transients + slice preemption against 4 concurrent
        reconciles; per-key serialization and dirty-requeue must still
        drive every job terminal. (Fault SEQUENCE varies with thread
        interleaving, so this asserts convergence, not injection
        tallies.)"""
        rep = run_soak(num_jobs=4, seed=20260803, workers=4)
        assert rep.workers == 4
        assert rep.converged, rep.stuck_jobs()
        assert rep.all_succeeded, rep.phases
        assert rep.availability == 1.0

    def test_ci_chaos_parallel_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_chaos_smoke

        run_chaos_smoke(seed=20260803, workers=4)


# --------------------------------------------------------------------------
# Watch-lag injection (ISSUE 4 satellite: the ROADMAP follow-up)
# --------------------------------------------------------------------------

class TestWatchLagInjection:
    LAG = 0.05

    def test_events_held_for_lag_then_delivered_in_order(self):
        import time

        api = InMemoryApiServer(registry=MetricsRegistry())
        chaos = ChaosApiServer(api, seed=1, registry=MetricsRegistry(),
                               watch_lag_s=self.LAG)
        q = chaos.watch("TpuJob")
        api.create(_job("a"))
        api.create(_job("b"))
        # Freshly written events are invisible until the lag elapses ...
        assert q.empty()
        time.sleep(self.LAG + 0.01)
        # ... then release in write order.
        assert not q.empty()
        assert q.get().object.metadata.name == "a"
        assert q.get().object.metadata.name == "b"
        chaos.stop_watch(q)

    def test_quiesce_releases_held_events_immediately(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        chaos = ChaosApiServer(api, seed=1, registry=MetricsRegistry(),
                               watch_lag_s=60.0)   # absurd lag
        q = chaos.watch("TpuJob")
        api.create(_job("held"))
        assert q.empty()
        chaos.quiesce()                             # lag goes with faults
        assert not q.empty()
        assert q.get().object.metadata.name == "held"
        chaos.stop_watch(q)

    def test_histogram_provably_measures_injected_lag(self):
        """The acceptance criterion: with seeded watch-lag chaos, every
        lag observation the manager records is >= the injected lag — the
        buckets below it stay EMPTY (deterministic in outcome: real time
        only ever adds lag on top)."""
        import time

        reg = MetricsRegistry()
        api = InMemoryApiServer(registry=reg)
        chaos = ChaosApiServer(api, seed=20260803, registry=reg,
                               watch_lag_s=self.LAG)
        mgr = ControllerManager(chaos, reg)
        ctl = TpuJobController(chaos, reg, hbm_check=False)
        mgr.register(ctl)
        kubelet = FakeKubelet(chaos, reg, outcome=lambda name: "Succeeded")
        mgr.register(kubelet)
        api.create(_job("lagged"))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            mgr.run_until_idle(include_timers_within=30.0)
            kubelet.tick()
            mgr.run_until_idle(include_timers_within=30.0)
            job = api.get("TpuJob", "lagged", "chaos")
            if job.status.phase in ("Succeeded", "Failed"):
                break
            time.sleep(self.LAG / 2)
        assert job.status.phase == "Succeeded", job.status.phase
        hist = reg.get("kftpu_watch_delivery_lag_seconds")
        total = sum(hist.count(controller=c.NAME)
                    for c in (ctl, kubelet))
        assert total > 0
        # No observation below the injected lag: the sub-lag buckets of
        # every controller series are empty.
        for c in (ctl, kubelet):
            n = hist.count(controller=c.NAME)
            if n == 0:
                continue
            below = [
                (le, cum) for le, cum in zip(
                    hist.buckets,
                    _cumulative(hist, controller=c.NAME))
                if le < self.LAG
            ]
            assert all(cum == 0 for _, cum in below), below
        mgr.close()

    def test_timed_get_honours_timeout_not_lag(self):
        """queue.Queue contract: get(timeout=t) must raise Empty after ~t,
        not serve out a 60s injected lag sentence."""
        import queue as queue_mod
        import time

        import pytest as _pytest

        api = InMemoryApiServer(registry=MetricsRegistry())
        chaos = ChaosApiServer(api, seed=1, registry=MetricsRegistry(),
                               watch_lag_s=60.0)
        q = chaos.watch("TpuJob")
        api.create(_job("slow"))
        t0 = time.monotonic()
        with _pytest.raises(queue_mod.Empty):
            q.get(timeout=0.05)
        assert time.monotonic() - t0 < 5.0
        chaos.stop_watch(q)

    def test_unlagged_chaos_watch_passes_through(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        chaos = ChaosApiServer(api, seed=1, registry=MetricsRegistry())
        q = chaos.watch("TpuJob")
        api.create(_job("now"))
        assert not q.empty()                        # no lag configured
        chaos.stop_watch(q)

    def test_soak_with_watch_lag_converges(self):
        rep = run_soak(num_jobs=2, seed=9, fault_rounds=3, max_rounds=40,
                       watch_lag_s=0.01)
        assert rep.converged, rep.stuck_jobs()
        assert rep.all_succeeded, rep.phases
        assert rep.watch_lag_s.get("p99", 0) >= 0.0


def _cumulative(hist, **labels):
    """Cumulative per-bucket counts for one labelset of a Histogram."""
    samples = hist.samples()
    want = tuple(sorted(labels.items()))
    out = []
    for le in hist.buckets:
        from kubeflow_tpu.utils.monitoring import _fmt_value

        key = want + (("le", _fmt_value(le)),)
        got = [v for name, lab, v in samples
               if name.endswith("_bucket") and lab == key]
        out.append(got[0] if got else 0)
    return out


class TestServingSoak:
    """Serving data-plane soak (ISSUE 7): the Serving/Notebook drain-path
    chaos follow-up open since PR 2. Backends flap, drain, and saturate
    mid-traffic; the invariants are routing exclusion, honest shedding,
    and exact request accounting."""

    def test_soak_is_clean_and_exercises_faults(self):
        from kubeflow_tpu.chaos import run_serving_soak

        rep = run_serving_soak(backends=3, rounds=10, requests_per_round=4,
                               seed=20260803)
        assert rep.clean, rep
        assert rep.rounds == 10
        assert rep.sent == 40
        # the seed must actually exercise the fault surface
        assert rep.flaps + rep.drains + rep.saturations > 0

    def test_saturated_fleet_sheds_with_retry_after(self):
        """A seed-independent direct check: force saturation rounds and
        assert every shed carried a backoff hint."""
        from kubeflow_tpu.chaos import run_serving_soak

        rep = run_serving_soak(backends=2, rounds=6, requests_per_round=3,
                               seed=7)
        assert rep.clean, rep
        assert rep.accounting_ok

    def test_gray_failure_paged_drained_and_cleared(self):
        """ISSUE 17: a *sick* backend passes health checks while its
        queue wait is pathological — the flap/kill model can't see it.
        The backend-queue-wait objective pages, the drain playbook
        removes it, and the page CLEARS, all with routing invariants
        intact."""
        from kubeflow_tpu.chaos import run_serving_soak

        rep = run_serving_soak(backends=3, rounds=12, seed=20260803,
                               sick=True, remediate=True)
        assert rep.clean, rep
        assert rep.sicks >= 1                  # fault actually injected
        assert rep.slo["pages"].get("backend-queue-wait", 0) >= 1
        assert rep.remediation["actions"] >= 1
        assert rep.slo["paging"] == []         # cleared, no operator
        assert rep.remediation["pending"] == 0

    def test_armed_clean_serving_soak_takes_no_actions(self):
        """Do-no-harm: the same soak with the controller armed but no
        sick injection must page nothing and act never."""
        from kubeflow_tpu.chaos import run_serving_soak

        rep = run_serving_soak(backends=3, rounds=12, seed=20260803,
                               remediate=True)
        assert rep.clean, rep
        assert rep.slo["transitions"] == 0
        assert rep.remediation["actions"] == 0
