"""Dashboard time-series metrics plane (webapps/metrics.py).

Mirrors the reference centraldashboard MetricsService surface
(app/metrics_service.ts:21-42 + stackdriver impl) with platform-local
sampling instead of a cloud monitoring API.
"""

import json
import urllib.request

from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.webapps.metrics import (
    MetricsCollector,
    MetricsService,
    Point,
    TimeSeriesStore,
    host_cpu_sampler,
)
from kubeflow_tpu.webapps.router import JsonHttpServer


class TestTimeSeriesStore:
    def test_record_query_window(self):
        st = TimeSeriesStore()
        st.record("a", 1.0, t=100.0)
        st.record("a", 2.0, t=200.0)
        st.record("a", 3.0, t=300.0)
        pts = st.query("a", window_s=150.0, now=310.0)
        assert [p.value for p in pts] == [2.0, 3.0]
        assert st.query("missing", now=310.0) == []
        assert st.names() == ["a"]

    def test_label_sets_are_distinct_streams(self):
        # Per-device points must not interleave into one sawtooth line:
        # each label set keeps its own deque and its own query group.
        st = TimeSeriesStore()
        for i in range(3):
            st.record("hbm", 10.0 + i, t=100.0 + i, labels=(("device", "0"),))
            st.record("hbm", 20.0 + i, t=100.0 + i, labels=(("device", "1"),))
        groups = st.query_groups("hbm", window_s=600.0, now=110.0)
        assert [dict(labels) for labels, _ in groups] == [
            {"device": "0"}, {"device": "1"},
        ]
        assert [p.value for p in groups[0][1]] == [10.0, 11.0, 12.0]
        assert [p.value for p in groups[1][1]] == [20.0, 21.0, 22.0]
        # merged view stays time-ordered and complete
        merged = st.query("hbm", window_s=600.0, now=110.0)
        assert [p.t for p in merged] == sorted(p.t for p in merged)
        assert len(merged) == 6
        assert st.names() == ["hbm"]

    def test_max_points_bound(self):
        st = TimeSeriesStore(max_points=3)
        for i in range(10):
            st.record("a", float(i), t=float(i))
        pts = st.query("a", window_s=100.0, now=9.0)
        assert [p.value for p in pts] == [7.0, 8.0, 9.0]


class TestCollector:
    def _collector(self, registry=None):
        st = TimeSeriesStore()
        hbm = [("0", 8e9, 16e9)]
        col = MetricsCollector(
            st, registry,
            cpu_sample=lambda: 0.25,
            hbm_sample=lambda: hbm,
        )
        return st, col

    def test_tick_samples_cpu_and_hbm(self):
        st, col = self._collector()
        col.tick(now=50.0)
        assert st.query("node_cpu_utilization", now=50.0)[0].value == 0.25
        hbm = st.query("tpu_hbm_utilization", now=50.0)[0]
        assert hbm.value == 0.5
        assert dict(hbm.labels) == {"device": "0"}
        assert st.query("tpu_hbm_bytes_in_use", now=50.0)[0].value == 8e9

    def test_tick_copies_registry_metrics(self):
        reg = MetricsRegistry()
        g = reg.gauge("kftpu_availability", "up")
        g.set(1.0)
        c = reg.counter("kftpu_reconciles_total", "n", ("kind",))
        c.inc(kind="Notebook")
        c.inc(kind="Notebook")
        st, col = self._collector(reg)
        col.tick(now=60.0)
        assert st.query("kftpu_availability", now=60.0)[0].value == 1.0
        pt = st.query("kftpu_reconciles_total", now=60.0)[0]
        assert pt.value == 2.0
        assert dict(pt.labels) == {"kind": "Notebook"}

    def test_tick_samples_heartbeats_for_staleness_detection(self):
        """ISSUE 4 satellite regression: snapshot() used to skip Heartbeat
        metrics, so the time-series collector could never show a wedged
        controller's heartbeat going stale. Now each tick records it."""
        reg = MetricsRegistry()
        hb = reg.heartbeat("tpujob")
        hb.beat()
        st, col = self._collector(reg)
        col.tick(now=60.0)
        pts = st.query("kftpu_tpujob_heartbeat", now=60.0)
        assert pts and pts[0].value == hb.last() > 0

    def test_tick_samples_histogram_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("kftpu_lat_seconds", "t", buckets=(0.1, 1.0))
        h.observe(0.05)
        st, col = self._collector(reg)
        col.tick(now=60.0)
        assert st.query("kftpu_lat_seconds_count", now=60.0)[0].value == 1.0
        buckets = st.query_groups("kftpu_lat_seconds_bucket", now=60.0)
        assert {dict(labels)["le"] for labels, _ in buckets} == \
            {"0.1", "1", "+Inf"}

    def test_host_cpu_sampler_contract(self):
        sample = host_cpu_sampler()
        first = sample()
        assert first is None  # no delta on the first reading
        second = sample()
        if second is not None:  # non-Linux hosts may keep returning None
            assert 0.0 <= second <= 1.0


class TestMetricsHttp:
    def test_query_over_http(self):
        st = TimeSeriesStore()
        st.record("node_cpu_utilization", 0.5)
        svc = MetricsService(st)
        srv = JsonHttpServer(svc.router(), port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/api/metrics") as r:
                assert json.load(r)["series"] == ["node_cpu_utilization"]
            with urllib.request.urlopen(
                f"{base}/api/metrics/node_cpu_utilization?window=60"
            ) as r:
                body = json.load(r)
            assert body["series"] == "node_cpu_utilization"
            assert len(body["points"]) == 1
            assert body["points"][0]["value"] == 0.5
            # bad window -> 400
            try:
                urllib.request.urlopen(
                    f"{base}/api/metrics/node_cpu_utilization?window=x"
                )
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()

    def test_mounted_in_hub(self):
        from kubeflow_tpu.controlplane.kfam import AccessManagement
        from kubeflow_tpu.controlplane.runtime.apiserver import (
            InMemoryApiServer,
        )
        from kubeflow_tpu.webapps.dashboard import DashboardApi
        from kubeflow_tpu.webapps.frontend import central_hub
        from kubeflow_tpu.webapps.jwa import NotebookWebApp
        from kubeflow_tpu.webapps.router import Request

        api = InMemoryApiServer()
        reg = MetricsRegistry()
        am = AccessManagement(api, reg)
        st = TimeSeriesStore()
        st.record("node_cpu_utilization", 0.1)
        hub = central_hub(
            api, DashboardApi(am), NotebookWebApp(api, reg),
            MetricsService(st),
        )
        status, body = hub.dispatch(Request(
            method="GET", path="/api/metrics", params={}, query={},
            body={}, caller="", headers={},
        ))
        assert status == 200
        assert body["series"] == ["node_cpu_utilization"]
