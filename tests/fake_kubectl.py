#!/usr/bin/env python3
"""A kubectl test double for KubectlApiServer integration tests.

Speaks the exact slice of the kubectl CLI the adapter uses (create/get/
replace/delete with -o json, -n/-l/--all-namespaces, --subresource status)
against a JSON-file store in $FAKE_KUBECTL_DIR — the process-boundary
analogue of the reference's envtest: real exec + serialization semantics,
no cluster. Implements apiserver behaviours the adapter's error mapping
relies on: AlreadyExists/NotFound/Conflict(resourceVersion), and
ownerReference cascade on delete.

Schema grounding: every incoming create/replace manifest is validated
against the vendored Kubernetes structural schemas
(kubeflow_tpu/controlplane/runtime/k8s_schema.py) — NOT this file's own
parser — so a field-name or type error a real apiserver would reject
fails here too, apiserver-style ("error validating data"). This is the
fake half of the contract whose emit half lives in runtime/kubectl.py.
"""

import datetime
import importlib.util
import json
import os
import sys
import uuid
from pathlib import Path

# Load the schema module by file path: going through the kubeflow_tpu
# package __init__ would import jax (~2s per kubectl invocation — the
# adapter shells out hundreds of times per test run).
_schema_path = (Path(__file__).resolve().parent.parent / "kubeflow_tpu"
                / "controlplane" / "runtime" / "k8s_schema.py")
_spec = importlib.util.spec_from_file_location("_k8s_schema", _schema_path)
_k8s_schema = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_k8s_schema)
validate = _k8s_schema.validate

STORE = Path(os.environ.get("FAKE_KUBECTL_DIR", "/tmp/fake-kubectl"))
CLUSTER_SCOPED = {"Namespace", "Profile", "PlatformConfig"}


def fail(msg, code=1):
    print(msg, file=sys.stderr)
    sys.exit(code)


def kind_from_resource(res):
    base = res.split(".")[0].rstrip()
    # tpujobs -> TpuJob etc: match against the store's known kinds plus a
    # static map for core/foreign kinds.
    known = {
        "pods": "Pod", "services": "Service", "namespaces": "Namespace",
        "serviceaccounts": "ServiceAccount", "resourcequotas": "ResourceQuota",
        "events": "Event", "rolebindings": "RoleBinding",
        "virtualservices": "VirtualService",
        "authorizationpolicies": "AuthorizationPolicy",
        "tpujobs": "TpuJob", "notebooks": "Notebook", "profiles": "Profile",
        "poddefaults": "PodDefault", "tensorboards": "Tensorboard",
        "servings": "Serving", "studyjobs": "StudyJob",
        "platformconfigs": "PlatformConfig",
    }
    if base not in known:
        fail(f"error: the server doesn't have a resource type {base!r}")
    return known[base]


def path_for(kind, ns, name):
    ns = "" if kind in CLUSTER_SCOPED else ns
    return STORE / kind / (f"{ns}__{name}.json")


def load_all(kind):
    d = STORE / kind
    if not d.is_dir():
        return []
    return [json.loads(p.read_text()) for p in sorted(d.glob("*.json"))]


def next_rv():
    p = STORE / "_rv"
    rv = int(p.read_text()) if p.exists() else 0
    rv += 1
    p.write_text(str(rv))
    return rv


def save(obj):
    kind = obj["kind"]
    meta = obj["metadata"]
    p = path_for(kind, meta.get("namespace", ""), meta["name"])
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(obj))


def parse_flags(argv):
    flags = {"ns": "", "all_ns": False, "selector": "", "output": "",
             "subresource": "", "positional": []}
    it = iter(argv)
    for a in it:
        if a in ("-n", "--namespace"):
            flags["ns"] = next(it)
        elif a == "--all-namespaces":
            flags["all_ns"] = True
        elif a == "-l":
            flags["selector"] = next(it)
        elif a == "-o":
            flags["output"] = next(it)
        elif a == "--subresource":
            flags["subresource"] = next(it)
        elif a == "-f":
            next(it)  # always "-" (stdin)
        elif a.startswith("--wait"):
            pass
        elif a == "--context":
            next(it)
        else:
            flags["positional"].append(a)
    return flags


def check_schema(obj):
    errors = validate(obj)
    if errors:
        fail("error: error validating data: " + "; ".join(errors[:5]))


def cmd_create(flags):
    obj = json.load(sys.stdin)
    check_schema(obj)
    kind, meta = obj["kind"], obj["metadata"]
    p = path_for(kind, meta.get("namespace", ""), meta["name"])
    if p.exists():
        fail(f'Error from server (AlreadyExists): {kind.lower()}s '
             f'"{meta["name"]}" already exists')
    meta["uid"] = str(uuid.uuid4())
    meta["resourceVersion"] = str(next_rv())
    meta["generation"] = 1
    meta["creationTimestamp"] = datetime.datetime.now(
        datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    save(obj)
    print(json.dumps(obj))


def cmd_get(flags):
    pos = flags["positional"]
    kind = kind_from_resource(pos[0])
    if len(pos) > 1:                        # single object
        p = path_for(kind, flags["ns"], pos[1])
        if not p.exists():
            fail(f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found')
        print(p.read_text())
        return
    items = load_all(kind)
    if not flags["all_ns"] and flags["ns"] and kind not in CLUSTER_SCOPED:
        items = [o for o in items
                 if o["metadata"].get("namespace") == flags["ns"]]
    if flags["selector"]:
        want = dict(kv.split("=", 1) for kv in flags["selector"].split(","))
        items = [o for o in items
                 if all(o["metadata"].get("labels", {}).get(k) == v
                        for k, v in want.items())]
    print(json.dumps({"kind": f"{kind}List", "items": items}))


def cmd_replace(flags):
    obj = json.load(sys.stdin)
    check_schema(obj)
    kind, meta = obj["kind"], obj["metadata"]
    p = path_for(kind, meta.get("namespace", ""), meta["name"])
    if not p.exists():
        fail(f'Error from server (NotFound): {kind.lower()}s '
             f'"{meta["name"]}" not found')
    cur = json.loads(p.read_text())
    if str(meta.get("resourceVersion", "")) != str(
            cur["metadata"]["resourceVersion"]):
        fail(f'Error from server (Conflict): Operation cannot be fulfilled: '
             f'the object has been modified')
    if flags["subresource"] == "status":
        cur["status"] = obj.get("status", {})
        cur["metadata"]["resourceVersion"] = str(next_rv())
        save(cur)
        print(json.dumps(cur))
        return
    # Server-owned identity survives replace.
    meta["uid"] = cur["metadata"]["uid"]
    meta["creationTimestamp"] = cur["metadata"].get("creationTimestamp")
    meta["resourceVersion"] = str(next_rv())
    gen = cur["metadata"].get("generation", 1)
    meta["generation"] = gen + (1 if obj.get("spec") != cur.get("spec") else 0)
    save(obj)
    print(json.dumps(obj))


def cmd_delete(flags):
    pos = flags["positional"]
    kind = kind_from_resource(pos[0])
    p = path_for(kind, flags["ns"], pos[1])
    if not p.exists():
        fail(f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found')
    obj = json.loads(p.read_text())
    p.unlink()
    # ownerReference cascade (real clusters: garbage collector controller).
    uid = obj["metadata"]["uid"]
    for d in STORE.iterdir():
        if not d.is_dir():
            continue
        for f in list(d.glob("*.json")):
            dep = json.loads(f.read_text())
            refs = dep["metadata"].get("ownerReferences", [])
            if any(r.get("uid") == uid for r in refs):
                f.unlink()
    print(f'{pos[0]} "{pos[1]}" deleted')


def cmd_logs(flags):
    # `kubectl logs <pod> -n ns`: emit canned logs for stored pods, the
    # real CLI's NotFound wording otherwise.
    name = flags["positional"][0]
    import os
    path = path_for("Pod", flags["ns"] or "default", name)
    if not os.path.exists(path):
        fail(f'Error from server (NotFound): pods "{name}" not found')
    print(f"log line from {name}")


def main():
    STORE.mkdir(parents=True, exist_ok=True)
    argv = sys.argv[1:]
    if not argv:
        fail("usage: fake_kubectl <verb> ...")
    verb, rest = argv[0], parse_flags(argv[1:])
    {
        "create": cmd_create,
        "get": cmd_get,
        "replace": cmd_replace,
        "delete": cmd_delete,
        "logs": cmd_logs,
    }.get(verb, lambda f: fail(f"unknown verb {verb}"))(rest)


if __name__ == "__main__":
    main()
