"""minijs interpreter unit tier: every dialect feature the page scripts
use (webapps/frontend.py, controlplane/bootstrap.py) has a direct test
here, so a page-script change that outgrows the interpreter fails loudly
in THIS file before the UI-execution tests go red."""

import pytest

from kubeflow_tpu.webapps.minijs import (
    Interpreter,
    JSError,
    js_to_string,
    undefined,
)


def run(src, **globals_):
    it = Interpreter(globals_)
    it.run(src)
    return it


def ev(src, **globals_):
    it = Interpreter(globals_)
    it.run(f"__result = ({src});")
    return it.globals["__result"]


class TestExpressions:
    def test_arithmetic_and_precedence(self):
        assert ev("1 + 2 * 3") == 7
        assert ev("(24 - 22 * (5 - 1) / 4)") == 2
        assert ev("7 % 3") == 1

    def test_string_concat_coerces(self):
        assert ev("'a' + 1") == "a1"
        assert ev("1 + '2'") == "12"
        assert ev("'x=' + undefined") == "x=undefined"
        assert ev("'' + [1, 2]") == "1,2"

    def test_number_to_string_drops_integral_point(self):
        assert ev("'' + 24") == "24"
        assert ev("'' + 24.5") == "24.5"
        assert ev("(120 / 2) + ''") == "60"

    def test_strict_equality(self):
        assert ev("1 === 1") is True
        assert ev("'1' === 1") is False
        assert ev("null === undefined") is False
        assert ev("!1") is False

    def test_ternary_or_and(self):
        assert ev("0 || 'fallback'") == "fallback"
        assert ev("'x' && 'y'") == "y"
        assert ev("1 ? 'a' : 'b'") == "a"
        assert ev("(5 - 5) || 1") == 1

    def test_template_literals_nested(self):
        assert ev("`a${1 + 1}b`") == "a2b"
        assert ev("`outer ${`inner ${1}`} end`") == "outer inner 1 end"
        assert ev("`${[1,2].map(x => `<${x}>`).join('')}`") == "<1><2>"

    def test_template_with_object_braces_in_substitution(self):
        assert ev("`${({a: 1})['a']}`") == "1"

    def test_object_literals(self):
        assert ev("({a: 1, 'b': 2}).b") == 2
        assert ev("({x: 5}).missing") is undefined
        it = run("const k = 'dyn'; __o = {[k]: 1, short: 2};")
        assert it.globals["__o"] == {"dyn": 1, "short": 2}

    def test_object_shorthand(self):
        assert ev("(() => { const components = [1]; "
                  "return {components}; })()") == {"components": [1]}

    def test_array_literals_and_spread(self):
        assert ev("[1, ...[2, 3], 4]") == [1, 2, 3, 4]
        assert ev("Math.min(...[3, 1, 2])") == 1
        assert ev("Math.max(1, ...[0.5])") == 1

    def test_index_and_member(self):
        assert ev("[10, 20][1]") == 20
        assert ev("[[1], [2]][1][0]") == 2
        assert ev("({a: {b: 3}}).a.b") == 3
        assert ev("'abc'.length") == 3
        assert ev("[1,2,3].length") == 3

    def test_out_of_range_index_is_undefined(self):
        assert ev("[1][5]") is undefined


class TestFunctions:
    def test_arrow_forms(self):
        assert ev("(x => x * 2)(21)") == 42
        assert ev("((a, b) => a + b)(1, 2)") == 3
        assert ev("(() => 7)()") == 7
        assert ev("((x) => { return x + 1; })(1)") == 2

    def test_destructured_params(self):
        assert ev("([a, b]) => a + ':' + b")(["k", "v"]) == "k:v"
        assert ev("[[1, 'a'], [2, 'b']].map(([n, s]) => s + n).join()") \
            == "a1,b2"

    def test_function_decl_and_hoisting(self):
        it = run("""
            __out = helper(2);
            function helper(x) { return x * 10; }
        """)
        assert it.globals["__out"] == 20

    def test_async_collapses_to_sync(self):
        it = run("""
            async function f(x) { return x + 1; }
            __out = await f(1);
            __all = await Promise.all([f(1), f(2)]);
        """)
        assert it.globals["__out"] == 2
        assert it.globals["__all"] == [2, 3]

    def test_closures(self):
        assert ev("(() => { let n = 0; "
                  "const inc = () => { n = n + 1; return n; }; "
                  "inc(); return inc(); })()") == 2

    def test_js_function_callable_from_python(self):
        it = run("function add(a, b) { return a + b; }")
        assert it.globals["add"](2, 3) == 5


class TestStatements:
    def test_const_let_multi_declarator(self):
        it = run("const lo = 1, hi = 5; let x = lo + hi;")
        assert it.globals["x"] == 6

    def test_array_destructuring_decl(self):
        it = run("const [a, b] = [1, 2];")
        assert it.globals["a"] == 1 and it.globals["b"] == 2

    def test_if_else_for_of(self):
        it = run("""
            let total = 0;
            for (const v of [1, 2, 3]) {
                if (v === 2) { total = total + 10; }
                else total = total + v;
            }
        """)
        assert it.globals["total"] == 14

    def test_try_catch_throw(self):
        it = run("""
            let msg = '';
            try { throw new Error('boom'); }
            catch (e) { msg = e.message; }
        """)
        assert it.globals["msg"] == "boom"

    def test_uncaught_throw_raises_jserror(self):
        with pytest.raises(JSError, match="boom"):
            run("throw new Error('boom');")

    def test_try_finally_propagates_and_runs_cleanup(self):
        with pytest.raises(JSError, match="boom"):
            run("""
                let cleaned = false;
                try { throw new Error('boom'); }
                finally { cleaned = true; }
            """)
        it = Interpreter()
        try:
            it.run("try { throw new Error('x'); } "
                   "finally { __cleaned = true; }")
        except JSError:
            pass
        assert it.globals["__cleaned"] is True

    def test_catch_rethrow_and_return_inside(self):
        assert ev("(() => { try { return 'a'; } catch (e) { return 'b'; } "
                  "})()") == "a"

    def test_undefined_variable_throws(self):
        with pytest.raises(JSError, match="not defined"):
            run("nope + 1;")


class TestStdlib:
    def test_esc_replace_with_callback(self):
        # The exact esc() from the served pages.
        it = run("""
            function esc(s) {
              return String(s).replace(/[&<>"']/g, c => ({'&': '&amp;',
                '<': '&lt;', '>': '&gt;', '"': '&quot;',
                "'": '&#39;'})[c]);
            }
            __out = esc('<img src=x onerror="hi">&\\'');
        """)
        assert it.globals["__out"] == \
            "&lt;img src=x onerror=&quot;hi&quot;&gt;&amp;&#39;"

    def test_array_methods(self):
        assert ev("[1, 2, 3].map(x => x * 2)") == [2, 4, 6]
        assert ev("[1, 2, 3].filter(x => x > 1)") == [2, 3]
        assert ev("[1, 2, 3].find(x => x === 2)") == 2
        assert ev("[1, 2].includes(2)") is True
        assert ev("['a', 'b'].join(', ')") == "a, b"
        assert ev("[1, 2, 3, 4].slice(0, 2)") == [1, 2]
        it = run("const a = []; a.push('x'); a.push('y'); __n = a.length;")
        assert it.globals["__n"] == 2

    def test_foreach_assigns_handlers(self):
        # The delegation pattern: forEach(b => b.onclick = async () => ...)
        class Btn:
            onclick = None

        b1, b2 = Btn(), Btn()
        it = Interpreter({"btns": [b1, b2]})
        it.run("btns.forEach(b => b.onclick = async () => 'clicked');")
        assert callable(b1.onclick) and callable(b2.onclick)
        assert b1.onclick() == "clicked"

    def test_object_entries(self):
        assert ev("Object.entries({a: 1}).map(([k, v]) => k + v)") == ["a1"]

    def test_json_stringify(self):
        assert ev("JSON.stringify({name: 'x', n: 2})") == \
            '{"name":"x","n":2}'
        assert ev("JSON.stringify({a: [1, 'b', true]})") == '{"a":[1,"b",true]}'

    def test_math_and_number_formatting(self):
        assert ev("Math.min(3, 1, 2)") == 1
        assert ev("(1.23456).toFixed(1)") == "1.2"
        assert ev("(0.000123456).toPrecision(4)") == "0.0001235"
        assert ev("Number('42') + 1") == 43

    def test_encode_uri_component(self):
        assert ev("encodeURIComponent('a b/c?')") == "a%20b%2Fc%3F"

    def test_array_isarray(self):
        assert ev("Array.isArray([1])") is True
        assert ev("Array.isArray('no')") is False

    def test_string_methods(self):
        assert ev("'a,b'.split(',')") == ["a", "b"]
        assert ev("'hello'.includes('ell')") is True
        assert ev("'  x '.trim()") == "x"

    def test_js_to_string_object(self):
        assert js_to_string({"a": 1}) == "[object Object]"


class TestHostInterop:
    def test_host_object_get_set(self):
        class El:
            def __init__(self):
                self.innerHTML = ""
                self.value = "seed"

        el = El()
        it = Interpreter({"el": el})
        it.run("el.innerHTML = '<p>' + el.value + '</p>';")
        assert el.innerHTML == "<p>seed</p>"

    def test_host_function_receives_js_values(self):
        seen = {}

        def grab(path, opts=undefined):
            seen["path"] = path
            seen["opts"] = opts
            return {"ok": True}

        it = Interpreter({"grab": grab})
        it.run("__r = grab('/api/x', {method: 'POST'}); __ok = __r.ok;")
        assert seen["path"] == "/api/x"
        assert seen["opts"] == {"method": "POST"}
        assert it.globals["__ok"] is True

    def test_missing_host_attr_is_undefined(self):
        class El:
            pass

        assert ev("el.nope", el=El()) is undefined

    def test_member_of_null_throws(self):
        with pytest.raises(JSError, match="cannot read"):
            run("const x = null; x.y;")
