"""Fleet goodput ledger (ISSUE 10): slice-second attribution with a
conservation invariant that holds EXACTLY (integer equality, never
tolerance), chaos-vs-policy preemption attribution parity, journal
replay byte-identity across SIGKILL, and fingerprint unions."""

import json
import types

from kubeflow_tpu.controlplane.api.meta import Condition, ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    MeshAxesSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.obs.goodput import (
    CATEGORIES,
    GoodputAccountant,
    chaos_policy_parity_report,
    goodput_rows_digest,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def _job(name, *, ns="obs", uid=None, phase="Pending", slices=1,
         assignment="", preemptions=0, restarts=0, admitted=None):
    j = TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type="v5e-16", num_slices=slices,
                        mesh=MeshAxesSpec(dp=-1)),
    )
    if uid:
        j.metadata.uid = uid
    j.status.phase = phase
    j.status.slice_assignment = assignment
    j.status.preemptions = preemptions
    j.status.restarts = restarts
    if admitted is not None:
        j.status.conditions = [Condition(
            type="Admitted", status="True" if admitted else "False",
            reason="x", message="")]
    return j


def _ev(type_, obj):
    return types.SimpleNamespace(type=type_, object=obj)


class TestAttribution:
    """The category state machine, driven by hand-fed watch events."""

    def test_idle_vs_queue_wait_split(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 3})
        # No demand: everything idles.
        acc.tick(1)
        # One queued 1-slice gang: exactly one free unit waits on it.
        acc.apply_event(_ev("ADDED", _job("q", uid="u1", phase="Pending",
                                          admitted=False)))
        acc.tick(2)
        snap = acc.snapshot()
        assert snap["categories_ticks"]["idle_free"] == 3 + 2
        assert snap["categories_ticks"]["queue_wait"] == 1
        assert snap["conserved"]
        # Demand-side mirror on the job ledger.
        assert snap["jobs"]["obs/q"]["categories_ticks"] == {
            "queue_wait": 1}

    def test_running_gang_is_productive_and_conserved(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 2})
        acc.apply_event(_ev("ADDED", _job("r", uid="u1", phase="Running",
                                          admitted=True)))
        acc.tick(5)
        snap = acc.snapshot()
        assert snap["categories_ticks"]["productive"] == 5
        assert snap["categories_ticks"]["idle_free"] == 5
        assert snap["tracked_ticks"] == 10
        assert sum(snap["categories_ticks"].values()) == 10
        assert snap["conserved"]
        assert snap["goodput_ratio"] == 0.5

    def test_rollback_reclassifies_unsaved_work(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 1})
        job = _job("r", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(4)                      # 4 productive, none saved
        acc.checkpoint_saved("u1")
        acc.tick(7)                      # 3 more productive, unsaved
        # Preemption lands: the 3 unsaved ticks are recompute — moved.
        job.status.preemptions = 1
        job.status.phase = "Restarting"
        acc.apply_event(_ev("MODIFIED", job))
        acc.tick(8)                      # held while restarting
        snap = acc.snapshot()
        assert snap["categories_ticks"]["productive"] == 4
        assert snap["categories_ticks"]["restart_rollback"] == 3 + 1
        assert snap["conserved"]
        assert snap["interruptions"]["preempt"] == 1

    def test_migration_cause_comes_from_defrag_event(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 1})
        job = _job("m", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(2)
        ev = types.SimpleNamespace(
            kind="Event", involved_kind="TpuJob", involved_name="m",
            involved_namespace="obs", reason="DefragMigration")
        acc.apply_event(_ev("ADDED", ev))
        job.status.preemptions = 1
        job.status.phase = "Restarting"
        acc.apply_event(_ev("MODIFIED", job))
        acc.tick(3)
        snap = acc.snapshot()
        assert snap["interruptions"]["migration"] == 1
        assert snap["interruptions"]["preempt"] == 0
        # Unsaved work moved to `migration`, and the held restart tick
        # classifies as migration too.
        assert snap["categories_ticks"]["migration"] == 2 + 1
        assert snap["conserved"]

    def test_checkpoint_window_is_overhead(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 2})
        job = _job("c", uid="u1", phase="Running", slices=2, admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(3)
        acc.set_checkpointing("u1", True)
        acc.tick(4)
        acc.set_checkpointing("u1", False)
        acc.checkpoint_saved("u1")
        acc.tick(5)
        snap = acc.snapshot()
        assert snap["categories_ticks"]["checkpoint_overhead"] == 2
        assert snap["categories_ticks"]["productive"] == 8
        assert snap["conserved"]

    def test_capacity_reclaim_stops_tracking(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 2})
        acc.tick(2)                      # 2 units x 2 ticks idle
        acc.set_capacity({"v5e-16": 1})
        acc.tick(5)                      # only 1 unit offered
        acc.set_capacity({"v5e-16": 2})
        acc.tick(6)
        snap = acc.snapshot()
        assert snap["tracked_ticks"] == 4 + 3 + 2
        assert snap["conserved"]

    def test_rollback_tracking_off_never_moves(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 1},
                                              track_rollback=False)
        job = _job("r", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(6)
        job.status.preemptions = 1
        job.status.phase = "Restarting"
        acc.apply_event(_ev("MODIFIED", job))
        acc.tick(7)
        snap = acc.snapshot()
        assert snap["categories_ticks"]["productive"] == 6
        assert snap["categories_ticks"]["restart_rollback"] == 1
        assert snap["interruptions"]["preempt"] == 1
        assert snap["conserved"]

    def test_categories_are_exhaustive(self):
        assert set(CATEGORIES) == {
            "productive", "queue_wait", "restart_rollback", "migration",
            "checkpoint_overhead", "idle_free",
        }


class TestJournalReplay:
    def test_replay_rebuilds_byte_identical_ledger(self, tmp_path):
        journal = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity({"v5e-16": 2},
                                              journal_path=journal,
                                              fsync=False)
        job = _job("r", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(3)
        acc.checkpoint_saved("u1")
        acc.tick(5)
        job.status.preemptions = 1
        job.status.phase = "Restarting"
        acc.apply_event(_ev("MODIFIED", job))
        acc.tick(6)
        acc.set_capacity({"v5e-16": 1})
        acc.tick(8)
        acc.close()

        twin = GoodputAccountant.from_capacity({"v5e-16": 2})
        assert twin.replay_from(journal) > 0
        assert twin.fingerprint() == acc.fingerprint()
        assert twin.last_tick() == acc.last_tick()
        assert twin.conservation()["exact"]

    def test_own_journal_replay_compacts_to_state_record(self, tmp_path):
        journal = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity({"v5e-16": 2},
                                              journal_path=journal,
                                              fsync=False)
        job = _job("r", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        for t in range(1, 6):
            acc.tick(t)
        acc.close()
        # Second incarnation replays ITS OWN journal: ledger rebuilt,
        # then the log compacts to one state record (bounded respawns).
        acc2 = GoodputAccountant.from_capacity({"v5e-16": 2},
                                               journal_path=journal,
                                               fsync=False)
        acc2.replay_from(journal)
        assert acc2.fingerprint() == acc.fingerprint()
        with open(journal) as f:
            lines = f.readlines()
        assert len(lines) == 1 and '"op": "state"' in lines[0]
        # Appends continue past the compacted head; a THIRD incarnation
        # replays state + tail to the same ledger.
        acc2.apply_event(_ev("ADDED", job))
        acc2.tick(7)
        acc2.close()
        acc3 = GoodputAccountant.from_capacity({"v5e-16": 2},
                                               journal_path=journal,
                                               fsync=False)
        acc3.replay_from(journal)
        assert acc3.fingerprint() == acc2.fingerprint()
        assert acc3.last_tick() == 7
        assert acc3.conservation()["exact"]

    def test_torn_tail_is_ignored(self, tmp_path):
        journal = str(tmp_path / "goodput.jsonl")
        acc = GoodputAccountant.from_capacity({"v5e-16": 1},
                                              journal_path=journal,
                                              fsync=False)
        acc.tick(3)
        acc.close()
        with open(journal, "a") as f:
            f.write('{"op": "tick", "t": 9')     # crash mid-append
        twin = GoodputAccountant.from_capacity({"v5e-16": 1})
        twin.replay_from(journal)
        assert twin.last_tick() == 3
        assert twin.conservation()["exact"]


class TestFingerprintUnion:
    def test_shard_rows_union_like_state_fingerprint(self):
        a = GoodputAccountant.from_capacity({"v5e-16": 2},
                                            unit_prefix="sh00:")
        b = GoodputAccountant.from_capacity({"v5e-16": 2},
                                            unit_prefix="sh01:")
        a.tick(4)
        b.tick(4)
        # Prefixed unit ids keep every per-unit row globally unique, so
        # the union digest is order-independent — exactly how
        # state_fingerprint unions per-shard rows.
        union1 = goodput_rows_digest(a.rows() + b.rows())
        union2 = goodput_rows_digest(b.rows() + a.rows())   # order-free
        assert union1 == union2
        # ...and sensitive: one more attributed tick on ONE shard
        # changes the fleet digest.
        a.tick(5)
        assert goodput_rows_digest(a.rows() + b.rows()) != union1
        # Unit rows never collide across shards.
        a_units = {r[1] for r in a.rows() if r[0] == "unit"}
        b_units = {r[1] for r in b.rows() if r[0] == "unit"}
        assert not (a_units & b_units)


class TestParity:
    def test_chaos_and_policy_preemption_attribute_identically(self):
        rep = chaos_policy_parity_report(seed=7)
        assert rep["conserved"]
        assert rep["preemptions_attributed"] == 1
        assert rep["identical"], (rep["chaos"], rep["policy"])


class TestLiveControlPlane:
    """The accountant against the real apiserver + controller stack."""

    def _world(self, capacity):
        registry = MetricsRegistry()
        api = InMemoryApiServer(registry=registry)
        mgr = ControllerManager(api, registry)
        mgr.register(TpuJobController(api, registry, hbm_check=False,
                                      capacity=dict(capacity)))
        kubelet = FakeKubelet(api, registry, outcome=lambda name: None)
        mgr.register(kubelet)
        return registry, api, mgr, kubelet

    def test_watch_stream_attribution_and_metrics(self):
        registry, api, mgr, kubelet = self._world({"v5e-16": 1})
        acc = GoodputAccountant.from_capacity({"v5e-16": 1},
                                              registry=registry)
        acc.attach(api)
        api.create(_job("train", ns="ml"))
        api.create(_job("waits", ns="ml"))      # capacity-blocked
        tick = 0
        for _ in range(3):
            # Kick parked admission requeues ONCE per tick, zero-window
            # drain (a wide window would treadmill the capacity-parked
            # gang's 5s park timer forever — the storm driver's rule).
            mgr.kick_timers(3600.0)
            mgr.run_until_idle(max_iterations=50000)
            kubelet.tick()
            mgr.run_until_idle(max_iterations=50000)
            acc.pump()
            tick += 1
            acc.tick(tick)
        snap = acc.snapshot()
        # One slice, one Running gang, one queued: every tick productive
        # (the queued gang can't show as queue_wait — zero free units).
        assert snap["categories_ticks"]["productive"] == 3
        assert snap["tracked_ticks"] == 3
        assert snap["conserved"]
        # Demand-side wait on the blocked job's own ledger.
        assert snap["jobs"]["ml/waits"]["categories_ticks"] == {
            "queue_wait": 3}
        # Metric surfaces.
        c = registry.get("kftpu_goodput_slice_seconds_total")
        assert c.value(category="productive") == 3.0
        g = registry.get("kftpu_job_goodput_ratio")
        assert g.value(namespace="ml", name="train") == 1.0
        assert g.value(namespace="ml", name="waits") == 0.0
        mgr.close()
        acc.close()


class TestSoakAndStormIntegration:
    def test_soak_goodput_conserves_and_attributes_preemptions(self):
        from kubeflow_tpu.chaos import run_soak

        rep = run_soak(num_jobs=4, seed=20260803, conflict_rate=0.3,
                       transient_rate=0.05, preempt_every=3,
                       fault_rounds=9, max_rounds=40)
        g = rep.goodput
        assert g and g["conserved"]
        assert sum(g["categories_ticks"].values()) == g["tracked_ticks"]
        assert g["interruptions"]["preempt"] == rep.job_preemption_restarts
        assert g["categories_ticks"]["productive"] > 0

    def test_storm_goodput_with_checkpoint_model(self):
        from kubeflow_tpu.scheduler.benchmark import (
            check_storm_gates,
            run_schedule_storm,
        )

        common = dict(num_jobs=18, fleet_capacity={"v5e-16": 4},
                      pool_size=4, seed=5, chaos_at_tick=5,
                      chaos_preempts=2, ckpt_every_ticks=2)
        rep = run_schedule_storm(policy="priority", **common)
        check_storm_gates(rep)          # includes goodput conservation
        g = rep.goodput
        assert g["conserved"]
        assert g["categories_ticks"]["productive"] > 0
        assert g["categories_ticks"]["checkpoint_overhead"] > 0
        assert g["categories_ticks"]["restart_rollback"] > 0
        assert rep.queue_age_count > 0
        # Tick-determinism holds with the ledger in the loop.
        again = run_schedule_storm(policy="priority", **common)
        assert again.summary() == rep.summary()

    def test_storm_default_mode_is_rollback_free(self):
        from kubeflow_tpu.scheduler.benchmark import run_schedule_storm

        rep = run_schedule_storm(num_jobs=10,
                                 fleet_capacity={"v5e-16": 4},
                                 pool_size=4, seed=3)
        g = rep.goodput
        assert g["conserved"]
        # No checkpoint model: continuous checkpointing, nothing moved.
        assert g["categories_ticks"]["checkpoint_overhead"] == 0


class TestShardedGoodput:
    def test_sigkill_replay_is_byte_identical(self):
        from kubeflow_tpu.chaos import run_sharded_soak

        rep = run_sharded_soak(num_jobs=4, shards=2, seed=20260803,
                               conflict_rate=0.3, transient_rate=0.05,
                               preempt_every=3, kill_shard_round=4,
                               fault_rounds=8, max_rounds=40)
        assert rep.shard_kills == 1
        assert rep.goodput_replay_identical
        assert rep.goodput_conserved
        assert rep.goodput["tracked_ticks"] > 0
        assert (sum(rep.goodput["categories_ticks"].values())
                == rep.goodput["tracked_ticks"])


class TestPlatformStatePersistence:
    def test_dump_load_roundtrip(self):
        acc = GoodputAccountant.from_capacity({"v5e-16": 2})
        job = _job("r", uid="u1", phase="Running", admitted=True)
        acc.apply_event(_ev("ADDED", job))
        acc.tick(4)
        state = json.loads(json.dumps(acc.dump_state()))   # wire trip
        twin = GoodputAccountant.from_capacity({"v5e-16": 2})
        twin.load_state(state)
        assert twin.fingerprint() == acc.fingerprint()
        assert twin.conservation()["exact"]
