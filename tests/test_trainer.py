import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import Llama, LlamaConfig, Mixtral, MixtralConfig, ResNet, ResNetConfig
from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
from kubeflow_tpu.train import TrainConfig, Trainer
from kubeflow_tpu.train.data import (
    SyntheticImageConfig,
    SyntheticTextConfig,
    synthetic_images,
    synthetic_text,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_host_local_mesh(AxisSpec(dp=2, fsdp=2, tp=2))


def _lm_batch(vocab=256, bs=4, seq=16, seed=0):
    it = synthetic_text(
        SyntheticTextConfig(batch_size=bs, seq_len=seq, vocab_size=vocab, seed=seed)
    )
    return {k: jnp.asarray(v) for k, v in next(it).items()}


class TestLmTrainer:
    def test_loss_decreases(self, mesh8):
        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm", learning_rate=1e-2,
                                             warmup_steps=2, total_steps=30),
                          mesh8)
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        _, m0 = trainer.step(state, batch)
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for i in range(15):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.9, losses
        assert int(state.step) == 15

    def test_params_are_sharded(self, mesh8):
        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm"), mesh8)
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        # mlp kernel is (embed=fsdp, mlp=tp)-sharded → each shard holds 1/4.
        mlp = state.params["layer_0"]["mlp"]["gate_proj"]["kernel"]
        shard = mlp.addressable_shards[0]
        assert shard.data.size == mlp.size // 4
        # Optimizer moments mirror param shardings.
        flat_opt = jax.tree.leaves(state.opt_state)
        big = [x for x in flat_opt if hasattr(x, "sharding") and x.size == mlp.size]
        assert big and all(
            b.addressable_shards[0].data.size == mlp.size // 4 for b in big
        )

    def test_mixtral_with_ep(self, devices8):
        mesh = make_host_local_mesh(AxisSpec(dp=2, ep=4))
        model = Mixtral(MixtralConfig.tiny())
        trainer = Trainer(
            model,
            TrainConfig(task="lm", aux_loss_weight=0.02, warmup_steps=2),
            mesh,
        )
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(1))
        assert np.isfinite(metrics["loss"])
        assert float(metrics["aux_loss"]) > 0

    def test_ring_attention_training(self, devices8):
        mesh = make_host_local_mesh(AxisSpec(dp=2, sp=4))
        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(
            model, TrainConfig(task="lm", attn_impl="ring", warmup_steps=2), mesh
        )
        batch = trainer.shard_batch(_lm_batch(seq=32))
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(metrics["loss"])


class TestOptimizerFamilies:
    """TrainConfig.optimizer selects the optimizer; every family must
    train (finite, decreasing loss) with params sharded over the same
    mesh, and params-shaped moment subtrees must inherit param shardings
    via the path-suffix matcher (factored/scalar stats replicate)."""

    @pytest.mark.parametrize("name", ["lion", "adafactor", "sgd"])
    def test_family_trains_sharded(self, mesh8, name):
        model = Llama(LlamaConfig.tiny())
        lr = {"lion": 1e-3, "adafactor": 1e-2, "sgd": 1e-2}[name]
        trainer = Trainer(
            model,
            TrainConfig(task="lm", optimizer=name, learning_rate=lr,
                        warmup_steps=2, total_steps=30),
            mesh8,
        )
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for _ in range(12):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all(), (name, losses)
        assert losses[-1] < losses[0], (name, losses)
        # Params stay sharded regardless of optimizer family.
        mlp = state.params["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert mlp.addressable_shards[0].data.size == mlp.size // 4

    def test_unknown_family_rejected(self):
        with pytest.raises(ValueError, match="unknown optimizer"):
            TrainConfig(optimizer="rmsprop").make_optimizer()


class TestLrSchedules:
    def _sched(self, name, lr=1e-3, warmup=10, total=100):
        return TrainConfig(learning_rate=lr, warmup_steps=warmup,
                           total_steps=total,
                           lr_schedule=name).make_schedule()

    @pytest.mark.parametrize("name", ["warmup_cosine", "warmup_linear",
                                      "constant", "rsqrt"])
    def test_warmup_and_peak(self, name):
        s = self._sched(name)
        assert float(s(0)) == pytest.approx(0.0, abs=1e-7)
        assert float(s(10)) == pytest.approx(1e-3, rel=1e-5)

    def test_tails(self):
        # cosine/linear decay to 10%; constant holds; rsqrt follows
        # peak*sqrt(w/step).
        assert float(self._sched("warmup_cosine")(100)) == pytest.approx(
            1e-4, rel=1e-3)
        assert float(self._sched("warmup_linear")(100)) == pytest.approx(
            1e-4, rel=1e-3)
        assert float(self._sched("constant")(100)) == pytest.approx(
            1e-3, rel=1e-6)
        assert float(self._sched("rsqrt")(1000)) == pytest.approx(
            1e-3 * (10 / 1000) ** 0.5, rel=1e-5)

    def test_family_trains(self, mesh8):
        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(
            model,
            TrainConfig(task="lm", learning_rate=1e-2, warmup_steps=2,
                        total_steps=30, lr_schedule="rsqrt"),
            mesh8,
        )
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for _ in range(10):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_unknown_rejected(self):
        with pytest.raises(ValueError, match="unknown lr_schedule"):
            TrainConfig(lr_schedule="cyclic").make_schedule()


class TestEvaluate:
    def test_lm_eval_metrics(self, mesh8):
        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm"), mesh8)
        batch = _lm_batch()
        sb = trainer.shard_batch(batch)
        state = trainer.init_state(jax.random.PRNGKey(0), sb)
        out = trainer.evaluate(state, [batch, _lm_batch(seed=1)])
        assert set(out) == {"loss", "accuracy", "perplexity"}
        assert np.isfinite(out["loss"]) and out["loss"] > 0
        assert out["perplexity"] == pytest.approx(
            np.exp(out["loss"]), rel=1e-6)
        # Deterministic: same held-out set scores identically (no rngs,
        # no state mutation).
        again = trainer.evaluate(state, [batch, _lm_batch(seed=1)])
        assert again["loss"] == out["loss"]

    def test_eval_excludes_z_loss_and_aux(self, devices8):
        """Eval loss is pure CE: on an MoE model the train-step loss
        carries aux routing terms, evaluate must not."""
        mesh = make_host_local_mesh(AxisSpec(dp=2, ep=4))
        model = Mixtral(MixtralConfig.tiny(num_experts=4))
        trainer = Trainer(
            model,
            TrainConfig(task="lm", aux_loss_weight=0.5, z_loss_weight=1.0),
            mesh,
        )
        batch = _lm_batch()
        sb = trainer.shard_batch(batch)
        state = trainer.init_state(jax.random.PRNGKey(0), sb)
        ev = trainer.evaluate(state, [batch])   # before step: step donates
        _, train_metrics = trainer.step(state, sb, rng=jax.random.PRNGKey(1))
        # The inflated z/aux train loss must exceed the pure-CE eval loss
        # (both scored on the same pre-update params and batch).
        assert float(train_metrics["loss"]) > ev["loss"]

    def test_image_eval(self, mesh8):
        model = ResNet(ResNetConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="image"), mesh8)
        it = synthetic_images(
            SyntheticImageConfig(batch_size=8, image_size=32, num_classes=10)
        )
        b = next(it)
        sb = trainer.shard_batch({k: jnp.asarray(v) for k, v in b.items()})
        state = trainer.init_state(jax.random.PRNGKey(0), sb)
        out = trainer.evaluate(state, [b])
        assert set(out) == {"loss", "accuracy"}
        assert np.isfinite(out["loss"])


class TestGradAccumulation:
    """TrainConfig.grad_accum_steps: K microbatches scanned per step with
    f32 gradient accumulation must match the full-batch step numerically
    (same data, f32 params -> tolerance is summation-order noise)."""

    def _trainer(self, mesh8, k):
        model = Llama(LlamaConfig.tiny())
        return Trainer(
            model,
            TrainConfig(task="lm", learning_rate=1e-2, warmup_steps=2,
                        total_steps=30, grad_accum_steps=k),
            mesh8,
        )

    def test_matches_full_batch_step(self, mesh8):
        batch = _lm_batch(bs=8)
        losses = {}
        for k in (1, 4):
            tr = self._trainer(mesh8, k)
            b = tr.shard_batch(batch)
            state = tr.init_state(jax.random.PRNGKey(0), b)
            for _ in range(3):
                state, metrics = tr.step(state, b)
            losses[k] = float(metrics["loss"])
            assert int(state.step) == 3
        # Same data, same updates: after 3 steps the losses agree to
        # f32 summation noise.
        assert losses[1] == pytest.approx(losses[4], rel=2e-4), losses

    def test_masked_batch_matches_global_normalisation(self, mesh8):
        """Padding distributed unevenly across microbatches: per-microbatch
        masked means must be token-weighted back to the full-batch global
        normalisation, not averaged equally."""
        batch = _lm_batch(bs=8)
        # LM rows carry seq_len+1 tokens (the shift contract); mask
        # matches the token shape and is sliced [:, 1:] to label shape.
        mask = np.ones((8, 17), np.int32)
        mask[:2, 4:] = 0     # rows 0-1 (microbatch 0 at K=4) mostly padding
        batch = {**batch, "mask": jnp.asarray(mask)}
        losses = {}
        for k in (1, 4):
            tr = self._trainer(mesh8, k)
            b = tr.shard_batch(batch)
            state = tr.init_state(jax.random.PRNGKey(0), b)
            for _ in range(3):
                state, metrics = tr.step(state, b)
            losses[k] = float(metrics["loss"])
        assert losses[1] == pytest.approx(losses[4], rel=2e-4), losses

    def test_batchnorm_model_threads_stats(self, mesh8):
        model = ResNet(ResNetConfig.tiny())
        trainer = Trainer(
            model,
            TrainConfig(task="image", learning_rate=1e-2, warmup_steps=2,
                        grad_accum_steps=2),
            mesh8,
        )
        it = synthetic_images(
            SyntheticImageConfig(batch_size=8, image_size=32, num_classes=10)
        )
        batch = trainer.shard_batch(
            {k: jnp.asarray(v) for k, v in next(it).items()})
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        before = jax.tree.leaves(state.extra_vars)[0].copy()
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        after = jax.tree.leaves(state.extra_vars)[0]
        assert not np.allclose(np.asarray(before), np.asarray(after))

    def test_indivisible_batch_rejected(self, mesh8):
        tr = self._trainer(mesh8, 3)
        b = tr.shard_batch(_lm_batch(bs=8))
        state = tr.init_state(jax.random.PRNGKey(0), b)
        with pytest.raises(AssertionError, match="not divisible"):
            tr.step(state, b)


class TestImageTrainer:
    def test_resnet_loss_decreases(self, mesh8):
        model = ResNet(ResNetConfig.tiny())
        trainer = Trainer(
            model,
            TrainConfig(task="image", learning_rate=5e-3, warmup_steps=2,
                        total_steps=30, weight_decay=0.0),
            mesh8,
        )
        it = synthetic_images(
            SyntheticImageConfig(batch_size=8, image_size=32, num_classes=10)
        )
        batch = trainer.shard_batch({k: jnp.asarray(v) for k, v in next(it).items()})
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        for _ in range(10):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses
        # batch_stats updated each step
        assert state.extra_vars["batch_stats"]


class TestCheckpoint:
    def test_save_restore_roundtrip(self, mesh8, tmp_path):
        from kubeflow_tpu.train import CheckpointService

        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm", warmup_steps=2), mesh8)
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        state, _ = trainer.step(state, batch)

        svc = CheckpointService(str(tmp_path / "ckpt"))
        assert svc.restore_latest(jax.eval_shape(lambda: state)) is None
        svc.save(int(state.step), state)
        svc.wait()
        assert svc.latest_step() == 1

        restored = svc.restore_latest(jax.eval_shape(lambda: state))
        assert restored is not None
        np.testing.assert_array_equal(
            np.asarray(restored.step), np.asarray(state.step)
        )
        a = jax.tree.leaves(restored.params)[0]
        b = jax.tree.leaves(state.params)[0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        svc.close()

    def test_resume_continues_training(self, mesh8, tmp_path):
        from kubeflow_tpu.train import CheckpointService

        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm", warmup_steps=2), mesh8)
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        for _ in range(3):
            state, _ = trainer.step(state, batch)
        svc = CheckpointService(str(tmp_path / "ckpt2"))
        svc.save(int(state.step), state)
        svc.wait()

        # Simulated preemption: fresh process state, restore, keep going.
        state2 = trainer.init_state(jax.random.PRNGKey(0), batch)
        restored = svc.restore_latest(jax.eval_shape(lambda: state2))
        assert int(restored.step) == 3
        restored, metrics = trainer.step(restored, batch)
        assert int(restored.step) == 4
        assert np.isfinite(metrics["loss"])
        svc.close()


class TestAuxLossNormalisation:
    def test_scan_and_unrolled_agree(self, devices8):
        """Effective MoE aux weighting must not depend on scan_layers."""
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh

        mesh = make_host_local_mesh(AxisSpec(dp=-1))
        batch = _lm_batch(bs=8, seq=16)
        outs = {}
        for scan in (False, True):
            cfg = MixtralConfig.tiny(num_layers=2, scan_layers=scan)
            trainer = Trainer(
                Mixtral(cfg),
                TrainConfig(task="lm", aux_loss_weight=0.02, warmup_steps=2),
                mesh,
            )
            b = trainer.shard_batch(batch)
            state = trainer.init_state(jax.random.PRNGKey(0), b)
            _, metrics = trainer.step(state, b)
            outs[scan] = float(metrics["aux_loss"])
        # Different init RNG streams under scan → values differ slightly, but
        # must be the same scale (a num_layers-factor bug would give 2x).
        ratio = outs[True] / outs[False]
        assert 0.6 < ratio < 1.67, outs


class TestAuxWeightInheritance:
    def test_model_config_aux_weight_used_by_default(self, devices8):
        from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh

        mesh = make_host_local_mesh(AxisSpec(dp=-1))
        cfg = MixtralConfig.tiny(num_layers=1)
        assert cfg.aux_loss_weight > 0
        trainer = Trainer(Mixtral(cfg), TrainConfig(task="lm"), mesh)
        assert trainer.aux_loss_weight == cfg.aux_loss_weight
        # Explicit TrainConfig value wins.
        t2 = Trainer(Mixtral(cfg), TrainConfig(task="lm", aux_loss_weight=0.5),
                     mesh)
        assert t2.aux_loss_weight == 0.5


class TestOptimizerShardingByPath:
    def test_masked_wrapper_states_inherit_param_shardings(self, mesh8):
        """Optax states that wrap params-shaped subtrees (masked weight
        decay, multi_transform) must still land their moments in the param
        shardings — matching is by path suffix, not whole-tree equality."""
        import optax

        model = Llama(LlamaConfig.tiny())
        trainer = Trainer(model, TrainConfig(task="lm"), mesh8)
        # Replace the optimizer with a masked chain whose state treedef
        # does NOT equal the params treedef.
        trainer.optimizer = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.masked(
                optax.adamw(1e-3),
                lambda params: jax.tree.map(lambda _: True, params),
            ),
        )
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)

        # Find an adam mu leaf for a tp-sharded kernel and compare with the
        # corresponding param's sharding.
        p = state.params["layer_0"]["attn"]["q_proj"]["kernel"]
        flat = jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
        mu_leaves = [
            (path, leaf) for path, leaf in flat
            if "q_proj" in "".join(str(k) for k in path)
            and ".mu" in "".join(str(k) for k in path)
        ]
        assert mu_leaves, "no mu leaf found for q_proj"
        for _, leaf in mu_leaves:
            assert leaf.sharding == p.sharding, (
                f"mu sharded {leaf.sharding}, param {p.sharding}"
            )

        # And a train step still runs.
        state2, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestMixedPrecisionOptState:
    def test_bf16_params_keep_f32_moments(self, mesh8):
        """bf16 params must NOT leak into optimizer state: optax inits
        states from the params tree, so without the f32 wrapper nu would be
        bf16 and underflow (bench/mixed-precision contract)."""
        cfg = LlamaConfig.tiny(param_dtype=jnp.bfloat16)
        trainer = Trainer(Llama(cfg), TrainConfig(task="lm"), mesh8)
        batch = trainer.shard_batch(_lm_batch())
        state = trainer.init_state(jax.random.PRNGKey(0), batch)
        assert all(
            p.dtype == jnp.bfloat16 for p in jax.tree.leaves(state.params)
        )
        float_moments = [
            l for l in jax.tree.leaves(state.opt_state)
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
            and l.ndim > 0
        ]
        assert float_moments
        assert all(l.dtype == jnp.float32 for l in float_moments), {
            l.dtype for l in float_moments
        }
        # And the step still trains.
        state2, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert all(
            p.dtype == jnp.bfloat16 for p in jax.tree.leaves(state2.params)
        )
