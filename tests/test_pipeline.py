"""Pipeline parallelism: the GPipe SPMD schedule must be a *relayout*, not a
different computation — outputs and gradients must match running the same
stacked weights sequentially layer-by-layer.

Mirrors the verification style of tests/test_attention_parallel.py (sharded
impl vs single-device reference, fwd + grad) on the 8-device virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from kubeflow_tpu.models.llama import DecoderLayer, Llama, LlamaConfig
from kubeflow_tpu.parallel.context import parallel_context
from kubeflow_tpu.parallel.pipeline import PipelinedLayers
from kubeflow_tpu.topology.mesh import AxisSpec, make_host_local_mesh
from kubeflow_tpu.train.trainer import TrainConfig, Trainer


def _cfg(**kw):
    kw.setdefault("remat", False)
    return LlamaConfig.tiny(**kw)


def _sequential_reference(params, cfg, x, positions):
    """Apply the pipeline's stacked params [S, Lps, ...] layer by layer."""
    stacked = params["stages"]["layers"]
    S = jax.tree.leaves(stacked)[0].shape[0]
    Lps = jax.tree.leaves(stacked)[0].shape[1]
    layer = DecoderLayer(cfg)
    for s in range(S):
        for l in range(Lps):
            p = jax.tree.map(lambda a: a[s, l], stacked)
            x = layer.apply({"params": p}, x, positions)
    return x


class TestPipelinedLayers:
    @pytest.mark.parametrize("stages,microbatches", [(2, 2), (2, 4), (4, 4)])
    def test_matches_sequential(self, stages, microbatches):
        cfg = _cfg(num_layers=4)
        B, S = microbatches * 2, 16
        mod = PipelinedLayers(
            cfg, layer_cls=DecoderLayer, num_stages=stages,
            num_microbatches=microbatches,
        )
        x = jax.random.normal(
            jax.random.key(0), (B, S, cfg.embed_dim), jnp.float32
        )
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        variables = mod.init(jax.random.key(1), x, positions)
        params = nn.meta.unbox(variables["params"])
        got = mod.apply({"params": params}, x, positions)
        want = _sequential_reference(params, cfg, x, positions)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_gradients_match_sequential(self):
        # f32 activations: the schedules reorder bf16 accumulations, so exact
        # grad comparison needs full precision (fwd test covers bf16).
        cfg = _cfg(num_layers=4, dtype=jnp.float32)
        stages, microbatches = 2, 2
        B, S = 4, 8
        mod = PipelinedLayers(
            cfg, layer_cls=DecoderLayer, num_stages=stages,
            num_microbatches=microbatches,
        )
        x = jax.random.normal(
            jax.random.key(0), (B, S, cfg.embed_dim), jnp.float32
        )
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        params = nn.meta.unbox(
            mod.init(jax.random.key(1), x, positions)["params"]
        )

        def loss_pipe(p, x):
            return jnp.sum(mod.apply({"params": p}, x, positions) ** 2)

        def loss_seq(p, x):
            return jnp.sum(_sequential_reference(p, cfg, x, positions) ** 2)

        gp_p, gp_x = jax.grad(loss_pipe, argnums=(0, 1))(params, x)
        gs_p, gs_x = jax.grad(loss_seq, argnums=(0, 1))(params, x)
        np.testing.assert_allclose(
            np.asarray(gp_x), np.asarray(gs_x), rtol=1e-3, atol=1e-3
        )
        for a, b in zip(jax.tree.leaves(gp_p), jax.tree.leaves(gs_p)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )

    def test_per_row_positions(self):
        """Packed-sequence style per-row position offsets must ride the
        pipeline with their microbatch (not be broadcast from row 0)."""
        cfg = _cfg(num_layers=2, dtype=jnp.float32)
        B, S = 4, 8
        mod = PipelinedLayers(
            cfg, layer_cls=DecoderLayer, num_stages=2, num_microbatches=2
        )
        x = jax.random.normal(
            jax.random.key(0), (B, S, cfg.embed_dim), jnp.float32
        )
        positions = (
            jnp.arange(S)[None, :] + jnp.array([0, 3, 7, 11])[:, None]
        )
        params = nn.meta.unbox(
            mod.init(jax.random.key(1), x, positions)["params"]
        )
        got = mod.apply({"params": params}, x, positions)
        want = _sequential_reference(params, cfg, x, positions)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_validation(self):
        cfg = _cfg(num_layers=4)
        x = jnp.zeros((4, 8, cfg.embed_dim))
        positions = jnp.broadcast_to(jnp.arange(8), (4, 8))
        bad_stages = PipelinedLayers(
            cfg, layer_cls=DecoderLayer, num_stages=3, num_microbatches=2
        )
        with pytest.raises(ValueError, match="not divisible by stages"):
            bad_stages.init(jax.random.key(0), x, positions)
        bad_mb = PipelinedLayers(
            cfg, layer_cls=DecoderLayer, num_stages=2, num_microbatches=3
        )
        with pytest.raises(ValueError, match="not divisible by microbatches"):
            bad_mb.init(jax.random.key(0), x, positions)


class TestPipelinedModel:
    def test_decode_rejected(self):
        cfg = _cfg(num_layers=2, pipeline_stages=2)
        model = Llama(cfg)
        tokens = jnp.zeros((2, 4), jnp.int32)
        with pytest.raises(ValueError, match="training layout"):
            model.init(jax.random.key(0), tokens, decode=True)

    def test_train_step_on_pp_mesh(self, devices8):
        """Full sharded train step with dp×pp×tp on the 8-device mesh: the
        stage dim of the stacked layer params must actually land on pp."""
        mesh = make_host_local_mesh(AxisSpec(dp=2, pp=2, tp=2))
        cfg = _cfg(
            num_layers=4, pipeline_stages=2, pipeline_microbatches=2,
            remat=True,
        )
        model = Llama(cfg)
        trainer = Trainer(
            model, TrainConfig(task="lm", warmup_steps=2, total_steps=4), mesh
        )
        tokens = jax.random.randint(jax.random.key(0), (8, 17), 0, cfg.vocab_size)
        batch = trainer.shard_batch({"inputs": tokens})
        state = trainer.init_state(jax.random.key(1), batch)

        stage_leaf = jax.tree.leaves(
            state.params["pipeline"]["stages"]["layers"]
        )[0]
        # [stages, layers/stage, ...] with stages sharded over pp.
        assert stage_leaf.shape[0] == 2
        spec = stage_leaf.sharding.spec
        assert spec[0] == "pp", f"stage dim not on pp: {spec}"

        state2, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        state3, metrics2 = trainer.step(state2, batch)
        assert float(metrics2["loss"]) < float(metrics["loss"]) + 1.0
