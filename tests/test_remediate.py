"""Self-healing remediation controller (ISSUE 17): guardrail edges
(budget, cooldown, one-outstanding, precheck), goodput verdicts and the
auto-disable trip, operator overrides, action-journal replay
byte-identity (torn tail, rotation, re-armed verdicts included),
pre/post flight-dump evidence, the remediation-disabled watchdog
objective, and the armed soak under a real worker pool."""

import json
import os

import pytest

from kubeflow_tpu.obs.flight import FlightRecorder
from kubeflow_tpu.obs.remediate import (
    ACTIONS_JOURNAL,
    Playbook,
    RemediationController,
    remediation_objective,
    series_base,
    series_label,
)
from kubeflow_tpu.obs.slo import SLOEngine
from kubeflow_tpu.utils.monitoring import MetricsRegistry

PAGE = {"synthetic": "page"}
OK = {"synthetic": "ok"}


def _pb(action=None, **kw):
    calls = []

    def _act(rec):
        calls.append(rec)
        return {"n": len(calls)}

    kw.setdefault("name", "pb")
    kw.setdefault("objective", "synthetic")
    kw.setdefault("budget", 10)
    kw.setdefault("cooldown", 1.0)
    kw.setdefault("verify_after", 1.0)
    return Playbook(action=action or _act, **kw), calls


class TestPlaybookValidation:
    def test_name_and_objective_required(self):
        with pytest.raises(ValueError):
            Playbook(name="", objective="o", action=lambda r: {})
        with pytest.raises(ValueError):
            Playbook(name="n", objective="", action=lambda r: {})

    def test_budget_and_disable_floors(self):
        with pytest.raises(ValueError):
            Playbook(name="n", objective="o", action=lambda r: {},
                     budget=0)
        with pytest.raises(ValueError):
            Playbook(name="n", objective="o", action=lambda r: {},
                     unpaid_disable_after=0)


class TestSeriesKeys:
    def test_base_strips_shard_prefix_and_group(self):
        assert series_base("sh03:backend-queue-wait[backend=b1]") \
            == "backend-queue-wait"
        assert series_base("goodput-interruptions") \
            == "goodput-interruptions"
        # A non-shard colon segment is part of the name, not routing.
        assert series_base("ns:thing[x=y]") == "ns:thing"

    def test_label_extraction(self):
        assert series_label("backend-queue-wait[backend=b1]") == "b1"
        assert series_label("plain") == ""


class TestGuardrails:
    def test_budget_exhaustion_stops_actions(self):
        pb, calls = _pb(budget=2, cooldown=0.0, verify_after=100.0,
                        unpaid_disable_after=99)
        ctl = RemediationController(playbooks=[pb])
        t = 0.0
        for _ in range(8):
            t += 1.0
            ctl.tick(t, states=PAGE)
        # One outstanding at a time would also cap this; give verdicts
        # room by settling against a cleared page between actions.
        assert len(calls) == 1
        ctl.tick(t + 100.0, states=OK)      # settle #1 (paid)
        for _ in range(8):
            t += 200.0
            ctl.tick(t, states=PAGE)
            ctl.tick(t + 101.0, states=OK)  # settle each verdict
        assert len(calls) == 2              # budget=2 is a lifetime cap
        snap = ctl.snapshot()["playbooks"]["pb"]
        assert snap["actions"] == 2
        assert not snap["disabled"]

    def test_cooldown_spaces_actions(self):
        pb, calls = _pb(cooldown=3.0, verify_after=0.5)
        ctl = RemediationController(playbooks=[pb])
        ctl.tick(1.0, states=PAGE)          # acts
        ctl.tick(2.0, states=PAGE)          # verdict settles; cooldown
        ctl.tick(3.0, states=PAGE)          # still inside cooldown
        assert len(calls) == 1
        ctl.tick(4.0, states=PAGE)          # 1.0 + 3.0 -> eligible
        assert len(calls) == 2

    def test_one_outstanding_action_per_playbook(self):
        pb, calls = _pb(cooldown=0.0, verify_after=50.0)
        ctl = RemediationController(playbooks=[pb])
        for t in range(1, 10):
            ctl.tick(float(t), states=PAGE)
        assert len(calls) == 1              # verdict still pending
        ctl.tick(60.0, states=PAGE)         # settles (unpaid), then acts
        assert len(calls) == 2

    def test_precheck_refusal_burns_no_budget(self):
        pb, calls = _pb(budget=2)
        pb = Playbook(name=pb.name, objective=pb.objective,
                      action=pb.action, precheck=lambda rec: False,
                      budget=2, cooldown=0.0, verify_after=1.0)
        ctl = RemediationController(playbooks=[pb])
        for t in range(1, 6):
            ctl.tick(float(t), states=PAGE)
        assert calls == []
        assert ctl.snapshot()["playbooks"]["pb"]["actions"] == 0

    def test_nothing_paging_means_nothing_happens(self):
        pb, calls = _pb()
        ctl = RemediationController(playbooks=[pb])
        for t in range(1, 6):
            ctl.tick(float(t), states=OK)
        assert calls == []

    def test_action_exception_contained_and_journaled(self, tmp_path):
        def _boom(rec):
            raise RuntimeError("seam exploded")

        pb = Playbook(name="boom", objective="synthetic", action=_boom,
                      cooldown=0.0, verify_after=1.0)
        path = str(tmp_path / ACTIONS_JOURNAL)
        ctl = RemediationController(playbooks=[pb], journal_path=path,
                                    fsync=False)
        ctl.tick(1.0, states=PAGE)          # must not raise
        ctl.close()
        recs = [json.loads(l) for l in open(path)]
        # The action was journaled BEFORE the seam blew up.
        assert [r["op"] for r in recs] == ["action"]


class TestVerdicts:
    def test_paid_requires_clear_and_cost_within_budget(self):
        cost = {"v": 0.0}
        pb, _ = _pb(cooldown=0.0, verify_after=1.0)
        ctl = RemediationController(playbooks=[pb],
                                    cost_fn=lambda: cost["v"])
        ctl.tick(1.0, states=PAGE)
        ctl.tick(2.5, states=OK)            # cleared, zero cost -> paid
        row = ctl.snapshot()["playbooks"]["pb"]
        assert (row["paid"], row["unpaid"], row["streak"]) == (1, 0, 0)

    def test_unpaid_when_page_persists(self):
        pb, _ = _pb(cooldown=0.0, verify_after=1.0,
                    unpaid_disable_after=99)
        ctl = RemediationController(playbooks=[pb])
        ctl.tick(1.0, states=PAGE)
        ctl.tick(2.5, states=PAGE)
        row = ctl.snapshot()["playbooks"]["pb"]
        assert (row["paid"], row["unpaid"], row["streak"]) == (0, 1, 1)

    def test_unpaid_when_cost_exceeds_budget_despite_clear(self):
        cost = {"v": 0.0}
        pb, _ = _pb(cooldown=0.0, verify_after=1.0,
                    unpaid_disable_after=99)
        ctl = RemediationController(playbooks=[pb],
                                    cost_fn=lambda: cost["v"])
        ctl.tick(1.0, states=PAGE)
        cost["v"] = 5.0                     # the action cost 5 ticks
        ctl.tick(2.5, states=OK)            # cleared but unrepaid
        row = ctl.snapshot()["playbooks"]["pb"]
        assert (row["paid"], row["unpaid"]) == (0, 1)
        assert row["last_verdict"]["cleared"] is True

    def test_paid_resets_the_unpaid_streak(self):
        pb, _ = _pb(cooldown=0.0, verify_after=1.0,
                    unpaid_disable_after=3)
        ctl = RemediationController(playbooks=[pb])
        ctl.tick(1.0, states=PAGE)
        ctl.tick(2.5, states=PAGE)          # unpaid, streak 1
        ctl.tick(3.0, states=PAGE)          # act again
        ctl.tick(4.5, states=OK)            # paid, streak resets
        ctl.tick(5.0, states=PAGE)
        ctl.tick(6.5, states=PAGE)          # unpaid, streak 1 again
        row = ctl.snapshot()["playbooks"]["pb"]
        assert row["streak"] == 1
        assert not row["disabled"]


class TestAutoDisable:
    def _trip(self, reg=None):
        pb, calls = _pb(cooldown=0.0, verify_after=1.0,
                        unpaid_disable_after=2, budget=10)
        ctl = RemediationController(reg, playbooks=[pb])
        t = 0.0
        for _ in range(10):
            t += 1.0
            ctl.tick(t, states=PAGE)
            if ctl.disabled_playbooks():
                break
        return ctl, calls, t

    def test_unpaid_streak_trips_within_budget(self):
        ctl, calls, _ = self._trip()
        row = ctl.snapshot()["playbooks"]["pb"]
        assert row["disabled"]
        assert row["disabled_source"] == "auto"
        assert row["streak"] >= 2
        assert len(calls) < 10              # tripped before the budget

    def test_disabled_playbook_takes_no_more_actions(self):
        ctl, calls, t = self._trip()
        n = len(calls)
        for _ in range(5):
            t += 1.0
            ctl.tick(t, states=PAGE)
        assert len(calls) == n

    def test_disable_pages_the_watchdog_objective(self):
        reg = MetricsRegistry()
        eng = SLOEngine(reg, objectives=[remediation_objective()])
        ctl, _, t = self._trip(reg)
        assert ctl.disabled_playbooks() == ["pb"]
        for _ in range(8):
            t += 1.0
            eng.evaluate(t)
        assert eng.pages_by_objective().get("remediation-disabled", 0) >= 1
        eng.close()


class TestOperatorOverrides:
    def test_disable_enable_roundtrip(self):
        pb, calls = _pb(cooldown=0.0, verify_after=1.0)
        ctl = RemediationController(playbooks=[pb])
        ctl.disable("pb", now=1.0, reason="maintenance")
        ctl.tick(2.0, states=PAGE)
        assert calls == []
        row = ctl.snapshot()["playbooks"]["pb"]
        assert row["disabled_source"] == "operator"
        ctl.enable("pb", now=3.0)
        ctl.tick(4.0, states=PAGE)
        assert len(calls) == 1

    def test_enable_resets_streak(self):
        pb, _ = _pb(cooldown=0.0, verify_after=1.0,
                    unpaid_disable_after=2)
        ctl = RemediationController(playbooks=[pb])
        t = 0.0
        for _ in range(10):
            t += 1.0
            ctl.tick(t, states=PAGE)
            if ctl.disabled_playbooks():
                break
        ctl.enable("pb", now=t + 1.0)
        assert ctl.snapshot()["playbooks"]["pb"]["streak"] == 0

    def test_unknown_playbook_raises(self):
        ctl = RemediationController()
        with pytest.raises(KeyError):
            ctl.disable("typo")
        with pytest.raises(KeyError):
            ctl.enable("typo")


class TestJournalReplay:
    def _scenario(self, path, *, fsync=False, rotate_bytes=1 << 20):
        pb, _ = _pb(cooldown=0.0, verify_after=1.0,
                    unpaid_disable_after=2)
        ctl = RemediationController(playbooks=[pb], journal_path=path,
                                    fsync=fsync,
                                    rotate_bytes=rotate_bytes)
        t = 0.0
        for _ in range(6):
            t += 1.0
            ctl.tick(t, states=PAGE)
        ctl.disable("pb", now=t + 1.0, reason="operator stop")
        ctl.enable("pb", now=t + 2.0)
        fp = ctl.fingerprint()
        ctl.close()
        return fp

    def test_replay_byte_identity(self, tmp_path):
        path = str(tmp_path / ACTIONS_JOURNAL)
        fp = self._scenario(path)
        fresh = RemediationController()     # no playbooks registered
        assert fresh.replay_from(path) > 0
        assert fresh.fingerprint() == fp

    def test_torn_tail_dropped(self, tmp_path):
        path = str(tmp_path / ACTIONS_JOURNAL)
        self._scenario(path)
        lines = open(path).readlines()
        # Crash mid-append: truncate inside the last record, then
        # replay — the torn record drops, everything before applies.
        with open(path, "w") as f:
            f.writelines(lines[:-1])
            f.write(lines[-1][: len(lines[-1]) // 2])
        expect = RemediationController()
        fresh = RemediationController()
        assert fresh.replay_from(path) == len(lines) - 1
        # The reference: a controller that never saw the last record.
        ref_path = str(tmp_path / "ref.jsonl")
        with open(ref_path, "w") as f:
            f.writelines(lines[:-1])
        expect.replay_from(ref_path)
        assert fresh.fingerprint() == expect.fingerprint()

    def test_rotation_keeps_replay_identical(self, tmp_path):
        path = str(tmp_path / ACTIONS_JOURNAL)
        fp = self._scenario(path, rotate_bytes=256)
        assert os.path.exists(path + ".1")  # rotation actually happened
        fresh = RemediationController()
        fresh.replay_from(path)
        assert fresh.fingerprint() == fp

    def test_unverdicted_action_rearmed_at_original_due(self, tmp_path):
        path = str(tmp_path / ACTIONS_JOURNAL)
        pb, _ = _pb(cooldown=0.0, verify_after=5.0)
        ctl = RemediationController(playbooks=[pb], journal_path=path,
                                    fsync=False)
        ctl.tick(1.0, states=PAGE)          # verdict due at 6.0
        ctl.close()                         # process dies mid-window
        pb2, _ = _pb(cooldown=0.0, verify_after=5.0)
        fresh = RemediationController(playbooks=[pb2],
                                      journal_path=path, fsync=False)
        fresh.replay_from(path)
        assert fresh.snapshot()["pending"] == 1
        fresh.tick(3.0, states=OK)          # before due: still pending
        assert fresh.snapshot()["pending"] == 1
        fresh.tick(6.0, states=OK)          # at due: settles, paid
        snap = fresh.snapshot()
        assert snap["pending"] == 0
        assert snap["playbooks"]["pb"]["paid"] == 1
        fresh.close()
        recs = [json.loads(l) for l in open(path)]
        assert [r["op"] for r in recs] == ["action", "verdict"]


class TestFlightEvidence:
    def test_every_action_has_pre_and_post_dumps(self, tmp_path):
        reg = MetricsRegistry()
        tick = {"now": 0}
        rec = FlightRecorder(registry=reg, now_fn=lambda: tick["now"])
        pb, calls = _pb(cooldown=0.0, verify_after=1.0,
                        unpaid_disable_after=99)
        ctl = RemediationController(reg, playbooks=[pb], recorder=rec,
                                    dump_dir=str(tmp_path))
        t = 0.0
        for _ in range(5):
            t += 1.0
            tick["now"] = int(t)
            ctl.tick(t, states=PAGE)
        assert len(calls) >= 2
        pre = [p for p in rec.dumps if "remediate-pre-pb" in p]
        post = [p for p in rec.dumps if "remediate-post-pb" in p]
        assert len(pre) == len(calls)
        assert len(post) == len(calls)
        assert all(os.path.exists(p) for p in pre + post)


@pytest.mark.slow
class TestSoakIntegration:
    def test_armed_soak_with_worker_pool_leaks_nothing(self):
        """remediate=True under workers=4: the conftest leaked-thread
        fixture is the real assertion; here we require convergence and
        the every-action-verdicted invariant."""
        from kubeflow_tpu.chaos import run_soak

        rep = run_soak(num_jobs=4, seed=20260803, conflict_rate=0.3,
                       transient_rate=0.05, preempt_every=3,
                       fault_rounds=9, max_rounds=40, workers=4,
                       remediate=True)
        assert rep.converged
        snap = rep.remediation
        assert snap["pending"] == 0
        assert snap["paid"] + snap["unpaid"] == snap["actions"]
        assert snap["disabled"] == []
