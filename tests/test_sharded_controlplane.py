"""Horizontally sharded control plane (ISSUE 6): router contract, shard
processes, leader election, WAL crash-replay under SIGKILL, and the
cross-shard union fingerprint gate.

Every gate here is counts/fingerprints, never wall-clock — the same
discipline as the rest of the CI stages — so the tests cannot flake on a
loaded host. The fleets are tiny (shard processes are real OS processes).
"""

import pytest

from kubeflow_tpu.controlplane.benchmark import (
    run_controlplane_sweep,
    signature_of_rows,
    state_rows,
)
from kubeflow_tpu.controlplane.shard import (
    ShardedControlPlane,
    ShardRouter,
    fleet_docs,
    run_sharded_sweep,
)


class TestShardRouter:
    def test_deterministic_and_in_range(self):
        r = ShardRouter(5)
        for i in range(50):
            ns = f"ns-{i}"
            assert 0 <= r.route("TpuJob", ns) < 5
            assert r.route("TpuJob", ns) == ShardRouter(5).route("TpuJob", ns)

    def test_namespace_colocation_contract(self):
        """Everything a controller touches while reconciling a key lives
        in that key's namespace — so all kinds in one namespace MUST land
        on one shard (the router hashes the namespace alone)."""
        r = ShardRouter(4)
        for ns in ("team-a", "ns-00", "kubeflow-ci"):
            shards = {r.route(kind, ns)
                      for kind in ("TpuJob", "Pod", "Service", "Event")}
            assert len(shards) == 1, (ns, shards)

    def test_cluster_scoped_kinds_have_a_deterministic_home(self):
        r = ShardRouter(4)
        assert r.route("Profile", "") == r.route("Profile", "ignored-ns")
        assert 0 <= r.route("PlatformConfig", "") < 4

    def test_single_shard_short_circuits(self):
        assert ShardRouter(1).route("TpuJob", "anything") == 0

    def test_cluster_scoped_replicated_but_fingerprinted_once(self):
        """Cluster-scoped kinds live on EVERY shard (the lease holder's
        singleton controllers read them locally, wherever the lease
        lands) while the union fingerprint counts them once, at their
        home shard — so it still matches a serial world's."""
        from kubeflow_tpu.controlplane.runtime import InMemoryApiServer
        from kubeflow_tpu.controlplane.api import object_from_dict

        doc = {"kind": "PlatformConfig",
               "metadata": {"name": "platform"},
               "spec": {"components": []}}
        cp = ShardedControlPlane(3, seed=5)
        try:
            created = cp.create([doc])
            assert created == {0: 1, 1: 1, 2: 1}, created
            for info in cp.info().values():
                assert info["store_objects"] == 1, info
            counts, signature = cp.fingerprint()
        finally:
            cp.close()
        assert counts.get("PlatformConfig", {}).get("-", 0) == 1, counts
        serial = InMemoryApiServer()
        serial.create(object_from_dict(doc))
        assert (counts, signature) == \
            signature_of_rows(state_rows(serial.list_all()))

    def test_route_doc(self):
        r = ShardRouter(3)
        doc = {"kind": "TpuJob", "metadata": {"namespace": "ns-7",
                                              "name": "x"}}
        assert r.route_doc(doc) == r.route("TpuJob", "ns-7")

    def test_rejects_zero_shards(self):
        with pytest.raises(ValueError):
            ShardRouter(0)


class TestShardedSweep:
    def test_union_fingerprint_equals_serial_world(self):
        """The tentpole gate: N stores + N GILs must converge to the
        byte-identical world one store does (per-(kind, ns, name, phase)
        union signature)."""
        serial = run_controlplane_sweep(num_jobs=18, num_namespaces=6)
        sharded = run_sharded_sweep(num_jobs=18, num_namespaces=6,
                                    shards=2, workers=1)
        assert sharded.all_succeeded, sharded.final_state
        assert sharded.state_signature == serial.state_signature, (
            sharded.final_state, serial.final_state)
        # Work actually spread: more than one shard hosted jobs.
        assert len(sharded.jobs_per_shard) > 1, sharded.jobs_per_shard

    def test_rows_signature_is_order_independent(self):
        rows = [("TpuJob", "a", "x", "Succeeded"),
                ("Pod", "a", "y", "Running")]
        assert signature_of_rows(rows) == \
            signature_of_rows(list(reversed(rows)))

    def test_worker_pools_compose_with_shards(self):
        serial = run_controlplane_sweep(num_jobs=12, num_namespaces=4)
        sharded = run_sharded_sweep(num_jobs=12, num_namespaces=4,
                                    shards=2, workers=2)
        assert sharded.all_succeeded
        assert sharded.state_signature == serial.state_signature


class TestLeaderElectionAndCrashReplay:
    def test_kill_replay_election_cycle(self, tmp_path):
        """One flow, every claim: exactly one leader runs the singleton;
        a SIGKILLed shard replays its WAL byte-identically; the lease
        moves on leader death and is NOT stolen back on restart; the
        fleet still converges after the crash."""
        cp = ShardedControlPlane(3, state_dir=str(tmp_path), seed=13)
        try:
            assert cp.leader_id == 0 and cp.epoch == 1
            info = cp.info()
            leaders = [i for i, x in info.items() if x["leading"]]
            assert leaders == [0]
            assert "shard-singleton" in info[0]["controllers"]
            for i in (1, 2):
                assert "shard-singleton" not in info[i]["controllers"]

            cp.create(fleet_docs(9, 6))
            cp.round(30.0)

            victim = cp.leader_id
            pre = cp.shard_fingerprint(victim)
            cp.kill(victim)
            assert victim not in cp.alive()
            assert cp.leader_id == 1 and cp.epoch == 2
            info = cp.info()
            assert [i for i, x in info.items() if x["leading"]] == [1]
            assert "shard-singleton" in info[1]["controllers"]

            cp.restart(victim)
            # Byte-identical WAL replay (the crash-recovery hard gate)...
            assert cp.shard_fingerprint(victim) == pre
            info = cp.info()
            assert info[victim]["wal_replayed"] > 0
            # ... and the restarted ex-leader FOLLOWS (no lease theft).
            assert cp.leader_id == 1
            assert not info[victim]["leading"]

            for _ in range(10):
                res = cp.round(120.0)
                if all(r["terminal"] for r in res.values()):
                    break
            counts, _sig = cp.fingerprint()
            assert counts["TpuJob"].get("Succeeded") == 9, counts
        finally:
            cp.close()

    def test_sharded_soak_with_shard_kill(self):
        """The chaos integration: conflicts/transients + slice preemption
        inside every shard, one whole-shard SIGKILL mid-soak — converges
        all-Succeeded with a byte-identical replay."""
        from kubeflow_tpu.chaos import run_sharded_soak

        rep = run_sharded_soak(num_jobs=4, shards=2, seed=3,
                               kill_shard_round=4, fault_rounds=8,
                               max_rounds=40)
        assert rep.converged, rep.phases
        assert rep.all_succeeded, rep.phases
        assert rep.shard_kills == 1
        assert rep.replay_identical
        assert sum(rep.injected.values()) > 0     # chaos actually fired

    def test_sharded_soak_remediation_survives_shard_kill(self):
        """ISSUE 17: with per-shard remediation armed, the mid-soak
        SIGKILL must also replay actions.jsonl byte-identically — the
        action journal rides the same WAL-dir recovery as alerts."""
        from kubeflow_tpu.chaos import run_sharded_soak

        rep = run_sharded_soak(num_jobs=4, shards=2, seed=3,
                               kill_shard_round=4, fault_rounds=8,
                               max_rounds=40, remediate=True)
        assert rep.converged, rep.phases
        assert rep.shard_kills == 1
        assert rep.actions_replay_identical
        assert rep.alerts_replay_identical
        assert rep.remediation["actions_total"] >= 1
        assert rep.remediation["pending"] == 0
        assert rep.remediation["disabled"] == []

    def test_ci_shard_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_shard_smoke

        run_shard_smoke(seed=20260803)

    def test_ci_cp_bench_smoke_sharded_leg_detects_divergence(self, monkeypatch):
        from kubeflow_tpu.tools import ci
        from kubeflow_tpu.controlplane import shard as shard_mod
        from kubeflow_tpu.tools.ci import GateFailure

        real = shard_mod.run_sharded_sweep

        def diverging(**kw):
            rep = real(**kw)
            rep.state_signature = "deadbeef"
            return rep

        monkeypatch.setattr(
            "kubeflow_tpu.controlplane.shard.run_sharded_sweep", diverging)
        with pytest.raises(GateFailure, match="union fingerprint"):
            ci.run_cp_bench_smoke(num_jobs=8, num_namespaces=4,
                                  workers=1, shards=2)


class TestCrossShardAdmissionLedger:
    """ISSUE 8 satellite (PR-6 follow-up): slice-capacity reservations
    route through the lease-holding shard's LedgerService, so two shards
    can no longer double-admit against the same capacity map."""

    @staticmethod
    def _split_namespaces(router):
        ns_by_shard = {}
        for i in range(64):
            ns = f"ns-{i:02d}"
            ns_by_shard.setdefault(router.route("TpuJob", ns), ns)
            if len(ns_by_shard) == 2:
                return ns_by_shard
        raise AssertionError("no namespace split found")

    def _docs(self, router):
        ns_by_shard = self._split_namespaces(router)
        return [
            {"kind": "TpuJob",
             "metadata": {"name": f"job-{shard}", "namespace": ns},
             "spec": {"sliceType": "v5e-16", "mesh": {"dp": -1},
                      "backoffSeconds": 0.0}}
            for shard, ns in sorted(ns_by_shard.items())
        ]

    def test_two_shard_race_cannot_double_admit(self):
        """Two jobs on DIFFERENT shards racing for ONE global slice:
        without the ledger each shard's local view would admit both; the
        leader's ledger serializes them — at most one gang in an in-use
        phase after any round, and both still complete (sequentially)."""
        cp = ShardedControlPlane(2, work_ticks=3,
                                 global_capacity={"v5e-16": 1})
        try:
            cp.create(self._docs(cp.router))
            in_use_phases = ("Scheduling", "Starting", "Running",
                            "Restarting")
            max_in_use = 0
            phases = {}
            for _ in range(20):
                res = cp.round(2.0, kick=10.0)
                counts, _sig = cp.fingerprint()
                phases = counts.get("TpuJob", {})
                max_in_use = max(max_in_use, sum(
                    v for p, v in phases.items() if p in in_use_phases))
                if all(r["terminal"] for r in res.values()):
                    break
            assert max_in_use <= 1, (
                f"double-admit: {max_in_use} gangs held the single "
                f"v5e-16 slice concurrently")
            assert phases.get("Succeeded", 0) == 2
            # All reservations returned once both gangs finished.
            snap = cp.ledger_snapshot()
            assert snap is not None and snap["reservations"] == 0
        finally:
            cp.close()

    def test_leader_failover_keeps_ledger_state(self, tmp_path):
        """Kill the lease holder mid-flight: the new leader replays the
        ledger journal, so reservations survive the failover and the
        blocked gang still parks (fail-closed) until capacity frees."""
        cp = ShardedControlPlane(2, work_ticks=4,
                                 global_capacity={"v5e-16": 1},
                                 state_dir=str(tmp_path))
        try:
            cp.create(self._docs(cp.router))
            cp.round(2.0, kick=10.0)
            counts, _sig = cp.fingerprint()
            in_use = sum(v for p, v in counts.get("TpuJob", {}).items()
                         if p in ("Scheduling", "Starting", "Running"))
            assert in_use == 1
            leader = cp.leader_id
            cp.kill(leader)
            cp.restart(leader)
            # The NEW leader (the survivor) serves a replayed ledger:
            # exactly the pre-crash reservation, not an empty map.
            snap = cp.ledger_snapshot()
            assert snap is not None
            assert snap["in_use"] == {"v5e-16": 1}
            for _ in range(24):
                res = cp.round(2.0, kick=10.0)
                if all(r["terminal"] for r in res.values()):
                    break
            counts, _sig = cp.fingerprint()
            assert counts.get("TpuJob", {}).get("Succeeded", 0) == 2
        finally:
            cp.close()
