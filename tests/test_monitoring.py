import pytest

from kubeflow_tpu.utils.monitoring import MetricsRegistry


class TestMetricsRegistry:
    def test_counter_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_test_total", "test", labels=("severity",))
        c.inc(severity="error")
        c.inc(2, severity="error")
        assert c.value(severity="error") == 3
        assert c.value(severity="warn") == 0

    def test_counter_label_typo_raises(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_test_total", "test", labels=("severity",))
        with pytest.raises(ValueError):
            c.inc(serverity="error")

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_err_total", "t", labels=("reason",))
        c.inc(reason='got "EOF"\nunexpected\\')
        out = reg.render()
        assert 'reason="got \\"EOF\\"\\nunexpected\\\\"' in out
        assert "\n# TYPE" in out  # no raw newline inside a sample line

    def test_duplicate_name_dedup(self):
        reg = MetricsRegistry()
        a = reg.counter("kftpu_x_total", "t")
        b = reg.counter("kftpu_x_total", "t")
        assert a is b
        assert reg.render().count("# TYPE kftpu_x_total") == 1

    def test_duplicate_name_kind_conflict(self):
        reg = MetricsRegistry()
        reg.counter("kftpu_x", "t")
        with pytest.raises(ValueError):
            reg.gauge("kftpu_x", "t")

    def test_heartbeat_staleness_detectable(self):
        reg = MetricsRegistry()
        hb = reg.heartbeat("testctl")
        assert hb.last() == 0.0  # never beat → stale is visible
        hb.beat()
        t1 = hb.last()
        assert t1 > 0
        # A scrape without an intervening beat returns the same stamp.
        assert hb.last() == t1

    def test_callback_gauge_set_rejected(self):
        reg = MetricsRegistry()
        g = reg.gauge("kftpu_now", "t", fn=lambda: 42.0)
        assert g.value() == 42.0
        with pytest.raises(ValueError):
            g.set(1.0)


class TestHistogram:
    """ISSUE 4 satellite: exposition-format contract for the new
    Histogram — bucket cumulativity, +Inf == _count, label escaping."""

    def _hist(self):
        reg = MetricsRegistry()
        h = reg.histogram("kftpu_lat_seconds", "t", labels=("verb",),
                          buckets=(0.01, 0.1, 1.0))
        return reg, h

    def test_buckets_are_cumulative(self):
        reg, h = self._hist()
        for v in (0.005, 0.05, 0.05, 0.5, 5.0):
            h.observe(v, verb="get")
        from kubeflow_tpu.utils.monitoring import parse_exposition

        samples = {
            (name, labels.get("le")): v
            for name, labels, v in parse_exposition(reg.render())
            if labels.get("verb") == "get" or "verb" in labels
        }
        assert samples[("kftpu_lat_seconds_bucket", "0.01")] == 1
        assert samples[("kftpu_lat_seconds_bucket", "0.1")] == 3
        assert samples[("kftpu_lat_seconds_bucket", "1")] == 4
        assert samples[("kftpu_lat_seconds_bucket", "+Inf")] == 5

    def test_inf_bucket_equals_count(self):
        reg, h = self._hist()
        for v in (0.02, 0.2, 2.0, 20.0):
            h.observe(v, verb="list")
        text = reg.render()
        from kubeflow_tpu.utils.monitoring import parse_exposition

        samples = parse_exposition(text)
        inf = [v for n, l, v in samples
               if n == "kftpu_lat_seconds_bucket" and l.get("le") == "+Inf"]
        count = [v for n, l, v in samples
                 if n == "kftpu_lat_seconds_count"]
        assert inf == count == [4]
        total = [v for n, l, v in samples if n == "kftpu_lat_seconds_sum"]
        assert total[0] == pytest.approx(22.22)

    def test_boundary_value_lands_in_its_bucket(self):
        """Prometheus buckets are le (<=): an observation exactly at a
        bound counts in that bound's bucket."""
        reg, h = self._hist()
        h.observe(0.1, verb="get")
        assert "kftpu_lat_seconds_bucket" in reg.render()
        from kubeflow_tpu.utils.monitoring import parse_exposition

        s = {l.get("le"): v
             for n, l, v in parse_exposition(reg.render())
             if n == "kftpu_lat_seconds_bucket"}
        assert s["0.1"] == 1
        assert s["0.01"] == 0

    def test_label_escaping_in_histogram_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("kftpu_h", "t", labels=("op",), buckets=(1.0,))
        h.observe(0.5, op='x "quoted"\nnl\\')
        out = reg.render()
        assert 'op="x \\"quoted\\"\\nnl\\\\"' in out
        # Parses back to the original value.
        from kubeflow_tpu.utils.monitoring import parse_exposition

        names = [l["op"] for n, l, v in parse_exposition(out) if "op" in l]
        assert names[0] == 'x "quoted"\nnl\\'

    def test_unescape_is_single_pass(self):
        """Regression: sequential str.replace corrupted a literal
        backslash followed by 'n' (r'C:\\new' -> backslash+newline)."""
        from kubeflow_tpu.utils.monitoring import parse_exposition

        reg = MetricsRegistry()
        c = reg.counter("kftpu_p_total", "t", labels=("path",))
        for v in ("C:\\new", "a\\\\nb", "\\n", "end\\"):
            c.inc(path=v)
        parsed = {l["path"] for n, l, v in parse_exposition(reg.render())
                  if "path" in l}
        assert parsed == {"C:\\new", "a\\\\nb", "\\n", "end\\"}

    def test_label_typo_raises(self):
        _, h = self._hist()
        with pytest.raises(ValueError):
            h.observe(0.1, verv="get")

    def test_nonfinite_buckets_rejected(self):
        from kubeflow_tpu.utils.monitoring import Histogram

        with pytest.raises(ValueError):
            Histogram("kftpu_bad", "t", buckets=(0.1, float("inf")))

    def test_quantile_interpolation(self):
        reg, h = self._hist()
        # 10 obs uniformly in (0, 0.01]: p50 interpolates inside bucket 1.
        for _ in range(10):
            h.observe(0.005, verb="get")
        assert 0 < h.quantile(0.5, verb="get") <= 0.01
        # All mass beyond the last finite bound clamps to it.
        reg2 = MetricsRegistry()
        h2 = reg2.histogram("kftpu_q", "t", buckets=(0.01, 0.1))
        for _ in range(5):
            h2.observe(99.0)
        assert h2.quantile(0.99) == 0.1
        assert reg2.get("kftpu_q") is h2

    def test_quantile_aggregates_label_subsets(self):
        reg, h = self._hist()
        for _ in range(9):
            h.observe(0.005, verb="get")
        h.observe(0.5, verb="list")
        # Per-label view vs whole-family view differ.
        assert h.quantile(0.9, verb="get") <= 0.01
        assert h.quantile(0.99) > 0.1
        assert h.percentiles(verb="get")["p50"] <= 0.01

    def test_empty_quantile_is_none(self):
        _, h = self._hist()
        assert h.quantile(0.5) is None
        assert h.percentiles() == {}


class TestSnapshotRegression:
    """ISSUE 4 satellite: snapshot() used to silently skip Heartbeat
    metrics (samplers missed stale-heartbeat detection) and could never
    carry labeled gauges; now every registered metric contributes."""

    def test_snapshot_includes_heartbeat(self):
        reg = MetricsRegistry()
        hb = reg.heartbeat("testctl")
        hb.beat()
        names = {name for name, _, _ in reg.snapshot()}
        assert "kftpu_testctl_heartbeat" in names
        val = [v for name, _, v in reg.snapshot()
               if name == "kftpu_testctl_heartbeat"]
        assert val[0] == hb.last() > 0

    def test_snapshot_includes_labeled_gauge(self):
        reg = MetricsRegistry()
        g = reg.gauge("kftpu_shard_depth", "t", labels=("shard",))
        g.set(3.0, shard="0")
        g.set(7.0, shard="1")
        samples = {labels: v for name, labels, v in reg.snapshot()
                   if name == "kftpu_shard_depth"}
        assert samples[(("shard", "0"),)] == 3.0
        assert samples[(("shard", "1"),)] == 7.0
        assert 'shard="1"' in reg.render()

    def test_snapshot_includes_histogram_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("kftpu_lat", "t", buckets=(0.1,))
        h.observe(0.05)
        names = {name for name, _, _ in reg.snapshot()}
        assert {"kftpu_lat_bucket", "kftpu_lat_sum",
                "kftpu_lat_count"} <= names

    def test_snapshot_still_covers_counters_and_gauges(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_c_total", "t", labels=("r",))
        c.inc(r="ok")
        reg.gauge("kftpu_g", "t", fn=lambda: 4.0)
        got = {(name, labels): v for name, labels, v in reg.snapshot()}
        assert got[("kftpu_c_total", (("r", "ok"),))] == 1.0
        assert got[("kftpu_g", ())] == 4.0

    def test_callback_gauge_cannot_take_labels(self):
        from kubeflow_tpu.utils.monitoring import Gauge

        with pytest.raises(ValueError):
            Gauge("kftpu_x", "t", fn=lambda: 1.0, label_names=("a",))

    def test_metric_name_sanitized(self):
        from kubeflow_tpu.utils.monitoring import sanitize_metric_name

        assert sanitize_metric_name("fake-kubelet") == "fake_kubelet"
        reg = MetricsRegistry()
        hb = reg.heartbeat("fake-kubelet")
        assert hb.name == "kftpu_fake_kubelet_heartbeat"


class TestValueFormatting:
    def test_timestamp_full_precision(self):
        from kubeflow_tpu.utils.monitoring import _fmt_value

        assert _fmt_value(1774000000.5) == "1774000000.5"
        assert _fmt_value(1234567.0) == "1234567"
        assert _fmt_value(0.25) == "0.25"

    def test_nonfinite_values_render(self):
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        reg = MetricsRegistry()
        g = reg.gauge("kftpu_bad", "t")
        g.set(float("inf"))
        assert "kftpu_bad +Inf" in reg.render()
        g.set(float("nan"))
        assert "kftpu_bad NaN" in reg.render()


class TestThreadSafety:
    """ISSUE 5 satellite: the reconcile worker pool observes histograms
    and bumps labeled counters from N threads at once — no update may be
    lost and the cumulative-bucket invariants must hold."""

    def test_histogram_observe_under_concurrent_observers(self):
        import threading

        from kubeflow_tpu.utils.monitoring import Histogram

        h = Histogram("kftpu_t", "t", label_names=("controller",),
                      buckets=(0.001, 0.01, 0.1, 1.0))
        per_thread, threads = 2000, 8

        def observe(i):
            for j in range(per_thread):
                h.observe((j % 7) * 0.005, controller=f"c{i % 2}")

        ts = [threading.Thread(target=observe, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        total = threads * per_thread
        assert h.count(controller="c0") + h.count(controller="c1") == total
        # +Inf bucket == _count for every labelset (cumulative invariant).
        for name, labels, v in h.samples():
            if name.endswith("_bucket") and dict(labels)["le"] == "+Inf":
                assert v == total / 2

    def test_labeled_counter_under_concurrent_incrementers(self):
        import threading

        reg = MetricsRegistry()
        c = reg.counter("kftpu_tc", "t", labels=("result",))
        per_thread, threads = 5000, 8

        def inc(i):
            for _ in range(per_thread):
                c.inc(result="ok" if i % 2 else "err")

        ts = [threading.Thread(target=inc, args=(i,)) for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value(result="ok") == threads // 2 * per_thread
        assert c.value(result="err") == threads // 2 * per_thread
