import pytest

from kubeflow_tpu.utils.monitoring import MetricsRegistry


class TestMetricsRegistry:
    def test_counter_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_test_total", "test", labels=("severity",))
        c.inc(severity="error")
        c.inc(2, severity="error")
        assert c.value(severity="error") == 3
        assert c.value(severity="warn") == 0

    def test_counter_label_typo_raises(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_test_total", "test", labels=("severity",))
        with pytest.raises(ValueError):
            c.inc(serverity="error")

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("kftpu_err_total", "t", labels=("reason",))
        c.inc(reason='got "EOF"\nunexpected\\')
        out = reg.render()
        assert 'reason="got \\"EOF\\"\\nunexpected\\\\"' in out
        assert "\n# TYPE" in out  # no raw newline inside a sample line

    def test_duplicate_name_dedup(self):
        reg = MetricsRegistry()
        a = reg.counter("kftpu_x_total", "t")
        b = reg.counter("kftpu_x_total", "t")
        assert a is b
        assert reg.render().count("# TYPE kftpu_x_total") == 1

    def test_duplicate_name_kind_conflict(self):
        reg = MetricsRegistry()
        reg.counter("kftpu_x", "t")
        with pytest.raises(ValueError):
            reg.gauge("kftpu_x", "t")

    def test_heartbeat_staleness_detectable(self):
        reg = MetricsRegistry()
        hb = reg.heartbeat("testctl")
        assert hb.last() == 0.0  # never beat → stale is visible
        hb.beat()
        t1 = hb.last()
        assert t1 > 0
        # A scrape without an intervening beat returns the same stamp.
        assert hb.last() == t1

    def test_callback_gauge_set_rejected(self):
        reg = MetricsRegistry()
        g = reg.gauge("kftpu_now", "t", fn=lambda: 42.0)
        assert g.value() == 42.0
        with pytest.raises(ValueError):
            g.set(1.0)


class TestValueFormatting:
    def test_timestamp_full_precision(self):
        from kubeflow_tpu.utils.monitoring import _fmt_value

        assert _fmt_value(1774000000.5) == "1774000000.5"
        assert _fmt_value(1234567.0) == "1234567"
        assert _fmt_value(0.25) == "0.25"

    def test_nonfinite_values_render(self):
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        reg = MetricsRegistry()
        g = reg.gauge("kftpu_bad", "t")
        g.set(float("inf"))
        assert "kftpu_bad +Inf" in reg.render()
        g.set(float("nan"))
        assert "kftpu_bad NaN" in reg.render()
