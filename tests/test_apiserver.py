import pytest

from kubeflow_tpu.controlplane.api import (
    Namespace,
    Notebook,
    NotebookSpec,
    ObjectMeta,
    Pod,
    TpuJob,
    TpuJobSpec,
    from_dict,
    object_from_dict,
    to_dict,
)
from kubeflow_tpu.controlplane.api.meta import OwnerReference
from kubeflow_tpu.controlplane.runtime import (
    ConflictError,
    InMemoryApiServer,
    NotFoundError,
)
from kubeflow_tpu.controlplane.runtime.apiserver import AlreadyExistsError


def _job(name="train", ns="user1"):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny"),
    )


class TestSerde:
    def test_roundtrip_camel_case(self):
        job = _job()
        d = to_dict(job)
        assert d["apiVersion"] == "tpu.kubeflow.org/v1alpha1"
        assert d["spec"]["sliceType"] == "v5e-16"
        assert d["spec"]["maxRestarts"] == 3
        back = from_dict(TpuJob, d)
        assert back.spec.slice_type == "v5e-16"
        assert back.metadata.name == "train"

    def test_object_from_dict_dispatch(self):
        nb = object_from_dict(
            {"kind": "Notebook", "metadata": {"name": "n", "namespace": "u"},
             "spec": {"tpuSlice": "v5e-8"}}
        )
        assert isinstance(nb, Notebook)
        assert nb.spec.tpu_slice == "v5e-8"

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError):
            object_from_dict({"kind": "Widget"})

    def test_unknown_keys_ignored(self):
        j = from_dict(TpuJob, {"spec": {"sliceType": "v5e-4", "bogus": 1}})
        assert j.spec.slice_type == "v5e-4"


class TestApiServerCrud:
    def test_create_get_list(self):
        api = InMemoryApiServer()
        api.create(_job("a"))
        api.create(_job("b"))
        api.create(_job("a", ns="user2"))
        assert api.get("TpuJob", "a", "user1").metadata.uid
        assert len(api.list("TpuJob", namespace="user1")) == 2
        assert len(api.list("TpuJob")) == 3

    def test_create_requires_namespace(self):
        api = InMemoryApiServer()
        with pytest.raises(Exception):
            api.create(_job("x", ns=""))

    def test_duplicate_create_raises(self):
        api = InMemoryApiServer()
        api.create(_job())
        with pytest.raises(AlreadyExistsError):
            api.create(_job())

    def test_optimistic_concurrency(self):
        api = InMemoryApiServer()
        api.create(_job())
        a = api.get("TpuJob", "train", "user1")
        b = api.get("TpuJob", "train", "user1")
        a.spec.max_restarts = 5
        api.update(a)
        b.spec.max_restarts = 7
        with pytest.raises(ConflictError):
            api.update(b)

    def test_generation_bumps_on_spec_change_only(self):
        api = InMemoryApiServer()
        api.create(_job())
        j = api.get("TpuJob", "train", "user1")
        j.status.phase = "Running"
        j = api.update(j)
        assert j.metadata.generation == 1
        j.spec.max_restarts = 9
        j = api.update(j)
        assert j.metadata.generation == 2

    def test_update_status_does_not_clobber_spec(self):
        api = InMemoryApiServer()
        api.create(_job())
        stale = api.get("TpuJob", "train", "user1")
        fresh = api.get("TpuJob", "train", "user1")
        fresh.spec.max_restarts = 11
        api.update(fresh)
        stale.status.phase = "Running"
        out = api.update_status(stale)
        assert out.spec.max_restarts == 11
        assert out.status.phase == "Running"

    def test_label_selector(self):
        api = InMemoryApiServer()
        j = _job("a")
        j.metadata.labels = {"team": "x"}
        api.create(j)
        api.create(_job("b"))
        assert [o.metadata.name for o in
                api.list("TpuJob", label_selector={"team": "x"})] == ["a"]

    def test_store_isolation(self):
        """Mutating a returned object must not corrupt the store."""
        api = InMemoryApiServer()
        api.create(_job())
        j = api.get("TpuJob", "train", "user1")
        j.spec.slice_type = "HACKED"
        assert api.get("TpuJob", "train", "user1").spec.slice_type == "v5e-16"


class TestLifecycle:
    def test_finalizer_blocks_deletion(self):
        api = InMemoryApiServer()
        j = _job()
        j.metadata.finalizers = ["tpu.kubeflow.org/teardown"]
        api.create(j)
        api.delete("TpuJob", "train", "user1")
        live = api.get("TpuJob", "train", "user1")
        assert live.metadata.deletion_timestamp is not None
        live.metadata.finalizers = []
        api.update(live)
        with pytest.raises(NotFoundError):
            api.get("TpuJob", "train", "user1")

    def test_owner_cascade(self):
        api = InMemoryApiServer()
        job = api.create(_job())
        pod = Pod(metadata=ObjectMeta(
            name="train-worker-0", namespace="user1",
            owner_references=[OwnerReference(
                kind="TpuJob", name="train", uid=job.metadata.uid)],
        ))
        api.create(pod)
        api.delete("TpuJob", "train", "user1")
        with pytest.raises(NotFoundError):
            api.get("Pod", "train-worker-0", "user1")

    def test_watch_sees_lifecycle(self):
        api = InMemoryApiServer()
        q = api.watch("TpuJob")
        api.create(_job())
        j = api.get("TpuJob", "train", "user1")
        j.status.phase = "Running"
        api.update(j)
        api.delete("TpuJob", "train", "user1")
        events = []
        while not q.empty():
            events.append(q.get().type)
        assert events == ["ADDED", "MODIFIED", "DELETED"]

    def test_watch_replays_existing(self):
        api = InMemoryApiServer()
        api.create(_job())
        q = api.watch("TpuJob")
        assert q.get_nowait().type == "ADDED"

    def test_admission_mutator_runs_on_create(self):
        api = InMemoryApiServer()

        def add_label(obj):
            if obj.kind == "Pod":
                obj.metadata.labels["mutated"] = "yes"
            return obj

        api.register_mutator(add_label)
        api.create(Pod(metadata=ObjectMeta(name="p", namespace="u")))
        assert api.get("Pod", "p", "u").metadata.labels["mutated"] == "yes"
        api.create(_job())
        assert "mutated" not in api.get("TpuJob", "train", "user1").metadata.labels
