"""Paginated list semantics (ISSUE 6): snapshot-pinned continue tokens.

The contract under test: a ``limit``/``continue_`` walk enumerates
EXACTLY the unpaginated list as of the walk's first page — same objects,
same order — no matter what writes land mid-walk. Plus the failure
modes: evicted snapshots raise ContinueExpiredError (410 Gone), and
copy counting stays O(page).
"""

import random

import pytest

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import TpuJob, TpuJobSpec
from kubeflow_tpu.controlplane.runtime import (
    ApiError,
    ContinueExpiredError,
    InMemoryApiServer,
    ListPage,
)


def _job(name, ns="ns", labels=None):
    return TpuJob(
        metadata=ObjectMeta(name=name, namespace=ns,
                            labels=dict(labels or {})),
        spec=TpuJobSpec(slice_type="v5e-16"),
    )


def _walk(api, limit, **query):
    """Full paginated walk; returns (items, resource_version)."""
    page = api.list("TpuJob", limit=limit, **query)
    assert isinstance(page, ListPage)
    items, rv = list(page.items), page.resource_version
    while page.continue_:
        page = api.list("TpuJob", limit=limit, continue_=page.continue_,
                        **query)
        assert page.resource_version == rv
        items.extend(page.items)
    return items, rv


class TestPagination:
    def test_every_limit_enumerates_the_unpaginated_list(self):
        api = InMemoryApiServer()
        for i in range(23):
            api.create(_job(f"j{i:02d}", ns=f"ns-{i % 3}"))
        full = [o.metadata.name for o in api.list("TpuJob", copy=False)]
        for limit in (1, 2, 3, 7, 22, 23, 100):
            items, _ = _walk(api, limit)
            assert [o.metadata.name for o in items] == full, limit

    def test_walk_is_snapshot_consistent_under_concurrent_writes(self):
        """Property test: random creates/deletes/updates land between
        pages; the walk must still enumerate exactly the list captured at
        its first page (the paginate-at-one-revision contract)."""
        rng = random.Random(0)
        for trial in range(5):
            api = InMemoryApiServer()
            names = [f"j{i:02d}" for i in range(rng.randrange(5, 30))]
            for n in names:
                api.create(_job(n))
            frozen = [o.metadata.name for o in api.list("TpuJob",
                                                        copy=False)]
            limit = rng.randrange(1, 6)
            page = api.list("TpuJob", limit=limit)
            items = list(page.items)
            extra = 0
            while page.continue_:
                # Chaos between pages: create, delete, update.
                op = rng.random()
                if op < 0.4:
                    api.create(_job(f"mid-{trial}-{extra}"))
                    extra += 1
                elif op < 0.7 and names:
                    victim = names.pop(rng.randrange(len(names)))
                    api.delete("TpuJob", victim, "ns")
                elif names:
                    obj = api.get("TpuJob", rng.choice(names), "ns")
                    obj.spec.max_restarts += 1
                    api.update(obj)
                page = api.list("TpuJob", limit=limit,
                                continue_=page.continue_)
                items.extend(page.items)
            assert [o.metadata.name for o in items] == frozen

    def test_completed_walk_frees_its_snapshot(self):
        api = InMemoryApiServer()
        for i in range(6):
            api.create(_job(f"j{i}"))
        _walk(api, 2)
        assert not api._page_snapshots

    def test_evicted_snapshot_raises_continue_expired(self):
        api = InMemoryApiServer()
        for i in range(4):
            api.create(_job(f"j{i}"))
        page = api.list("TpuJob", limit=1)
        stale = page.continue_
        # Open (and abandon) enough concurrent walks to evict the first.
        for _ in range(InMemoryApiServer.MAX_PAGE_SNAPSHOTS + 1):
            api.list("TpuJob", limit=1)
        with pytest.raises(ContinueExpiredError):
            api.list("TpuJob", limit=1, continue_=stale)

    def test_malformed_token_raises_api_error(self):
        api = InMemoryApiServer()
        api.create(_job("j0"))
        with pytest.raises(ApiError):
            api.list("TpuJob", limit=1, continue_="not-a-token")
        with pytest.raises(ApiError):
            api.list("TpuJob", limit=0)

    def test_nonpositive_limit_rejected_mid_walk(self):
        """limit is validated on EVERY page: a continuation with
        limit<=0 would return an empty page whose token never advances,
        spinning a standard `while page.continue_` walk forever."""
        api = InMemoryApiServer()
        for i in range(4):
            api.create(_job(f"j{i}"))
        page = api.list("TpuJob", limit=2)
        assert page.continue_
        for bad in (0, -1):
            with pytest.raises(ApiError):
                api.list("TpuJob", limit=bad, continue_=page.continue_)
        # The walk itself is unharmed — and continuing WITHOUT a limit
        # drains the rest of the pinned snapshot in one page.
        rest = api.list("TpuJob", continue_=page.continue_)
        assert [o.metadata.name for o in page.items + rest.items] == \
            [f"j{i}" for i in range(4)]
        assert rest.continue_ == ""

    def test_copy_count_is_per_page(self):
        """The O(matches) discipline extends to pages: each page deepcopies
        exactly the objects it returns; copy=False pages copy nothing."""
        api = InMemoryApiServer()
        for i in range(10):
            api.create(_job(f"j{i}"))
        api.copied = {}
        page = api.list("TpuJob", limit=4)
        assert api.copied.get("list", 0) == 4
        api.list("TpuJob", limit=4, continue_=page.continue_)
        assert api.copied.get("list", 0) == 8
        api.copied = {}
        zero = api.list("TpuJob", limit=4, copy=False)
        assert api.copied.get("list", 0) == 0
        # Zero-copy pages ARE the stored snapshots.
        assert zero.items[0] is api.get("TpuJob", "j0", "ns", copy=False)

    def test_label_selector_pins_with_the_snapshot(self):
        api = InMemoryApiServer()
        for i in range(8):
            api.create(_job(f"j{i}", labels={"team": "x" if i % 2 else "y"}))
        want = [o.metadata.name
                for o in api.list("TpuJob", label_selector={"team": "x"},
                                  copy=False)]
        page = api.list("TpuJob", label_selector={"team": "x"}, limit=2)
        items = list(page.items)
        api.create(_job("late", labels={"team": "x"}))
        while page.continue_:
            page = api.list("TpuJob", limit=2, continue_=page.continue_)
            items.extend(page.items)
        assert [o.metadata.name for o in items] == want

    def test_chaos_proxy_passes_pagination_through(self):
        from kubeflow_tpu.chaos.api import ChaosApiServer

        api = InMemoryApiServer()
        for i in range(5):
            api.create(_job(f"j{i}"))
        chaos = ChaosApiServer(api, seed=0)
        items, _ = _walk(chaos, 2)
        assert len(items) == 5
