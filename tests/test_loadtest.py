"""Control-plane load test (tools/loadtest.py) — the reference's
notebook-controller/loadtest/ run in-process and pinned in CI.

Asserts convergence (every object reaches steady state under bulk load)
and that reconcile work doesn't blow up super-linearly with store size —
timing asserts are deliberately loose (CI machines vary); the load
numbers themselves are reported by the tool, not pinned here.
"""

from kubeflow_tpu.tools.loadtest import run_load


class TestControlPlaneLoad:
    def test_bulk_load_converges(self):
        out = run_load(notebooks=150, jobs=30, profiles=6)
        assert out["notebooks_not_ready"] == 0
        assert out["jobs_not_running"] == 0
        assert out["objects"] == 186
        # Floor, not a benchmark: catches accidental O(n^2) reconcile
        # regressions (a livelocked drain would also trip max_iterations).
        assert out["objects_per_sec"] > 20

    def test_reconcile_loops_scale_linearly(self):
        small = run_load(notebooks=50, jobs=10, profiles=5)
        large = run_load(notebooks=200, jobs=40, profiles=5)
        ratio = large["reconcile_loops"] / max(1, small["reconcile_loops"])
        objects_ratio = large["objects"] / small["objects"]
        # Loops per object must stay roughly constant: allow 3x headroom
        # over linear before calling it a regression.
        assert ratio < 3 * objects_ratio, (small, large)


class TestServingLbLoad:
    def test_lb_sustains_concurrent_load_and_spreads(self):
        """The L7 balancer under 8 concurrent clients: no errors, sane
        throughput floor (conservative: in-process stubs serve thousands
        of req/s), and load actually spreads across backends — a wedged
        least-loaded picker would pin everything to one."""
        from kubeflow_tpu.tools.loadtest import run_serving_lb_load

        out = run_serving_lb_load(backends=2, clients=8, requests=240)
        assert out["lb_errors"] == 0
        assert out["lb_requests_per_sec"] > 50       # floor, not a bench
        spread = out["lb_backend_spread"]
        assert sum(spread) == out["lb_requests"]
        assert min(spread) > 0                       # both backends worked
