"""Control-plane load test (tools/loadtest.py) — the reference's
notebook-controller/loadtest/ run in-process and pinned in CI.

Asserts convergence (every object reaches steady state under bulk load)
and that reconcile work doesn't blow up super-linearly with store size —
timing asserts are deliberately loose (CI machines vary); the load
numbers themselves are reported by the tool, not pinned here.

The ISSUE-12 token-model benches (continuous batching / affinity A/B)
carry real sleeps, so their integration tests are ``slow``-marked —
tier-1 keeps the count-based CI smoke stages instead.
"""

import pytest

from kubeflow_tpu.tools.loadtest import run_load


class TestControlPlaneLoad:
    def test_bulk_load_converges(self):
        out = run_load(notebooks=150, jobs=30, profiles=6)
        assert out["notebooks_not_ready"] == 0
        assert out["jobs_not_running"] == 0
        assert out["objects"] == 186
        # Floor, not a benchmark: catches accidental O(n^2) reconcile
        # regressions (a livelocked drain would also trip max_iterations).
        assert out["objects_per_sec"] > 20

    def test_reconcile_loops_scale_linearly(self):
        small = run_load(notebooks=50, jobs=10, profiles=5)
        large = run_load(notebooks=200, jobs=40, profiles=5)
        ratio = large["reconcile_loops"] / max(1, small["reconcile_loops"])
        objects_ratio = large["objects"] / small["objects"]
        # Loops per object must stay roughly constant: allow 3x headroom
        # over linear before calling it a regression.
        assert ratio < 3 * objects_ratio, (small, large)


class TestServingLbLoad:
    def test_lb_sustains_concurrent_load_and_spreads(self):
        """The L7 balancer under 8 concurrent clients: no errors, sane
        throughput floor (conservative: in-process stubs serve thousands
        of req/s), and load actually spreads across backends — a wedged
        least-loaded picker would pin everything to one."""
        from kubeflow_tpu.tools.loadtest import run_serving_lb_load

        out = run_serving_lb_load(backends=2, clients=8, requests=240)
        assert out["lb_errors"] == 0
        assert out["lb_requests_per_sec"] > 50       # floor, not a bench
        spread = out["lb_backend_spread"]
        assert sum(spread) == out["lb_requests"]
        assert min(spread) > 0                       # both backends worked


class TestServeBench:
    """Open-loop serving bench (ISSUE 7): fixed-arrival-rate traffic
    through the real LB over SimServingReplica backends. Counts are the
    contract — every request lands in exactly one outcome bucket; rates
    and latencies are reported, not pinned (CI machines vary)."""

    def test_shed_run_accounts_every_request(self):
        from kubeflow_tpu.tools.loadtest import run_serve_bench

        out = run_serve_bench(
            rate_qps=60.0, duration_s=1.0, replicas=1, max_batch=2,
            max_queue=4, service_time_s=0.05, shed=True, autoscale=False,
            client_timeout_s=3.0)
        assert out["accounting_ok"], out
        assert out["offered"] == 60
        # 1.5x overload: the excess MUST shed, the rest MUST succeed
        assert out["ok"] > 0 and out["shed"] > 0
        assert out["shed_with_retry_after"] == out["shed"]
        assert out["timeouts"] == 0 and out["errors"] == 0
        # sheds split between engine 429s and LB watermark 503s; together
        # they are exactly the client-visible shed count
        assert out["engine_shed"] + out["lb_shed"] == out["shed"]
        assert out["served_by_backends"] == out["ok"]

    def test_autoscale_run_reaches_max_replicas(self):
        from kubeflow_tpu.tools.loadtest import run_serve_bench

        out = run_serve_bench(
            rate_qps=80.0, duration_s=1.5, replicas=1, max_replicas=2,
            max_batch=2, max_queue=4, service_time_s=0.05, shed=True,
            autoscale=True, target_queue_wait_s=0.02,
            scrape_interval_s=0.1, client_timeout_s=3.0)
        assert out["accounting_ok"], out
        assert out["replicas_end"] == 2          # pressure drove scale-up
        assert out["ok"] > 0

    def test_noshed_baseline_counts_timeouts(self):
        """The pre-ISSUE-7 configuration: unbounded queues, no watermark.
        At 3x capacity with a tight client budget the backlog converts
        into client timeouts — and the accounting still sums exactly."""
        from kubeflow_tpu.tools.loadtest import run_serve_bench

        out = run_serve_bench(
            rate_qps=120.0, duration_s=1.0, replicas=1, max_batch=2,
            max_queue=4, service_time_s=0.05, shed=False, autoscale=False,
            client_timeout_s=0.6)
        assert out["accounting_ok"], out
        assert out["shed"] == 0                  # nothing sheds...
        assert out["timeouts"] > 0               # ...so clients die waiting


class TestContinuousBatchingBench:
    """ISSUE 12: the token-model A/B legs. Counts are the contract —
    exact request accounting and KV-block conservation; the comparative
    perf gates live in bench.py where the recorded run is made."""

    @pytest.mark.slow
    def test_continuous_paged_leg_invariants(self):
        from kubeflow_tpu.tools.loadtest import run_continuous_bench

        out = run_continuous_bench(mode="continuous", dense_kv=False,
                                   duration_s=1.5)
        assert out["accounting_ok"], out
        assert out["errors"] == 0 and out["timeouts"] == 0
        assert out["shed_with_retry_after"] == out["shed"]
        assert out["kv"]["conservation_ok"]
        assert out["kv"]["blocks_leaked"] == 0
        assert out["midstep_admissions"] > 0
        assert out["served_by_backends"] == out["ok"]

    @pytest.mark.slow
    def test_stepbatch_leg_never_admits_midstep(self):
        from kubeflow_tpu.tools.loadtest import run_continuous_bench

        out = run_continuous_bench(mode="stepbatch", dense_kv=True,
                                   duration_s=1.5)
        assert out["accounting_ok"], out
        assert out["midstep_admissions"] == 0
        assert out["kv"]["conservation_ok"]
        assert out["kv"]["blocks_leaked"] == 0

    @pytest.mark.slow
    def test_affinity_bench_separates_hit_rates(self):
        from kubeflow_tpu.tools.loadtest import run_affinity_bench

        out = run_affinity_bench(duration_s=2.0)
        assert out["affine"]["accounting_ok"]
        assert out["blind"]["accounting_ok"]
        assert out["affine"]["kv_conservation_ok"]
        assert out["blind"]["kv_conservation_ok"]
        assert out["affine"]["hit_rate"] > out["blind"]["hit_rate"]
        assert out["affine"]["prefix_hits"] > 0


class TestServeCiSmokes:
    def test_ci_serve_bench_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_serve_bench_smoke

        run_serve_bench_smoke(rate_qps=60.0, duration_s=1.5)

    def test_ci_serving_soak_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_serving_soak_smoke

        run_serving_soak_smoke(seed=20260803)

    @pytest.mark.slow
    def test_ci_affinity_smoke_stage(self):
        from kubeflow_tpu.tools.ci import run_affinity_smoke

        run_affinity_smoke()
