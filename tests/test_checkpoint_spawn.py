"""Spawn-from-checkpoint (VERDICT r4 Missing #3 — the Rok variant).

The reference ships a second spawner backend creating notebooks from
storage snapshots (jupyter-web-app/backend/kubeflow_jupyter/rok/app.py:
16-136). TPU-native analogue: TpuJobs produce orbax checkpoints;
the spawner lists them (GET .../checkpoints), NotebookSpec.checkpoint
names one, and the notebook controller injects KFTPU_RESTORE_DIR so the
in-pod kernel restores the snapshot on start.
"""

import json
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta
from kubeflow_tpu.controlplane.api.types import (
    Notebook,
    NotebookSpec,
    PlatformConfig,
    Profile,
    ProfileSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.ckpt_catalog import (
    list_checkpoints,
    resolve_checkpoint,
)
from kubeflow_tpu.controlplane.platform import Platform

USER_HEADER = "x-goog-authenticated-user-email"
USER = "alice@example.com"


def _ckpt_dir(tmp_path: Path, name: str, steps=(0, 100)) -> str:
    d = tmp_path / name
    for s in steps:
        (d / str(s)).mkdir(parents=True)
        (d / str(s) / "state").mkdir()
    return str(d)


@pytest.fixture()
def stack(tmp_path):
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                          spec=ProfileSpec(owner=USER)))
    pf.reconcile()
    ckpt = _ckpt_dir(tmp_path, "llama-run")
    pf.api.create(TpuJob(
        metadata=ObjectMeta(name="llama-run", namespace="alice"),
        spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny",
                        checkpoint_dir=ckpt)))
    return pf, ckpt


class TestCatalog:
    def test_lists_job_checkpoints_with_latest_step(self, stack):
        pf, ckpt = stack
        entries = list_checkpoints(pf.api, "alice")
        assert len(entries) == 1
        e = entries[0]
        assert e["name"] == "llama-run"
        assert e["dir"] == ckpt
        assert e["latestStep"] == 100
        assert e["sourceKind"] == "TpuJob"

    def test_jobs_without_steps_or_dir_are_absent(self, stack, tmp_path):
        pf, _ = stack
        pf.api.create(TpuJob(
            metadata=ObjectMeta(name="no-dir", namespace="alice"),
            spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny")))
        empty = tmp_path / "empty-ckpt"
        empty.mkdir()
        pf.api.create(TpuJob(
            metadata=ObjectMeta(name="no-steps", namespace="alice"),
            spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny",
                            checkpoint_dir=str(empty))))
        names = [e["name"] for e in list_checkpoints(pf.api, "alice")]
        assert names == ["llama-run"]

    def test_resolve(self, stack):
        pf, ckpt = stack
        assert resolve_checkpoint(pf.api, "alice", "llama-run")["dir"] == ckpt
        assert resolve_checkpoint(pf.api, "alice", "nope") is None


class TestJwaSurface:
    def test_checkpoints_endpoint_and_create(self, stack):
        pf, ckpt = stack
        got = pf.jwa.list_checkpoints(USER, "alice")
        assert got[0]["name"] == "llama-run"

        out = pf.jwa.create_notebook(USER, "alice", {
            "name": "restore-nb", "checkpoint": "llama-run"})
        assert out["checkpoint"] == "llama-run"
        nb = pf.api.get("Notebook", "restore-nb", "alice")
        assert nb.spec.checkpoint == "llama-run"

    def test_unknown_checkpoint_is_400(self, stack):
        pf, _ = stack
        from kubeflow_tpu.webapps.router import RestError

        with pytest.raises(RestError, match="unknown checkpoint"):
            pf.jwa.create_notebook(USER, "alice", {
                "name": "bad-nb", "checkpoint": "ghost"})


class TestControllerInjection:
    def test_pod_gets_restore_env_and_annotation(self, stack):
        pf, ckpt = stack
        pf.api.create(Notebook(
            metadata=ObjectMeta(name="restore-nb", namespace="alice"),
            spec=NotebookSpec(image="jupyter:latest",
                              checkpoint="llama-run")))
        pf.reconcile()
        pod = pf.api.get("Pod", "restore-nb-0", "alice")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_RESTORE_DIR"] == ckpt
        assert pod.metadata.annotations[
            "checkpoint-source.tpu.kubeflow.org/job"] == "llama-run"

    def test_missing_checkpoint_waits_loudly_then_recovers(
            self, stack, tmp_path):
        pf, _ = stack
        late = tmp_path / "late-ckpt"
        pf.api.create(TpuJob(
            metadata=ObjectMeta(name="late-job", namespace="alice"),
            spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny",
                            checkpoint_dir=str(late))))
        pf.api.create(Notebook(
            metadata=ObjectMeta(name="late-nb", namespace="alice"),
            spec=NotebookSpec(image="jupyter:latest",
                              checkpoint="late-job")))
        pf.reconcile()
        assert pf.api.try_get("Pod", "late-nb-0", "alice") is None
        nb = pf.api.get("Notebook", "late-nb", "alice")
        cond = next(c for c in nb.status.conditions if c.type == "Ready")
        assert cond.reason == "CheckpointNotFound"

        # The job saves its first step -> the requeued reconcile recovers.
        (late / "0").mkdir(parents=True)
        pf.manager.run_until_idle(include_timers_within=10)
        pod = pf.api.get("Pod", "late-nb-0", "alice")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["KFTPU_RESTORE_DIR"] == str(late)


class TestSpawnerPageE2E:
    """The VERDICT's done-condition: the spawner e2e creates a notebook
    from a checkpoint produced by a prior TpuJob — through the REAL
    executed page script (MicroBrowser + minijs)."""

    def test_spawn_from_checkpoint_through_real_page(self, stack):
        from kubeflow_tpu.webapps.browser import MicroBrowser
        from kubeflow_tpu.webapps.frontend import central_hub
        from kubeflow_tpu.webapps.router import JsonHttpServer

        pf, ckpt = stack
        pf.manager.start()
        hub = central_hub(pf.api, pf.dashboard, pf.jwa)
        srv = JsonHttpServer(hub, port=0).start()
        try:
            b = MicroBrowser(f"http://127.0.0.1:{srv.port}",
                             user_header=USER_HEADER, user=USER)
            b.open("/spawner")
            # init() populated the picker from the checkpoints API.
            picker = b.element("ckpt")
            assert "from llama-run @ step 100" in picker.innerHTML
            assert picker.value == ""          # "blank notebook" default

            b.set_value("name", "ck-nb")
            b.set_value("ckpt", "llama-run")
            b.submit("spawn")
            assert ">ck-nb<" in b.element("list").innerHTML

            nb = pf.api.get("Notebook", "ck-nb", "alice")
            assert nb.spec.checkpoint == "llama-run"
            # The controller (running under the manager) builds the pod
            # with the restore env.
            import time

            for _ in range(100):
                pod = pf.api.try_get("Pod", "ck-nb-0", "alice")
                if pod is not None:
                    break
                time.sleep(0.05)
            env = {e.name: e.value for e in pod.spec.containers[0].env}
            assert env["KFTPU_RESTORE_DIR"] == ckpt
        finally:
            srv.stop()
            pf.manager.stop()

    def test_waiting_notebook_emits_one_event_not_one_per_tick(
            self, stack, tmp_path):
        from kubeflow_tpu.controlplane.controllers.notebook import (
            NotebookController,
        )
        from kubeflow_tpu.utils.monitoring import MetricsRegistry

        pf, _ = stack
        late = tmp_path / "never-ckpt"
        pf.api.create(TpuJob(
            metadata=ObjectMeta(name="never-job", namespace="alice"),
            spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny",
                            checkpoint_dir=str(late))))
        pf.api.create(Notebook(
            metadata=ObjectMeta(name="wait-nb", namespace="alice"),
            spec=NotebookSpec(image="jupyter:latest",
                              checkpoint="never-job")))
        # Drive the waiting notebook's requeue ticks directly: the event
        # must fire on the TRANSITION only, not once per 5s tick.
        ctl = NotebookController(pf.api, MetricsRegistry())
        for _ in range(4):
            ctl.reconcile("alice", "wait-nb")
        events = [e for e in pf.api.list("Event", namespace="alice")
                  if e.reason == "CheckpointNotFound"
                  and e.involved_name == "wait-nb"]
        assert len(events) == 1, [e.message for e in events]


class TestRealOrbaxLoop:
    """Close the loop with a REAL orbax checkpoint: what the producing
    job's CheckpointService wrote is exactly what the spawned notebook's
    KFTPU_RESTORE_DIR restores — byte-exact, not a fake step dir."""

    def test_write_catalog_spawn_restore(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.train.checkpoint import CheckpointService

        ckdir = str(tmp_path / "real-run")
        svc = CheckpointService(ckdir)
        state = {"params": {"w": jnp.arange(8, dtype=jnp.float32)},
                 "step": 7}
        svc.save(7, state)
        svc.close()

        pf = Platform()
        pf.apply_config(PlatformConfig(
            metadata=ObjectMeta(name="kubeflow-tpu")))
        pf.api.create(Profile(metadata=ObjectMeta(name="alice"),
                              spec=ProfileSpec(owner=USER)))
        pf.reconcile()
        pf.api.create(TpuJob(
            metadata=ObjectMeta(name="real-run", namespace="alice"),
            spec=TpuJobSpec(slice_type="v5e-16", model="llama-tiny",
                            checkpoint_dir=ckdir)))
        entry = resolve_checkpoint(pf.api, "alice", "real-run")
        assert entry is not None and entry["latestStep"] == 7

        pf.jwa.create_notebook(USER, "alice", {
            "name": "resume-nb", "checkpoint": "real-run"})
        pf.reconcile()
        pod = pf.api.get("Pod", "resume-nb-0", "alice")
        env = {e.name: e.value for e in pod.spec.containers[0].env}

        restored = CheckpointService(
            env["KFTPU_RESTORE_DIR"]).restore_raw_latest()
        assert restored["step"] == 7
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]),
            np.arange(8, dtype=np.float32))
