import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import (
    Llama,
    LlamaConfig,
    Mixtral,
    MixtralConfig,
    ResNet,
    ResNetConfig,
    ViT,
    ViTConfig,
    get_model,
    list_models,
)


class TestLlama:
    def test_forward_shapes(self):
        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        tokens = jnp.ones((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_scan_matches_unrolled(self):
        # Same seed → same params modulo layout; outputs must agree.
        tokens = jnp.arange(16)[None, :] % 250
        # f32 activations: scan vs inline compile to different fusion orders,
        # which is bf16-visible noise but must vanish at f32 tolerances.
        cfg_u = LlamaConfig.tiny(num_layers=2, scan_layers=False, dtype=jnp.float32)
        cfg_s = LlamaConfig.tiny(num_layers=2, scan_layers=True, dtype=jnp.float32)
        mu, ms = Llama(cfg_u), Llama(cfg_s)
        pu = mu.init(jax.random.PRNGKey(0), tokens)
        ps = ms.init(jax.random.PRNGKey(0), tokens)
        # Transplant unrolled params into the scanned (stacked) layout to
        # compare computation, not init RNG streams.
        import flax
        from flax import linen as nn

        pu = nn.meta.unbox(pu)
        flat_u = flax.traverse_util.flatten_dict(pu["params"])
        stacked = {}
        for k, v in flat_u.items():
            if k[0].startswith("layer_"):
                idx = int(k[0].split("_")[1])
                stacked.setdefault(("layers",) + k[1:], {})[idx] = v
            else:
                stacked[k] = v
        merged = {}
        for k, v in stacked.items():
            if isinstance(v, dict):
                merged[k] = jnp.stack([v[i] for i in sorted(v)])
            else:
                merged[k] = v
        ps2 = {"params": flax.traverse_util.unflatten_dict(merged)}
        out_u = mu.apply(pu, tokens)
        out_s = ms.apply(ps2, tokens)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_s), atol=1e-5
        )

    def test_decode_cache_matches_full(self):
        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        tokens = jnp.arange(8)[None, :]
        params = model.init(jax.random.PRNGKey(0), tokens)
        full = model.apply(params, tokens)

        cache0 = model.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
        v = {"params": params["params"], "cache": cache0}
        out_p, vp = model.apply(v, tokens[:, :7], decode=True, mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(full[:, :7]), atol=1e-5
        )
        v2 = {"params": params["params"], "cache": vp["cache"]}
        out_d, _ = model.apply(
            v2, tokens[:, 7:8], positions=jnp.array([[7]]), decode=True,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(out_d[:, 0]), np.asarray(full[:, 7]), atol=1e-5
        )

    def test_num_params_formula(self):
        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32))
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == model.num_params()

    def test_causality(self):
        """Changing a future token must not affect past logits."""
        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        t1 = jnp.arange(8)[None, :]
        t2 = t1.at[0, -1].set(99)
        params = model.init(jax.random.PRNGKey(0), t1)
        o1 = model.apply(params, t1)
        o2 = model.apply(params, t2)
        np.testing.assert_allclose(
            np.asarray(o1[:, :-1]), np.asarray(o2[:, :-1]), atol=1e-6
        )


class TestMixtral:
    def test_forward_and_aux_loss(self):
        cfg = MixtralConfig.tiny()
        model = Mixtral(cfg)
        tokens = jnp.ones((2, 16), jnp.int32)
        init_vars = model.init(jax.random.PRNGKey(0), tokens)
        # init also populates "losses" (sow runs at init); feed params only,
        # as the train step does, else sown tuples accumulate stale entries.
        params = {"params": init_vars["params"]}
        logits, state = model.apply(params, tokens, mutable=["losses"])
        assert logits.shape == (2, 16, cfg.vocab_size)
        aux = jax.tree.leaves(state["losses"])
        assert len(aux) == cfg.num_layers
        assert all(jnp.isfinite(a).all() for a in aux)

    def test_grad_finite(self):
        cfg = MixtralConfig.tiny(num_layers=1)
        model = Mixtral(cfg)
        tokens = jnp.ones((2, 8), jnp.int32)
        params = {"params": model.init(jax.random.PRNGKey(0), tokens)["params"]}

        def loss(p):
            logits, state = model.apply(p, tokens, mutable=["losses"])
            aux = sum(jax.tree.leaves(state["losses"]))
            return logits.mean() + 0.02 * aux

        g = jax.grad(loss)(params)
        assert all(jnp.isfinite(x).all() for x in jax.tree.leaves(g))


class TestResNet:
    def test_forward(self):
        cfg = ResNetConfig.tiny()
        model = ResNet(cfg)
        imgs = jnp.ones((2, 32, 32, 3))
        vars_ = model.init(jax.random.PRNGKey(0), imgs, train=False)
        logits = model.apply(vars_, imgs, train=False)
        assert logits.shape == (2, cfg.num_classes)

    def test_train_updates_batch_stats(self):
        cfg = ResNetConfig.tiny()
        model = ResNet(cfg)
        imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        vars_ = model.init(jax.random.PRNGKey(0), imgs, train=True)
        _, updated = model.apply(
            vars_, imgs, train=True, mutable=["batch_stats"]
        )
        before = jax.tree.leaves(vars_["batch_stats"])
        after = jax.tree.leaves(updated["batch_stats"])
        assert any(
            not np.allclose(np.asarray(b), np.asarray(a))
            for b, a in zip(before, after)
        )

    def test_resnet50_param_count(self):
        cfg = ResNetConfig.resnet50(num_classes=1000)
        model = ResNet(cfg)
        vars_ = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), jnp.ones((1, 224, 224, 3)),
                               train=False)
        )
        n = sum(np.prod(x.shape) for x in jax.tree.leaves(vars_["params"]))
        # Canonical ResNet-50 ≈ 25.56M params.
        assert 25_000_000 < n < 26_000_000


class TestViT:
    def test_forward(self):
        cfg = ViTConfig.tiny()
        model = ViT(cfg)
        imgs = jnp.ones((2, 32, 32, 3))
        params = model.init(jax.random.PRNGKey(0), imgs)
        logits = model.apply(params, imgs)
        assert logits.shape == (2, cfg.num_classes)

    def test_vit_l16_param_count(self):
        cfg = ViTConfig.vit_l16()
        model = ViT(cfg)
        vars_ = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), jnp.ones((1, 224, 224, 3)))
        )
        n = sum(np.prod(x.shape) for x in jax.tree.leaves(vars_["params"]))
        # ViT-L/16 ≈ 304M params.
        assert 300_000_000 < n < 310_000_000


class TestRegistry:
    def test_catalogue(self):
        names = list_models()
        for expected in ("llama3-8b", "mixtral-8x7b", "resnet50", "vit-l16"):
            assert expected in names

    def test_get_model_tiny(self):
        model, cfg = get_model("llama-tiny")
        assert isinstance(model, Llama)
        assert cfg.embed_dim == 64

    def test_unknown_model(self):
        with pytest.raises(KeyError):
            get_model("gpt-17")

    def test_llama3_8b_param_count(self):
        model, cfg = get_model("llama3-8b")
        assert 7.9e9 < model.num_params() < 8.2e9


class TestMixtralSharesBackbone:
    def test_tie_embeddings_and_softcap_honored(self):
        cfg = MixtralConfig.tiny(num_layers=1, tie_embeddings=True,
                                 logits_softcap=5.0)
        model = Mixtral(cfg)
        tokens = jnp.ones((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        # Tied: no separate lm_head param.
        assert "lm_head" not in variables["params"]
        logits = model.apply({"params": variables["params"]}, tokens)
        assert float(jnp.abs(logits).max()) <= 5.0


class TestViTDropout:
    def test_dropout_active_in_train_mode(self):
        cfg = ViTConfig.tiny(dropout=0.5)
        model = ViT(cfg)
        imgs = jnp.ones((2, 32, 32, 3))
        params = model.init(jax.random.PRNGKey(0), imgs)
        # The head kernel is zero-initialised → logits are 0 regardless of
        # features; give it weight so dropout noise reaches the output.
        from flax import linen as nn
        import flax

        params = nn.meta.unbox(params)
        flat = flax.traverse_util.flatten_dict(params["params"])
        flat[("head", "kernel")] = jnp.ones_like(flat[("head", "kernel")])
        params = {"params": flax.traverse_util.unflatten_dict(flat)}
        a = model.apply(params, imgs, train=True,
                        rngs={"dropout": jax.random.PRNGKey(1)})
        b = model.apply(params, imgs, train=True,
                        rngs={"dropout": jax.random.PRNGKey(2)})
        assert not np.allclose(np.asarray(a), np.asarray(b))
        # Eval mode is deterministic and needs no rng.
        c = model.apply(params, imgs, train=False)
        d = model.apply(params, imgs, train=False)
        np.testing.assert_allclose(np.asarray(c), np.asarray(d))


class TestHeadLogits:
    """head_logits (the serving prefill's split logits tail) must mirror
    the model's own logits op-for-op in every config variant."""

    @pytest.mark.parametrize("kw", [
        {},
        {"tie_embeddings": True},
        {"logits_softcap": 30.0},
        {"logits_f32": False},
    ])
    def test_matches_model_logits(self, kw):
        from kubeflow_tpu.models.llama import Llama, LlamaConfig, head_logits

        cfg = LlamaConfig.tiny(**kw)
        m = Llama(cfg)
        tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
        variables = m.init(jax.random.PRNGKey(0), tokens)
        full = m.apply(variables, tokens)
        hidden = m.apply(variables, tokens, return_hidden=True)
        split = head_logits(cfg, variables["params"], hidden)
        assert split.dtype == full.dtype
        np.testing.assert_allclose(
            np.asarray(split, np.float32), np.asarray(full, np.float32),
            rtol=1e-5, atol=1e-6,
        )
