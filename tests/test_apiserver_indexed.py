"""Indexed, copy-light apiserver (ISSUE 3): secondary-index list
equivalence against the naive full scan, snapshot-replacement mutation
safety, deterministic copy counters, and breadth-first cascade GC."""

import random

import pytest

from kubeflow_tpu.controlplane.api import (
    Namespace,
    ObjectMeta,
    Pod,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.api.meta import OwnerReference
from kubeflow_tpu.controlplane.runtime import (
    InMemoryApiServer,
    NotFoundError,
)
from kubeflow_tpu.controlplane.runtime.apiserver import CLUSTER_SCOPED
from kubeflow_tpu.utils.monitoring import MetricsRegistry


def _job(name, ns="u", labels=None):
    j = TpuJob(metadata=ObjectMeta(name=name, namespace=ns),
               spec=TpuJobSpec(slice_type="v5e-16"))
    j.metadata.labels = dict(labels or {})
    return j


def _naive_list(api, kind, namespace=None, label_selector=None):
    """The pre-index reference implementation: full store scan. The indexed
    list must return exactly this, for every query shape."""
    out = []
    for (k, ns, _), obj in api._objects.items():
        if k != kind:
            continue
        if namespace is not None and kind not in CLUSTER_SCOPED \
                and ns != namespace:
            continue
        if label_selector and not all(
            obj.metadata.labels.get(lk) == lv
            for lk, lv in label_selector.items()
        ):
            continue
        out.append(obj)
    return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))


def _ids(objs):
    return [(o.kind, o.metadata.namespace, o.metadata.name) for o in objs]


class TestIndexedListEquivalence:
    KINDS = ("TpuJob", "Pod", "Namespace")       # Namespace: cluster-scoped
    NAMESPACES = ("u1", "u2", "u3")
    LABELS = ({"team": "x"}, {"team": "y"}, {"tier": "prod"}, {})

    def _random_object(self, rng, i):
        kind = rng.choice(self.KINDS)
        labels = dict(rng.choice(self.LABELS))
        if kind == "Namespace":
            obj = Namespace(metadata=ObjectMeta(name=f"ns-{i:03d}"))
        elif kind == "Pod":
            obj = Pod(metadata=ObjectMeta(
                name=f"pod-{i:03d}", namespace=rng.choice(self.NAMESPACES)))
        else:
            obj = _job(f"job-{i:03d}", ns=rng.choice(self.NAMESPACES))
        obj.metadata.labels = labels
        return obj

    def _queries(self, rng, n):
        for _ in range(n):
            yield (
                rng.choice(self.KINDS),
                rng.choice((None,) + self.NAMESPACES),
                rng.choice((None,) + tuple(
                    s for s in self.LABELS if s)),
            )

    @pytest.mark.parametrize("seed", [0, 1, 2026])
    def test_property_indexed_equals_naive(self, seed):
        """Random store + random churn: every (kind, ns, selector) query
        answered by the indexes matches the naive full scan exactly —
        including cluster-scoped kinds, where namespace is ignored."""
        rng = random.Random(seed)
        api = InMemoryApiServer(registry=MetricsRegistry())
        live = []
        for i in range(rng.randrange(40, 80)):
            obj = self._random_object(rng, i)
            live.append(api.create(obj))
        # Churn: random updates (relabel) and deletes keep the indexes
        # honest under replacement and removal.
        rng.shuffle(live)
        for obj in live[: len(live) // 3]:
            got = api.get(obj.kind, obj.metadata.name,
                          obj.metadata.namespace)
            got.metadata.labels = dict(rng.choice(self.LABELS))
            api.update(got)
        for obj in live[-len(live) // 4:]:
            api.delete(obj.kind, obj.metadata.name, obj.metadata.namespace)

        for kind, ns, sel in self._queries(rng, 60):
            want = _ids(_naive_list(api, kind, ns, sel))
            assert _ids(api.list(kind, ns, sel)) == want, (kind, ns, sel)
            assert _ids(api.list(kind, ns, sel, copy=False)) == want

    def test_owner_index_follows_updates(self):
        """Re-parenting an object on update must move it between owner-uid
        buckets: cascade-deleting the old owner spares it, the new owner
        takes it down."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        a = api.create(_job("owner-a"))
        b = api.create(_job("owner-b"))
        pod = Pod(metadata=ObjectMeta(
            name="p", namespace="u",
            owner_references=[OwnerReference(kind="TpuJob", name="owner-a",
                                             uid=a.metadata.uid)]))
        api.create(pod)
        live = api.get("Pod", "p", "u")
        live.metadata.owner_references = [
            OwnerReference(kind="TpuJob", name="owner-b",
                           uid=b.metadata.uid)]
        api.update(live)
        api.delete("TpuJob", "owner-a", "u")
        assert api.try_get("Pod", "p", "u") is not None
        api.delete("TpuJob", "owner-b", "u")
        assert api.try_get("Pod", "p", "u") is None


class TestCopyLightReads:
    def test_zero_copy_reads_share_the_snapshot(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        s1 = api.list("TpuJob", namespace="u", copy=False)[0]
        s2 = api.list("TpuJob", namespace="u", copy=False)[0]
        s3 = api.get("TpuJob", "a", "u", copy=False)
        assert s1 is s2 is s3          # zero copies: one shared snapshot
        assert api.copied == {}        # and the counter agrees

    def test_copy_counter_counts_matches_not_store(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        for i in range(30):
            api.create(_job(f"j-{i:02d}", ns=f"ns-{i % 3}"))
        for i in range(40):
            api.create(Pod(metadata=ObjectMeta(name=f"p-{i:02d}",
                                               namespace="ns-0")))
        api.copied = {}
        got = api.list("TpuJob", namespace="ns-0")      # default copy=True
        assert len(got) == 10
        assert api.copied == {"list": 10}               # O(matches): 10/70
        api.get("TpuJob", "j-00", "ns-0")
        assert api.copied == {"list": 10, "get": 1}

    def test_mutating_a_zero_copy_read_cannot_corrupt_the_store(self):
        """Snapshots are REPLACED on every write, never edited in place: a
        rogue mutation of a previously handed-out zero-copy result lands on
        a detached snapshot and the store never sees it."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        shared = api.list("TpuJob", namespace="u", copy=False)[0]

        # A legitimate writer replaces the snapshot wholesale...
        writer = api.get("TpuJob", "a", "u")            # private copy
        writer.spec.max_restarts = 9
        api.update(writer)
        # ...so the reader's old snapshot is detached; vandalising it
        # cannot reach the store.
        shared.spec.slice_type = "HACKED"
        live = api.get("TpuJob", "a", "u")
        assert live.spec.slice_type == "v5e-16"
        assert live.spec.max_restarts == 9

    def test_update_status_replaces_not_edits(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        shared = api.get("TpuJob", "a", "u", copy=False)
        writer = api.get("TpuJob", "a", "u")
        writer.status.phase = "Running"
        api.update_status(writer)
        assert shared.status.phase == "Pending"   # old snapshot untouched...
        assert api.get("TpuJob", "a", "u",
                       copy=False).status.phase == "Running"
        assert api.get("TpuJob", "a", "u", copy=False) is not shared

    def test_private_copies_stay_private(self):
        """The pre-existing store-isolation contract, restated for the new
        read path: default reads are safe to mutate freely."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        mine = api.list("TpuJob", namespace="u")[0]
        mine.spec.slice_type = "SCRIBBLED"
        assert api.get("TpuJob", "a", "u").spec.slice_type == "v5e-16"

    def test_watch_events_share_one_object_across_watchers(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        q1, q2 = api.watch("TpuJob"), api.watch("TpuJob")
        api.create(_job("a"))
        e1, e2 = q1.get_nowait(), q2.get_nowait()
        assert e1 is e2                      # one event object per write
        assert e1.object is api.get("TpuJob", "a", "u", copy=False)

    def test_watch_replay_is_snapshot_backed(self):
        api = InMemoryApiServer(registry=MetricsRegistry())
        api.create(_job("a"))
        api.copied = {}
        q = api.watch("TpuJob")
        ev = q.get_nowait()
        assert ev.type == "ADDED"
        assert ev.object is api.get("TpuJob", "a", "u", copy=False)
        assert api.copied == {}              # replay copied nothing


class TestCascadeBfs:
    def test_transitive_cascade_via_owner_index(self):
        """job -> pod -> grandchild: the whole chain goes down breadth-
        first off the owner-uid index."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        job = api.create(_job("root"))
        pod = api.create(Pod(metadata=ObjectMeta(
            name="child", namespace="u",
            owner_references=[OwnerReference(kind="TpuJob", name="root",
                                             uid=job.metadata.uid)])))
        api.create(Pod(metadata=ObjectMeta(
            name="grandchild", namespace="u",
            owner_references=[OwnerReference(kind="Pod", name="child",
                                             uid=pod.metadata.uid)])))
        api.delete("TpuJob", "root", "u")
        for name in ("child", "grandchild"):
            with pytest.raises(NotFoundError):
                api.get("Pod", name, "u")

    def test_cascade_respects_finalizers(self):
        """A finalizer-carrying dependent is only *marked* by the cascade;
        its own dependents survive until the finalizer clears — then the
        update-path removal cascades on."""
        api = InMemoryApiServer(registry=MetricsRegistry())
        job = api.create(_job("root"))
        mid = Pod(metadata=ObjectMeta(
            name="mid", namespace="u",
            finalizers=["tpu.kubeflow.org/drain"],
            owner_references=[OwnerReference(kind="TpuJob", name="root",
                                             uid=job.metadata.uid)]))
        mid = api.create(mid)
        api.create(Pod(metadata=ObjectMeta(
            name="leaf", namespace="u",
            owner_references=[OwnerReference(kind="Pod", name="mid",
                                             uid=mid.metadata.uid)])))
        api.delete("TpuJob", "root", "u")
        held = api.get("Pod", "mid", "u")
        assert held.metadata.deletion_timestamp is not None
        assert api.try_get("Pod", "leaf", "u") is not None
        held.metadata.finalizers = []
        api.update(held)
        assert api.try_get("Pod", "mid", "u") is None
        assert api.try_get("Pod", "leaf", "u") is None
