"""Data-plane step profiler (ISSUE 19): conservation-by-construction in
the tick domain, trace-id adoption into request timelines, byte-identical
seeded perfetto export with the acceptance track structure, bounded-ring
overflow accounting, zero-overhead-when-disabled (including no jax at
module import), cost-catalog goldens for the tiny model, flight-recorder
phase evidence, and the one-sided regression gate's non-vacuity both
ways (clean passes; chaos in one phase trips exactly that phase)."""

import json
import os
import subprocess
import sys

import pytest

from kubeflow_tpu.obs.flight import DUMP_PHASE_TAIL, FlightRecorder, stitch
from kubeflow_tpu.obs.profiler import (
    NULL_STEP,
    Profiler,
    TickClock,
    perfetto_json,
    perfetto_track_counts,
    profile_gate_failures,
    seeded_serving_profile,
    seeded_train_profile,
    serving_cost_catalog,
    train_cost_catalog,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _baseline():
    with open(os.path.join(REPO_ROOT, "PROFILE_r19.json")) as f:
        return json.load(f)


def _tick_profiler(**kw):
    return Profiler(now_fn=TickClock(), **kw)


def _drive(prof, *, track="serve", steps=3,
           phases=("prefill", "decode_chunk", "retire")):
    for i in range(steps):
        h = prof.start_step(track, i + 1)
        for p in phases:
            h.mark(p)
        prof.finish_step(h)


class TestTickDomain:
    def test_phases_tile_the_step_exactly(self):
        prof = _tick_profiler()
        h = prof.start_step("serve", 1)
        h.mark("prefill")
        h.mark("decode_chunk")
        h.mark("retire")
        srec = prof.finish_step(h)
        # Every clock read is one tick: 3 marks -> 3 ticks of step span,
        # one per phase, and the tiles sum to the span by construction.
        assert srec["dur"] == 3
        assert srec["phases"] == {"prefill": 1, "decode_chunk": 1,
                                  "retire": 1}
        s = prof.summary()["serve"]
        assert s["conservation_ok"]
        assert s["step_ticks"] == sum(s["phase_ticks"].values())

    def test_chaos_ticks_land_inside_the_named_phase(self):
        prof = _tick_profiler(chaos_extra_ticks={"decode_chunk": 5})
        _drive(prof, steps=2)
        s = prof.summary()["serve"]
        assert s["conservation_ok"]  # chaos ticks are *inside* the tile
        assert s["phase_ticks"]["decode_chunk"] == 2 * (1 + 5)
        assert s["phase_ticks"]["prefill"] == 2
        assert s["phase_ticks"]["retire"] == 2

    def test_fractions_sum_to_one(self):
        prof = _tick_profiler()
        _drive(prof, steps=4)
        s = prof.summary()["serve"]
        assert sum(s["fractions"].values()) == pytest.approx(1.0)

    def test_ring_overflow_is_reported_not_silent(self):
        # 3 phases/step, phase ring of 6 -> only the last 2 steps stay
        # fully resident; older steps must be counted as dropped and
        # excluded from the fractions (else conservation would lie).
        prof = _tick_profiler(capacity=6)
        _drive(prof, steps=10)
        s = prof.summary()["serve"]
        assert s["steps_dropped"] > 0
        assert s["steps"] + s["steps_dropped"] == 10
        assert s["conservation_ok"]
        assert s["step_ticks"] == sum(s["phase_ticks"].values())

    def test_multi_track_rollup_is_independent(self):
        prof = _tick_profiler()
        _drive(prof, track="serve", steps=2)
        _drive(prof, track="train", steps=3,
               phases=("data_load", "step_compute"))
        s = prof.summary()
        assert s["serve"]["steps"] == 2
        assert s["train"]["steps"] == 3
        assert s["train"]["phase_ticks"] == {"data_load": 3,
                                             "step_compute": 3}


class TestDisabled:
    def test_null_handle_no_clock_no_spans_no_rings(self):
        calls = []

        def counting_now():
            calls.append(1)
            return len(calls)

        tracer = Tracer()
        prof = Profiler(enabled=False, now_fn=counting_now, tracer=tracer)
        h = prof.start_step("train", 1)
        assert h is NULL_STEP
        h.mark("data_load")
        assert prof.finish_step(h) is None
        prof.sample_counters({"x": 1.0})
        assert calls == []            # the clock was never read
        assert tracer.spans() == []
        assert prof.summary() == {}

    def test_module_import_pulls_no_jax(self):
        # Zero overhead when off extends to import time: a process that
        # only imports the profiler must not pay the jax import.
        code = ("import sys; import kubeflow_tpu.obs.profiler; "
                "assert 'jax' not in sys.modules, 'jax imported'")
        subprocess.run([sys.executable, "-c", code], check=True,
                       cwd=REPO_ROOT)


class TestRegressionGate:
    BASE = {"serve": {"budget": 0.1,
                      "phase_fractions": {"prefill": 0.3,
                                          "decode_chunk": 0.7}}}

    def _summary(self, prefill, decode):
        return {"serve": {"steps": 5, "steps_dropped": 0,
                          "step_ticks": prefill + decode,
                          "conservation_ok": True,
                          "phase_ticks": {"prefill": prefill,
                                          "decode_chunk": decode},
                          "fractions": {
                              "prefill": prefill / (prefill + decode),
                              "decode_chunk":
                                  decode / (prefill + decode)}}}

    def test_clean_profile_passes(self):
        assert profile_gate_failures(self._summary(30, 70),
                                     self.BASE) == []

    def test_one_sided_growth_trips_only_the_grown_phase(self):
        fails = profile_gate_failures(self._summary(60, 40), self.BASE)
        assert len(fails) == 1 and "prefill" in fails[0]
        # the complement SHRANK by the same amount: not a regression
        assert not any("decode_chunk" in f for f in fails)

    def test_zero_observation_guard(self):
        fails = profile_gate_failures({}, self.BASE)
        assert fails and "vacuous" in fails[0]
        empty = {"serve": {"steps": 0, "conservation_ok": True,
                           "fractions": {}}}
        assert profile_gate_failures(empty, self.BASE)

    def test_conservation_violation_fails(self):
        s = self._summary(30, 70)
        s["serve"]["conservation_ok"] = False
        assert any("conservation" in f
                   for f in profile_gate_failures(s, self.BASE))

    def test_missing_phase_fails(self):
        s = self._summary(30, 70)
        del s["serve"]["fractions"]["decode_chunk"]
        assert any("absent" in f
                   for f in profile_gate_failures(s, self.BASE))


class TestCostCatalogGoldens:
    """Analytic values for LlamaConfig.tiny (E=64 H=4 Hkv=2 Dh=16 M=128
    L=2 V=256), hand-computed — these pin the formulas, so a silent
    change to the FLOP model breaks here, not in a dashboard."""

    def _cfg(self):
        from kubeflow_tpu.models import LlamaConfig

        return LlamaConfig.tiny()

    def test_train_catalog(self):
        cat = train_cost_catalog(self._cfg(), seq_len=16, global_batch=2,
                                 mesh_axes={"dp": 2, "fsdp": 1})
        e = cat["train_step"]
        # per_layer = 4096(q) + 4096(kv) + 4096(o) + 24576(mlp) = 36864
        # params = 2*36864 + 256*64 = 90112
        assert e["matmul_params"] == 90112
        # attn fwd/token @S=16 causal: 4*16*4*16*2 // 2 = 4096
        # train fpt = 3 * (2*90112 + 4096) = 552960
        assert e["flops_per_token"] == 552960
        assert e["tokens_per_call"] == 32
        assert e["flops"] == 552960 * 32
        # grads: 4 bytes * params; ring allreduce on dp=2 moves
        # 2*(n-1)/n = all of it; fsdp extent 1 contributes nothing.
        assert e["collective_bytes"] == {"dp": 4 * 90112}

    def test_serving_catalog(self):
        cat = serving_cost_catalog(self._cfg(), context_len=64,
                                   kv_block_size=8, blocks_per_seq=8,
                                   batch=2)
        # fwd fpt = 2*90112 + attn; prefill causal @64: 32768//2
        assert cat["prefill"]["flops_per_token"] == 180224 + 16384
        # decode attends the whole cache: full 32768
        assert cat["decode_chunk"]["flops_per_token"] == 180224 + 32768
        # gather: L * (B*blocks*bs rows) * (Hkv*Dh*2B) * K+V * R+W
        #       = 2 * 128 * 64 * 2 * 2 = 65536
        assert cat["block_gather"]["bytes_per_dispatch"] == 65536

    def test_mfu_against_known_peak(self):
        prof = _tick_profiler()
        ratio = prof.set_train_mfu(tokens_per_sec=1e6,
                                   flops_per_token=5e7,
                                   peak_tflops=100.0)
        assert ratio == pytest.approx(0.5)
        assert prof.catalog["train_step"]["mfu"] == pytest.approx(0.5)

    def test_unknown_peak_reports_zero_not_fiction(self):
        prof = _tick_profiler()
        assert prof.set_train_mfu(tokens_per_sec=1e6,
                                  flops_per_token=5e7,
                                  peak_tflops=0.0) == 0.0


class TestFlightIntegration:
    def test_dump_appends_bounded_phase_ring_and_stitches(self, tmp_path):
        clock = TickClock()
        fl = FlightRecorder(shard="s0", now_fn=clock)
        prof = Profiler(now_fn=clock, flight=fl, shard="s0")
        _drive(prof, steps=DUMP_PHASE_TAIL)   # 3x tail -> must truncate
        fl.record("alert", {"state": "page"})
        path = fl.dump(str(tmp_path), reason="alert-page")
        recs = FlightRecorder.load(path)
        header = recs[0]
        phases = [r for r in recs if r.get("kind") == "phase"]
        # bounded: exactly the tail, and the header advertises it
        assert len(phases) == DUMP_PHASE_TAIL
        assert header["phases"] == DUMP_PHASE_TAIL
        assert phases[-1]["data"]["phase"] == "retire"
        assert all(r["data"]["track"] == "serve" for r in phases)
        # stitch keeps (t, shard, seq) order with phases interleaved
        merged = [r for r in stitch([path]) if r.get("kind") != "flight"]
        keys = [(r.get("t", 0), r.get("shard", ""), r.get("seq", 0))
                for r in merged]
        assert keys == sorted(keys)
        # the alert entry and the phase evidence share one timeline
        kinds = {r.get("kind") for r in merged}
        assert {"alert", "phase"} <= kinds

    def test_overlapping_dumps_dedup_phases(self, tmp_path):
        clock = TickClock()
        fl = FlightRecorder(shard="s0", now_fn=clock)
        prof = Profiler(now_fn=clock, flight=fl, shard="s0")
        _drive(prof, steps=2)
        p1 = fl.dump(str(tmp_path), reason="first")
        p2 = fl.dump(str(tmp_path), reason="second")
        merged = stitch([p1, p2])
        phases = [r for r in merged if r.get("kind") == "phase"]
        assert len(phases) == 2 * 3   # deduped on (shard, seq, kind, t)


def _export_step_conservation(text):
    """Parse a perfetto export: per (pid, step), the phase spans (tid !=
    0) must tile the step span (tid == 0) exactly — the acceptance
    criterion's integer-tick conservation, checked on the EXPORT."""
    doc = json.loads(text)
    step_dur = {}
    phase_sum = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        key = (ev["pid"], ev["args"]["step"])
        if ev["tid"] == 0:
            step_dur[key] = step_dur.get(key, 0) + ev["dur"]
        else:
            phase_sum[key] = phase_sum.get(key, 0) + ev["dur"]
    assert step_dur, "export has no step spans"
    for key, dur in step_dur.items():
        assert phase_sum.get(key, 0) == dur, (key, dur, phase_sum)


class TestPerfettoExport:
    def test_tick_export_structure_and_conservation(self):
        prof = _tick_profiler(shard="proc0")
        _drive(prof, steps=3)
        prof.sample_counters({"hbm_pool_occupancy_ratio": 0.5,
                              "kv_blocks_shared": 2.0})
        text = prof.export_perfetto()
        counts = perfetto_track_counts(text)
        assert counts == {"phase_tracks": 3, "counter_tracks": 2}
        _export_step_conservation(text)
        doc = json.loads(text)
        names = {ev["args"]["name"] for ev in doc["traceEvents"]
                 if ev.get("name") == "process_name"}
        assert names == {"serve:proc0"}

    def test_rendering_is_pure_and_path_write_matches(self, tmp_path):
        prof = _tick_profiler()
        _drive(prof, steps=2)
        data = prof.to_dict()
        assert perfetto_json(data) == perfetto_json(
            json.loads(json.dumps(data)))  # survives a JSON round trip
        p = tmp_path / "out.json"
        text = prof.export_perfetto(str(p))
        assert p.read_text() == text


# ----------------------- seeded end-to-end scenarios ----------------------
# One engine build per scenario (jax compile) — shared via module fixtures.


@pytest.fixture(scope="module")
def serving_bundle():
    tracer = Tracer()
    registry = MetricsRegistry()
    prof = seeded_serving_profile(tracer=tracer, registry=registry)
    return prof, tracer, registry


@pytest.fixture(scope="module")
def train_prof():
    return seeded_train_profile()


class TestSeededServing:
    def test_summary_matches_recorded_baseline(self, serving_bundle):
        prof, _, _ = serving_bundle
        rec = _baseline()["recorded"]["serve"]
        s = prof.summary()["serve"]
        assert s["conservation_ok"] and s["steps_dropped"] == 0
        assert s["steps"] == rec["steps"]
        assert s["step_ticks"] == rec["step_ticks"]
        assert s["phase_ticks"] == rec["phase_ticks"]

    def test_gate_clean_leg_passes(self, serving_bundle):
        prof, _, _ = serving_bundle
        gates = _baseline()["gates"]
        assert profile_gate_failures(
            prof.summary(), {"serve": gates["serve"]}) == []

    def test_trace_id_adoption(self, serving_bundle):
        _, tracer, _ = serving_bundle
        spans = tracer.spans()
        # queue-wait instant events adopt the REQUEST's trace id, so
        # they stitch into the `tpuctl trace req:<n>` timeline...
        req_waits = [s for s in spans if s.name == "serve/queue_wait"
                     and s.trace_id.startswith("req:")]
        assert req_waits
        # ...while anonymous engine steps share one profile/run root.
        roots = [s for s in spans if s.name == "profile/run"]
        assert len(roots) == 1
        run_id = roots[0].trace_id
        decode = [s for s in spans if s.name == "serve/decode_chunk"]
        assert decode and all(s.trace_id == run_id for s in decode)

    def test_phase_histogram_registered_and_observed(self, serving_bundle):
        _, _, registry = serving_bundle
        text = registry.render()
        assert 'kftpu_serving_phase_seconds_count{phase="decode_chunk"}' \
            in text
        assert 'phase="block_gather"' in text

    def test_counter_tracks_nonvacuous(self, serving_bundle):
        prof, _, _ = serving_bundle
        by_name = {}
        for rec in prof.to_dict()["counters"]:
            by_name.setdefault(rec["name"], []).append(rec["value"])
        assert max(by_name["hbm_pool_occupancy_ratio"]) > 0.0
        # the shared block-aligned prefix makes COW sharing observable
        assert max(by_name["kv_blocks_shared"]) >= 1.0
        assert max(by_name["hbm_pool_high_water_ratio"]) <= 1.0

    def test_export_byte_identical_and_structured(self, serving_bundle):
        prof, _, _ = serving_bundle
        text = prof.export_perfetto()
        assert seeded_serving_profile().export_perfetto() == text
        counts = perfetto_track_counts(text)
        exp = _baseline()["export"]["serve"]
        assert counts["phase_tracks"] >= 4
        assert counts["counter_tracks"] >= 2
        assert counts == exp
        _export_step_conservation(text)

    def test_chaos_trips_exactly_the_slowed_phase(self):
        slow = seeded_serving_profile(
            chaos_extra_ticks={"decode_chunk": 7})
        gates = _baseline()["gates"]
        fails = profile_gate_failures(slow.summary(),
                                      {"serve": gates["serve"]})
        assert fails, "injected slowdown did not trip the gate"
        assert all("decode_chunk" in f for f in fails), fails


class TestSeededTrain:
    def test_summary_matches_recorded_baseline(self, train_prof):
        rec = _baseline()["recorded"]["train"]
        s = train_prof.summary()["train"]
        assert s["conservation_ok"] and s["steps_dropped"] == 0
        assert s["steps"] == rec["steps"]
        assert s["step_ticks"] == rec["step_ticks"]
        assert s["phase_ticks"] == rec["phase_ticks"]

    def test_gate_clean_leg_passes(self, train_prof):
        gates = _baseline()["gates"]
        assert profile_gate_failures(
            train_prof.summary(), {"train": gates["train"]}) == []

    def test_catalog_attached(self, train_prof):
        import jax

        e = train_prof.catalog["train_step"]
        assert e["flops_per_token"] == 552960   # tiny @ seq 16 golden
        # grad allreduce rides the dp axis: nothing to reduce on one
        # device, the full ring bill 2*(n-1)/n * 4B*params otherwise.
        ndev = jax.device_count()
        expected = {} if ndev == 1 else {
            "dp": 2 * (ndev - 1) * (4 * 90112) // ndev}
        assert e["collective_bytes"] == expected
