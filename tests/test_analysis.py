"""Tests for the static analyzer (ISSUE 16).

Per rule: a true positive, a true negative, a suppression honored,
and the reason-is-mandatory contract (a reasonless allow-comment
suppresses nothing and is itself reported as KF100). Plus the
self-check that matters most: the analyzer exits clean on this repo.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kubeflow_tpu.analysis import run_analysis, scan_file, scan_tree
from kubeflow_tpu.analysis.engine import render_human, render_json
from kubeflow_tpu.analysis.rules import (
    ClockDomainRule,
    JournalBeforeMutateRule,
    JournalDisciplineRule,
    MetricHygieneRule,
    ReadAliasingRule,
    VacuousGateRule,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "kubeflow_tpu")


def _scan(tmp_path, source, rules, relpath="mod.py"):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(source))
    return scan_file(str(p), rules, relpath=relpath)


def _active(findings):
    return [f for f in findings if not f.suppressed]


# ---------------------------------------------------------------- KF101


class TestClockDomain:
    def test_wall_clock_in_tick_domain_flagged(self, tmp_path):
        src = """
            import time

            def step():
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        assert [f.rule for f in _active(fs)] == ["KF101"]
        assert "time.time()" in fs[0].message

    def test_outside_tick_domain_not_flagged(self, tmp_path):
        src = """
            import time

            def step():
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="utils/anything.py")
        assert fs == []

    def test_now_fn_default_reference_not_flagged(self, tmp_path):
        # Referencing time.time (no call) is the injection seam itself.
        src = """
            import time

            def step(now_fn=None):
                now_fn = now_fn or time.time
                return now_fn()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="obs/slo.py")
        assert fs == []

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            import time

            def dump():
                # kftpu: allow(KF101): host timestamp for the artifact
                now = time.time()
                return now
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="obs/flight.py")
        assert _active(fs) == []
        assert [f.rule for f in fs] == ["KF101"]
        assert fs[0].suppressed
        assert fs[0].reason == "host timestamp for the artifact"

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            import time

            def dump():
                # kftpu: allow(KF101)
                now = time.time()
                return now
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="obs/flight.py")
        # The original finding stays ACTIVE, and the comment itself is
        # reported once as KF100.
        rules = sorted(f.rule for f in _active(fs))
        assert rules == ["KF100", "KF101"]


# ---------------------------------------------------------------- KF102


class TestJournalDiscipline:
    def test_open_append_on_jsonl_flagged(self, tmp_path):
        src = """
            def log(path, rec):
                with open(path + "/events.jsonl", "a") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert [f.rule for f in _active(fs)] == ["KF102"]

    def test_module_jsonl_constant_taints_appends(self, tmp_path):
        src = """
            JOURNAL = "wal.jsonl"

            def log(path, rec):
                with open(path, mode="ab") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert [f.rule for f in _active(fs)] == ["KF102"]

    def test_utils_layer_exempt(self, tmp_path):
        # utils/ IS the discipline layer — JsonlJournal lives there.
        src = """
            def append(path, rec):
                with open(path + "/events.jsonl", "a") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="utils/journal.py")
        assert fs == []

    def test_non_jsonl_append_not_flagged(self, tmp_path):
        src = """
            def log(path, rec):
                with open(path + "/events.log", "a") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert fs == []

    def test_apply_before_journal_flagged(self, tmp_path):
        src = """
            class C:
                def commit(self, rec):
                    self._apply_update(rec)
                    self.journal_write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert [f.rule for f in _active(fs)] == ["KF102"]
        assert "precedes the journal write" in fs[0].message

    def test_journal_before_apply_ok(self, tmp_path):
        src = """
            class C:
                def commit(self, rec):
                    self.journal_write(rec)
                    self._apply_update(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert fs == []

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            def log(path, rec):
                # kftpu: allow(KF102): pre-journal bootstrap writer
                with open(path + "/events.jsonl", "a") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert _active(fs) == []
        assert fs[0].suppressed

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            def log(path, rec):
                # kftpu: allow(KF102)
                with open(path + "/events.jsonl", "a") as f:
                    f.write(rec)
        """
        fs = _scan(tmp_path, src, [JournalDisciplineRule()],
                   relpath="controlplane/thing.py")
        assert sorted(f.rule for f in _active(fs)) == ["KF100", "KF102"]


# ---------------------------------------------------------------- KF103


class TestMetricHygiene:
    def test_bad_name_flagged(self, tmp_path):
        src = """
            def wire(reg):
                reg.counter("Bad-Name_total")
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF103"]
        assert "does not match" in fs[0].message

    def test_dynamic_name_flagged(self, tmp_path):
        src = """
            def wire(reg, suffix):
                reg.gauge("kftpu_" + suffix)
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF103"]
        assert "not a string literal" in fs[0].message

    def test_good_registration_clean(self, tmp_path):
        src = """
            def wire(reg):
                reg.counter("kftpu_widgets_total", labels=("outcome",))
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        fs += list(rule.finalize())
        assert fs == []

    def test_duplicate_registration_flagged(self, tmp_path):
        src = """
            def wire(reg):
                reg.counter("kftpu_widgets_total")

            def wire_again(reg):
                reg.counter("kftpu_widgets_total")
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        fs += list(rule.finalize())
        assert [f.rule for f in _active(fs)] == ["KF103"]
        assert "more than one site" in fs[0].message

    def test_too_many_labels_flagged(self, tmp_path):
        src = """
            def wire(reg):
                reg.counter("kftpu_widgets_total",
                            labels=("a", "b", "c", "d", "e", "f"))
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        assert any("cardinality hazard" in f.message for f in _active(fs))

    def test_docs_cross_check(self, tmp_path):
        docs = tmp_path / "observability.md"
        docs.write_text(textwrap.dedent("""\
            # Obs

            Prose mention of `kftpu_undocumented_total` does not count.

            ## Metric name inventory

            | name | type |
            |---|---|
            | `kftpu_documented_total` | counter |
            | `kftpu_component_up_<target>` | gauge |

            ## Next section
        """))
        src = """
            def wire(reg):
                reg.counter("kftpu_documented_total")
                reg.gauge("kftpu_component_up_prober")
                reg.counter("kftpu_undocumented_total")
        """
        rule = MetricHygieneRule(docs_inventory=str(docs))
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        fs += list(rule.finalize())
        active = _active(fs)
        assert len(active) == 1
        assert "kftpu_undocumented_total" in active[0].message

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            def wire(reg, target):
                reg.gauge(
                    # kftpu: allow(KF103): per-target name, sanitized
                    "kftpu_up_" + target)
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        assert _active(fs) == []
        assert fs and fs[0].suppressed

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            def wire(reg, target):
                reg.gauge(
                    # kftpu: allow(KF103)
                    "kftpu_up_" + target)
        """
        rule = MetricHygieneRule(docs_inventory="")
        fs = _scan(tmp_path, src, [rule], relpath="x.py")
        assert sorted(f.rule for f in _active(fs)) == ["KF100", "KF103"]


# ---------------------------------------------------------------- KF104


class TestReadAliasing:
    def test_mutation_through_alias_flagged(self, tmp_path):
        src = """
            def reconcile(api):
                job = api.get("Job", "j", copy=False)
                job.status.phase = "Running"
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF104"]
        assert "mutation through" in fs[0].message

    def test_mutating_method_on_alias_flagged(self, tmp_path):
        src = """
            def reconcile(api):
                for job in api.list("Job", copy=False):
                    job.status.conditions.append("x")
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF104"]
        assert ".append()" in fs[0].message

    def test_alias_stored_on_attribute_flagged(self, tmp_path):
        src = """
            class C:
                def cache(self, api):
                    job = api.get("Job", "j", copy=False)
                    self.last = job
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF104"]
        assert "outlives the call frame" in fs[0].message

    def test_rebind_to_private_copy_clears_alias(self, tmp_path):
        # The sanctioned peek-then-reread idiom: the copy=False peek is
        # read-only; before writing, the name is rebound to a private
        # copy. No finding.
        src = """
            def reconcile(api):
                job = api.get("Job", "j", copy=False)
                if job.status.phase == "Done":
                    return
                job = api.get("Job", "j")
                job.status.phase = "Running"
                api.put(job)
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert fs == []

    def test_read_only_use_not_flagged(self, tmp_path):
        src = """
            def count(api):
                return len(api.list("Job", copy=False))
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert fs == []

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            def reconcile(api):
                job = api.get("Job", "j", copy=False)
                # kftpu: allow(KF104): single-threaded test helper
                job.status.phase = "Running"
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert _active(fs) == []
        assert fs[0].suppressed

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            def reconcile(api):
                job = api.get("Job", "j", copy=False)
                # kftpu: allow(KF104)
                job.status.phase = "Running"
        """
        fs = _scan(tmp_path, src, [ReadAliasingRule()], relpath="x.py")
        assert sorted(f.rule for f in _active(fs)) == ["KF100", "KF104"]


# ---------------------------------------------------------------- KF105


class TestVacuousGate:
    def test_gate_without_guard_flagged(self, tmp_path):
        src = """
            def check_storm_gates(report):
                out = []
                if report.errors:
                    out.append("errors")
                return out
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert [f.rule for f in _active(fs)] == ["KF105"]
        assert "zero-observation guard" in fs[0].message

    def test_gate_with_zero_guard_ok(self, tmp_path):
        src = """
            def check_storm_gates(report):
                out = []
                if report.submitted == 0:
                    out.append("vacuous: nothing submitted")
                    return out
                if report.errors:
                    out.append("errors")
                return out
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert fs == []

    def test_gate_delegating_to_gate_ok(self, tmp_path):
        src = """
            def check_all_gates(report):
                return check_storm_gates(report)

            def check_storm_gates(report):
                return ["empty"] if report.submitted == 0 else []
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert fs == []

    def test_non_gate_function_ignored(self, tmp_path):
        src = """
            def summarize(report):
                return [e for e in report.errors]
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert fs == []

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            # kftpu: allow(KF105): wrapper; inner gate owns the guard
            def check_wrapper_gates(report):
                return _inner(report)
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert _active(fs) == []
        assert fs[0].suppressed

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            # kftpu: allow(KF105)
            def check_wrapper_gates(report):
                return _inner(report)
        """
        fs = _scan(tmp_path, src, [VacuousGateRule()], relpath="x.py")
        assert sorted(f.rule for f in _active(fs)) == ["KF100", "KF105"]


# ---------------------------------------------------------------- KF106


class TestJournalBeforeMutate:
    def test_seam_call_without_journal_flagged(self, tmp_path):
        src = """
            def kick(self, manager):
                manager.kick_timers(60.0)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert [f.rule for f in _active(fs)] == ["KF106"]
        assert "kick_timers" in fs[0].message

    def test_journal_before_seam_ok(self, tmp_path):
        src = """
            def tick(self, pb, rec):
                self._journal_rec(rec)
                pb.action(rec)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert fs == []

    def test_seam_before_journal_flagged(self, tmp_path):
        # The ordering matters, not mere presence of a journal call —
        # acting first loses the record a crash-replay depends on.
        src = """
            def tick(self, pb, rec):
                pb.action(rec)
                self._journal_rec(rec)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert [f.rule for f in _active(fs)] == ["KF106"]

    def test_action_bound_closure_ok(self, tmp_path):
        # Factory closures bound as Playbook(action=...) run strictly
        # after the controller's journal write — covered one frame up.
        src = """
            def drain(lb):
                def _act(rec):
                    lb.set_backends([])
                    return {}
                return Playbook(name="d", objective="o", action=_act)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert fs == []

    def test_seam_in_precheck_closure_flagged(self, tmp_path):
        # Prechecks are READ-ONLY probes that run before anything is
        # journaled — a mutation there is exactly the bug class.
        src = """
            def drain(lb):
                def _precheck(rec):
                    lb.set_backends([])
                    return True
                def _act(rec):
                    return {}
                return Playbook(name="d", objective="o", action=_act,
                                precheck=_precheck)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert [f.rule for f in _active(fs)] == ["KF106"]

    def test_outside_remediation_module_not_flagged(self, tmp_path):
        src = """
            def kick(self, manager):
                manager.kick_timers(60.0)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="controlplane/manager.py")
        assert fs == []

    def test_suppression_with_reason_honored(self, tmp_path):
        src = """
            def kick(self, manager):
                # kftpu: allow(KF106): replay path; journaled upstream
                manager.kick_timers(60.0)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert _active(fs) == []
        assert fs[0].suppressed

    def test_reasonless_suppression_rejected(self, tmp_path):
        src = """
            def kick(self, manager):
                # kftpu: allow(KF106)
                manager.kick_timers(60.0)
        """
        fs = _scan(tmp_path, src, [JournalBeforeMutateRule()],
                   relpath="obs/remediate.py")
        assert sorted(f.rule for f in _active(fs)) == ["KF100", "KF106"]


# ------------------------------------------------------------- engine


class TestEngine:
    def test_syntax_error_reported_not_raised(self, tmp_path):
        p = tmp_path / "bad.py"
        p.write_text("def broken(:\n")
        fs = scan_file(str(p), [ClockDomainRule()])
        assert [f.rule for f in fs] == ["KF001"]

    def test_suppression_scans_up_through_comment_block(self, tmp_path):
        src = """
            import time

            def step():
                # Multi-line justification: the artifact timestamp is
                # host-side metadata, not simulated state.
                # kftpu: allow(KF101): artifact timestamp, host-side

                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        assert _active(fs) == []
        assert fs[0].suppressed

    def test_suppression_does_not_leak_past_code(self, tmp_path):
        # An allow-comment above intervening CODE must not suppress a
        # finding below that code.
        src = """
            import time

            def step():
                # kftpu: allow(KF101): covers only the next line
                a = 1
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        assert [f.rule for f in _active(fs)] == ["KF101"]

    def test_suppression_wrong_rule_id_ignored(self, tmp_path):
        src = """
            import time

            def step():
                # kftpu: allow(KF102): wrong rule entirely
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        assert [f.rule for f in _active(fs)] == ["KF101"]

    def test_render_json_splits_active_and_suppressed(self, tmp_path):
        src = """
            import time

            def a():
                return time.time()

            def b():
                # kftpu: allow(KF101): justified
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        doc = json.loads(render_json(fs))
        assert len(doc["findings"]) == 1
        assert len(doc["suppressed"]) == 1
        assert doc["suppressed"][0]["reason"] == "justified"

    def test_render_human_counts(self, tmp_path):
        src = """
            import time

            def a():
                return time.time()
        """
        fs = _scan(tmp_path, src, [ClockDomainRule()],
                   relpath="chaos/soak.py")
        text = render_human(fs)
        assert "1 finding(s), 0 suppressed" in text
        assert "KF101" in text

    def test_scan_tree_skips_pycache(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "__pycache__").mkdir(parents=True)
        (pkg / "__pycache__" / "junk.py").write_text("import time\n")
        (pkg / "ok.py").write_text("x = 1\n")
        fs = scan_tree(str(pkg), [ClockDomainRule()])
        assert fs == []


# --------------------------------------------------- the repo is clean


class TestRepoClean:
    def test_package_analyzes_clean_within_budget(self):
        """The headline acceptance check: zero active findings on the
        real package and at most 10 justified suppressions."""
        findings = run_analysis(PKG)
        active = [f for f in findings if not f.suppressed]
        assert active == [], "\n".join(f.render() for f in active)
        suppressed = [f for f in findings if f.suppressed]
        assert len(suppressed) <= 10
        assert all(f.reason for f in suppressed)

    @pytest.mark.slow
    def test_cli_exit_codes(self, tmp_path):
        env = dict(os.environ)
        # Clean tree -> 0.
        r = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", PKG],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr
        # A dirty file -> 1.
        bad = tmp_path / "chaos"
        bad.mkdir()
        f = bad / "soak.py"
        f.write_text("import time\n\ndef t():\n    return time.time()\n")
        r = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", str(tmp_path)],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert r.returncode == 1
        # A missing path -> 2.
        r = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis",
             str(tmp_path / "nope")],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert r.returncode == 2
