"""HF checkpoint import (tools/import_hf.py): logit parity with torch.

Builds a tiny random-init transformers LlamaForCausalLM, converts its
state dict, and pins that our flax Llama reproduces the torch logits —
the only test that actually proves the weight-layout mapping (transposes,
per-head reshapes, RoPE convention) is right.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from kubeflow_tpu.models import Llama
from kubeflow_tpu.tools.import_hf import (
    config_from_hf,
    llama_params_from_state_dict,
)

HF_CFG = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=128,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    tie_word_embeddings=False,
    attention_bias=False,
    mlp_bias=False,
)


def _torch_model():
    cfg = transformers.LlamaConfig(**HF_CFG)
    torch.manual_seed(0)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def torch_model():
    return _torch_model()


@pytest.mark.parametrize("scan_layers", [False, True])
def test_logits_match_torch(torch_model, scan_layers):
    cfg = config_from_hf(
        HF_CFG, scan_layers=scan_layers, remat=False,
        param_dtype=jnp.float32, dtype=jnp.float32,
    )
    params = llama_params_from_state_dict(
        torch_model.state_dict(), cfg
    )
    tokens = np.array([[3, 14, 15, 92, 65, 35], [8, 9, 7, 9, 3, 2]])
    with torch.no_grad():
        want = torch_model(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(
        Llama(cfg).apply({"params": params}, jnp.asarray(tokens)),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_tied_embeddings_have_no_lm_head(torch_model):
    hf = dict(HF_CFG, tie_word_embeddings=True)
    cfg_t = transformers.LlamaConfig(**hf)
    torch.manual_seed(1)
    m = transformers.LlamaForCausalLM(cfg_t)
    m.eval()
    cfg = config_from_hf(
        hf, scan_layers=False, remat=False,
        param_dtype=jnp.float32, dtype=jnp.float32,
    )
    assert cfg.tie_embeddings
    params = llama_params_from_state_dict(m.state_dict(), cfg)
    assert "lm_head" not in params
    tokens = np.array([[1, 2, 3, 4]])
    with torch.no_grad():
        want = m(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(
        Llama(cfg).apply({"params": params}, jnp.asarray(tokens)),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_config_mapping_defaults():
    cfg = config_from_hf(HF_CFG)
    assert cfg.vocab_size == 128
    assert cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.mlp_dim == 112


def test_unsupported_features_raise():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(dict(
            HF_CFG, rope_scaling={"rope_type": "llama3", "factor": 8.0}
        ))
    with _pytest.raises(ValueError, match="attention_bias"):
        config_from_hf(dict(HF_CFG, attention_bias=True))
    with _pytest.raises(ValueError, match="hidden_act"):
        config_from_hf(dict(HF_CFG, hidden_act="gelu"))


MIXTRAL_HF_CFG = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=96,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=128,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    tie_word_embeddings=False,
    num_local_experts=4,
    num_experts_per_tok=2,
    router_aux_loss_coef=0.02,
)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_mixtral_logits_match_torch(scan_layers):
    from kubeflow_tpu.models import Mixtral
    from kubeflow_tpu.tools.import_hf import (
        mixtral_config_from_hf,
        mixtral_params_from_state_dict,
    )

    tcfg = transformers.MixtralConfig(**MIXTRAL_HF_CFG)
    torch.manual_seed(0)
    tm = transformers.MixtralForCausalLM(tcfg)
    tm.eval()
    # capacity_factor high enough that no token is dropped — HF has no
    # capacity limit, so parity only holds drop-free.
    cfg = mixtral_config_from_hf(
        MIXTRAL_HF_CFG, scan_layers=scan_layers, remat=False,
        capacity_factor=8.0,
        param_dtype=jnp.float32, dtype=jnp.float32,
    )
    params = mixtral_params_from_state_dict(tm.state_dict(), cfg)
    tokens = np.array([[3, 14, 15, 92, 65, 35], [8, 9, 7, 9, 3, 2]])
    with torch.no_grad():
        want = tm(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(
        Mixtral(cfg).apply({"params": params}, jnp.asarray(tokens)),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_export_round_trips(torch_model, scan_layers):
    """params -> HF state dict -> params reproduces the original tree
    exactly (and the exported dict loads into a torch model)."""
    from kubeflow_tpu.tools.import_hf import llama_state_dict_from_params

    cfg = config_from_hf(
        HF_CFG, scan_layers=scan_layers, remat=False,
        param_dtype=jnp.float32, dtype=jnp.float32,
    )
    params = llama_params_from_state_dict(torch_model.state_dict(), cfg)
    sd = llama_state_dict_from_params(params, cfg)
    # load exported dict into a fresh torch model: keys + shapes line up
    m2 = _torch_model()
    m2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    params2 = llama_params_from_state_dict(sd, cfg)
    for (p1, l1), (p2, l2) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(params2)[0],
    ):
        assert p1 == p2
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
