"""Native C++ data loader: build, determinism, file crops, concurrency."""

import numpy as np
import pytest

from kubeflow_tpu.train.native_loader import (
    NativeLoaderUnavailable,
    NativeTokenLoader,
)


@pytest.fixture(scope="module")
def loader_cls():
    try:
        ldr = NativeTokenLoader(batch_size=2, seq_len=8, seed=0)
    except NativeLoaderUnavailable as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    ldr.close()
    return NativeTokenLoader


class TestNativeLoader:
    def test_shapes_and_vocab_bounds(self, loader_cls):
        ldr = loader_cls(batch_size=4, seq_len=32, vocab_size=1000, seed=1)
        try:
            for _ in range(3):
                b = next(ldr)
                assert b["inputs"].shape == (4, 32)
                assert b["inputs"].dtype == np.int32
                assert b["inputs"].min() >= 0
                assert b["inputs"].max() < 1000
        finally:
            ldr.close()

    def test_deterministic_across_instances(self, loader_cls):
        def take(n):
            ldr = loader_cls(batch_size=2, seq_len=16, seed=7,
                             num_threads=3)
            try:
                return [next(ldr)["inputs"].copy() for _ in range(n)]
            finally:
                ldr.close()

        a, b = take(5), take(5)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_streams_differ_by_seed(self, loader_cls):
        a = loader_cls(batch_size=2, seq_len=16, seed=1)
        b = loader_cls(batch_size=2, seq_len=16, seed=2)
        try:
            assert not np.array_equal(next(a)["inputs"], next(b)["inputs"])
        finally:
            a.close()
            b.close()

    def test_learnable_structure(self, loader_cls):
        """The synthetic stream must have next-token structure (like
        data.py's generator) so loss curves mean something."""
        ldr = loader_cls(batch_size=8, seq_len=256, vocab_size=256, seed=3)
        try:
            b = next(ldr)["inputs"]
        finally:
            ldr.close()
        prev, nxt = b[:, :-1].ravel(), b[:, 1:].ravel()
        frac = np.mean(nxt == (prev * 7 + 3) % 256)
        assert 0.6 < frac < 0.9          # ~75% deterministic successor

    def test_token_file_crops(self, loader_cls, tmp_path):
        corpus = np.arange(10000, dtype=np.int32)
        path = tmp_path / "tokens.bin"
        corpus.tofile(path)
        ldr = loader_cls(batch_size=4, seq_len=64, seed=5,
                         token_file=str(path))
        try:
            b = next(ldr)["inputs"]
        finally:
            ldr.close()
        # Each row is a contiguous crop of the corpus (consecutive ints).
        for row in b:
            assert row[0] >= 0 and row[-1] < 10000
            np.testing.assert_array_equal(np.diff(row), 1)

    def test_token_file_too_small_errors(self, loader_cls, tmp_path):
        path = tmp_path / "tiny.bin"
        np.arange(4, dtype=np.int32).tofile(path)
        with pytest.raises(NativeLoaderUnavailable):
            loader_cls(batch_size=1, seq_len=64, token_file=str(path))

    def test_missing_file_errors(self, loader_cls, tmp_path):
        with pytest.raises(NativeLoaderUnavailable):
            loader_cls(batch_size=1, seq_len=8,
                       token_file=str(tmp_path / "nope.bin"))

    def test_throughput_counter(self, loader_cls):
        ldr = loader_cls(batch_size=2, seq_len=8, seed=0, queue_depth=8)
        try:
            for _ in range(10):
                next(ldr)
            assert ldr.batches_produced >= 10
        finally:
            ldr.close()

    def test_out_of_vocab_corpus_errors(self, loader_cls, tmp_path):
        """A corpus with tokens outside [0, vocab) must fail at open —
        clamped-garbage training is silent otherwise."""
        bad = np.array([1, 2, 999999, 3] * 100, dtype=np.int32)
        path = tmp_path / "bad.bin"
        bad.tofile(path)
        with pytest.raises(NativeLoaderUnavailable):
            loader_cls(batch_size=1, seq_len=8, vocab_size=1000,
                       token_file=str(path))

    def test_queue_depth_one_respected(self, loader_cls):
        ldr = loader_cls(batch_size=2, seq_len=8, seed=0, queue_depth=1)
        try:
            a = next(ldr)["inputs"].copy()
            b = next(ldr)["inputs"]
            assert not np.array_equal(a, b)
        finally:
            ldr.close()

    def test_stall_counter(self, loader_cls):
        """stalls counts next() calls that beat the producers — the
        loader-fed bench asserts this stays ~0 during timed steps."""
        import time

        ldr = loader_cls(batch_size=4, seq_len=64, seed=0,
                         num_threads=2, queue_depth=4)
        try:
            assert ldr.stalls >= 0
            # let the ring fill; steady-state pops must not add stalls
            time.sleep(0.3)
            base = ldr.stalls
            for _ in range(3):
                next(ldr)
                time.sleep(0.05)
            assert ldr.stalls == base
        finally:
            ldr.close()
        assert ldr.stalls == 0      # closed handle reports 0, not crash

    def test_closed_loader_raises_not_segfaults(self, loader_cls):
        ldr = loader_cls(batch_size=1, seq_len=8, seed=0)
        ldr.close()
        with pytest.raises(StopIteration):
            next(ldr)
        assert ldr.batches_produced == 0
