"""Worker entrypoint: env contract -> real (single-process) training runs.

The multi-process jax.distributed path is exercised by tests/e2e; here the
contract pieces that burned before are pinned: hparam overrides against the
frozen TrainConfig, swept total_steps changing the steps actually run, the
termination report's loss key, and pp wiring into the model's pipeline.
"""

import json
import os

import pytest

from kubeflow_tpu.train import runner


def _env(tmp_path, **over):
    env = {
        "KFTPU_MODEL": "llama-tiny",
        "KFTPU_TRAIN_STEPS": "2",
        "KFTPU_BATCH_PER_HOST": "8",  # divisible by dp=8 (virtual devices)
        "KFTPU_SEQ_LEN": "16",
        "KFTPU_MESH": json.dumps({"dp": -1}),
        "KFTPU_TERMINATION_LOG": str(tmp_path / "term.json"),
    }
    env.update(over)
    return env


def _run(monkeypatch, tmp_path, **over):
    for k in list(os.environ):
        if k.startswith("KFTPU_"):
            monkeypatch.delenv(k)
    for k, v in _env(tmp_path, **over).items():
        monkeypatch.setenv(k, v)
    cfg = runner.env_config()
    assert runner.run(cfg) == 0
    return json.loads((tmp_path / "term.json").read_text())


class TestRunnerContract:
    def test_basic_run_reports_loss(self, monkeypatch, tmp_path):
        report = _run(monkeypatch, tmp_path)
        assert report["steps"] == 2
        assert report["loss"] > 0
        assert report["tokens_per_sec"] > 0

    def test_hparam_overrides_frozen_trainconfig(self, monkeypatch, tmp_path):
        """KFTPU_HPARAMS must survive TrainConfig being frozen, and a swept
        total_steps must change the number of steps actually run."""
        report = _run(
            monkeypatch, tmp_path,
            KFTPU_HPARAMS=json.dumps(
                {"learning_rate": "0.01", "total_steps": "3"}
            ),
        )
        assert report["steps"] == 3

    def test_seed_controls_init_and_data(self, monkeypatch, tmp_path):
        """KFTPU_SEED: same seed reproduces the run; different seeds
        produce different losses (init + data stream both keyed)."""
        a = _run(monkeypatch, tmp_path, KFTPU_SEED="1")
        b = _run(monkeypatch, tmp_path, KFTPU_SEED="1")
        c = _run(monkeypatch, tmp_path, KFTPU_SEED="2")
        assert a["loss"] == b["loss"]
        assert a["loss"] != c["loss"]

    def test_eval_every_reports_heldout_metrics(self, monkeypatch, tmp_path):
        """KFTPU_EVAL_EVERY wires Trainer.evaluate into the loop and the
        final held-out score into the termination report (the StudyJob
        objective channel: objective: eval_loss)."""
        report = _run(
            monkeypatch, tmp_path,
            KFTPU_EVAL_EVERY="1", KFTPU_EVAL_BATCHES="2",
        )
        assert report["eval_loss"] > 0
        assert report["eval_perplexity"] == pytest.approx(
            __import__("math").exp(report["eval_loss"]), rel=1e-6)
        # Train loss on the training batch and eval loss on the held-out
        # stream are distinct numbers.
        assert report["eval_loss"] != report["loss"]

    def test_model_kw_reaches_the_registry_factory(self, monkeypatch,
                                                   tmp_path):
        """KFTPU_MODEL_KW (JSON kwargs for the model factory) is how a
        flagship job requests bf16 params / a remat policy; the
        admission-time capacity planner reads the same contract, so the
        runner must actually honor it."""
        report = _run(
            monkeypatch, tmp_path,
            KFTPU_MODEL_KW=json.dumps(
                {"param_dtype": "bfloat16", "remat": False}),
        )
        assert report["loss"] > 0
        # a bogus kwarg fails loudly rather than silently training a
        # different model than the planner accounted for
        with pytest.raises(TypeError):
            _run(monkeypatch, tmp_path,
                 KFTPU_MODEL_KW=json.dumps({"no_such_knob": 1}))

    def test_pp_mesh_requires_pipeline_support(self, monkeypatch, tmp_path):
        with pytest.raises(ValueError, match="pipeline"):
            _run(
                monkeypatch, tmp_path,
                KFTPU_MODEL="mixtral-tiny",
                KFTPU_MESH=json.dumps({"dp": -1, "pp": 2}),
            )

    def test_trace_dir_writes_profile(self, monkeypatch, tmp_path):
        """KFTPU_TRACE_DIR must produce an actual jax.profiler capture
        (SURVEY §5 Tracing: something has to *produce* the trace the
        Tensorboard CR serves)."""
        trace = tmp_path / "traces"
        _run(
            monkeypatch, tmp_path,
            KFTPU_TRAIN_STEPS="4",
            KFTPU_TRACE_DIR=str(trace),
            KFTPU_TRACE_STEPS="1",
        )
        profiles = list(trace.rglob("*.xplane.pb"))
        assert profiles, f"no trace written under {trace}"

    def test_profile_dir_writes_step_profile(self, monkeypatch, tmp_path):
        """KFTPU_PROFILE_DIR: the runner brackets its loop with the step
        profiler (obs/profiler.py, ISSUE 19) and writes profile.json +
        the perfetto render at exit — conservation holding in the real
        wall-clock domain, every step present, cost catalog attached."""
        pdir = tmp_path / "profile"
        _run(monkeypatch, tmp_path, KFTPU_TRAIN_STEPS="3",
             KFTPU_PROFILE_DIR=str(pdir))
        data = json.loads((pdir / "profile.json").read_text())
        s = data["summary"]["train"]
        assert s["steps"] == 3 and s["steps_dropped"] == 0
        assert s["conservation_ok"]
        assert set(s["phase_ticks"]) >= {"data_load", "host_to_device",
                                         "step_compute"}
        assert data["catalog"]["train_step"]["flops_per_token"] > 0
        assert (pdir / "profile.perfetto.json").exists()

    def test_pp_mesh_pipelines_dense_model(self, monkeypatch, tmp_path):
        # batch 8 = 2 microbatches x mb 4, mb divisible by dp=4 (8 devs / pp 2).
        report = _run(
            monkeypatch, tmp_path,
            KFTPU_BATCH_PER_HOST="8",
            KFTPU_MESH=json.dumps({"dp": -1, "pp": 2}),
        )
        assert report["loss"] > 0

    @staticmethod
    def _require_toolchain():
        from kubeflow_tpu.train.native_loader import (
            NativeLoaderUnavailable,
            NativeTokenLoader,
        )

        try:
            NativeTokenLoader(batch_size=1, seq_len=4).close()
        except NativeLoaderUnavailable as e:
            pytest.skip(f"native toolchain unavailable: {e}")

    def test_native_loader_with_corpus(self, monkeypatch, tmp_path):
        """KFTPU_DATA_PATH drives training from a real tokenised corpus
        through the C++ loader."""
        import numpy as np

        self._require_toolchain()
        corpus = (np.arange(50000, dtype=np.int32) % 256)
        path = tmp_path / "corpus.bin"
        corpus.tofile(path)
        report = _run(
            monkeypatch, tmp_path,
            KFTPU_LOADER="native",
            KFTPU_DATA_PATH=str(path),
        )
        assert report["loss"] > 0

    def test_native_loader_missing_corpus_fails(self, monkeypatch, tmp_path):
        from kubeflow_tpu.train.native_loader import NativeLoaderUnavailable

        self._require_toolchain()
        with pytest.raises(NativeLoaderUnavailable):
            _run(
                monkeypatch, tmp_path,
                KFTPU_DATA_PATH=str(tmp_path / "missing.bin"),
            )
