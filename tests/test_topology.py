import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.topology import (
    AxisSpec,
    get_slice,
    list_slices,
    make_mesh,
    plan_mesh,
)
from kubeflow_tpu.topology.slices import TpuGeneration


class TestSliceCatalogue:
    def test_v5e_16_shape(self):
        s = get_slice("v5e-16")
        assert s.num_chips == 16
        assert s.topology.dims == (4, 4)
        assert s.num_hosts == 4
        assert s.chips_per_host == 4
        assert s.gke_topology == "4x4"

    def test_v5e_single_host(self):
        s = get_slice("v5e-8")
        assert s.num_hosts == 1
        assert s.chips_per_host == 8

    def test_v4_is_3d_torus_naming(self):
        s = get_slice("v4-128")
        assert s.topology.dims == (4, 4, 4)
        assert all(s.topology.wrap)  # full cube → torus
        assert s.generation.is_3d

    def test_node_selectors(self):
        sel = get_slice("v5e-64").node_selectors()
        assert sel["cloud.google.com/gke-tpu-topology"] == "8x8"
        assert "tpu" in sel["cloud.google.com/gke-tpu-accelerator"]

    def test_unknown_slice_raises(self):
        with pytest.raises(KeyError):
            get_slice("v99-3")

    def test_catalogue_nonempty(self):
        assert "v5e-16" in list_slices()
        assert "v5p-128" in list_slices()

    def test_hbm_and_flops(self):
        s = get_slice("v5e-16")
        assert s.hbm_gib_total == 16 * 16.0
        assert s.bf16_tflops_total == pytest.approx(16 * 197.0)
        assert TpuGeneration.V5P.hbm_gib_per_chip > TpuGeneration.V5E.hbm_gib_per_chip


class TestAxisSpec:
    def test_resolve_wildcard(self):
        a = AxisSpec(dp=-1, tp=4).resolve(16)
        assert a.dp == 4 and a.tp == 4

    def test_resolve_exact(self):
        a = AxisSpec(dp=2, fsdp=4, tp=2).resolve(16)
        assert a.as_dict() == {
            "dp": 2, "pp": 1, "ep": 1, "fsdp": 4, "sp": 1, "tp": 2,
        }

    def test_resolve_mismatch_raises(self):
        with pytest.raises(ValueError):
            AxisSpec(dp=3).resolve(16)

    def test_two_wildcards_raise(self):
        with pytest.raises(ValueError):
            AxisSpec(dp=-1, tp=-1).resolve(16)


class TestMeshPlan:
    def test_plan_v5e16_tp4(self):
        plan = plan_mesh("v5e-16", AxisSpec(dp=-1, tp=4))
        assert plan.num_chips == 16
        assert plan.axes.tp == 4
        assert plan.axes.dp == 4
        # tp should consume a whole ICI dimension
        assert "ici" in plan.ici_assignment["tp"]

    def test_plan_sp_prefers_ring(self):
        plan = plan_mesh("v5e-256", AxisSpec(dp=-1, sp=16))
        # v5e-256 is a 16x16 torus → sp should land on a wrapped dim
        assert plan.ici_assignment["sp"].startswith("ici")

    def test_plan_overflow_raises(self):
        with pytest.raises(ValueError):
            plan_mesh("v5e-4", AxisSpec(tp=8))

    def test_make_mesh_on_cpu(self, devices8):
        plan = plan_mesh("v5e-8", AxisSpec(dp=2, fsdp=2, tp=2))
        mesh = make_mesh(plan, devices=devices8)
        assert mesh.shape["dp"] == 2
        assert mesh.shape["fsdp"] == 2
        assert mesh.shape["tp"] == 2
        assert mesh.shape["ep"] == 1

        # The mesh is usable: shard an array and reduce over it.
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jnp.arange(16.0).reshape(8, 2)
        xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), "tp")))
        assert float(xs.sum()) == float(np.arange(16.0).sum())


class TestMultisliceMesh:
    """Hybrid ICI+DCN mesh (topology.make_multislice_mesh): the dcn axis
    takes num_slices as its outer factor, other axes stay within a slice."""

    def test_shape_and_slice_grouping(self, devices8):
        from kubeflow_tpu.topology import make_multislice_mesh

        mesh = make_multislice_mesh(
            AxisSpec(dp=2, fsdp=2, tp=2), 2, dcn_axis="dp", devices=devices8
        )
        assert mesh.shape["dp"] == 2
        assert mesh.shape["fsdp"] == 2
        assert mesh.shape["tp"] == 2
        # dp index 0 must hold exactly the first contiguous device block
        # (the first virtual slice); dp index 1 the second.
        dev = np.asarray(mesh.devices)
        first = set(d.id for d in dev[0].ravel())
        second = set(d.id for d in dev[1].ravel())
        assert first == {d.id for d in devices8[:4]}
        assert second == {d.id for d in devices8[4:]}

    def test_trains_a_step(self, devices8):
        from kubeflow_tpu.models import Llama, LlamaConfig
        from kubeflow_tpu.topology import make_multislice_mesh
        from kubeflow_tpu.train import TrainConfig, Trainer
        from kubeflow_tpu.train.data import (
            SyntheticTextConfig,
            synthetic_text,
        )

        mesh = make_multislice_mesh(
            AxisSpec(dp=2, fsdp=2, tp=2), 2, dcn_axis="dp", devices=devices8
        )
        model = Llama(LlamaConfig.tiny(scan_layers=True, remat=True))
        tr = Trainer(model, TrainConfig(task="lm", warmup_steps=1), mesh)
        it = synthetic_text(
            SyntheticTextConfig(batch_size=4, seq_len=32, vocab_size=256)
        )
        batch = tr.shard_batch(
            {k: jnp.asarray(v) for k, v in next(it).items()}
        )
        state = tr.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = tr.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_rejects_bad_axis_and_divisibility(self, devices8):
        from kubeflow_tpu.topology import make_multislice_mesh

        with pytest.raises(ValueError, match="dcn_axis"):
            make_multislice_mesh(
                AxisSpec(dp=4, tp=2), 2, dcn_axis="tp", devices=devices8
            )
        with pytest.raises(ValueError, match="divisible"):
            make_multislice_mesh(
                AxisSpec(dp=2, fsdp=2, tp=2), 4, devices=devices8
            )
