import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    logical_spec,
    merge_rules,
)


class TestLogicalSpec:
    def test_default_mapping(self):
        spec = logical_spec(("act_batch", "act_seq", "act_embed"))
        assert spec == P(("dp", "fsdp"), "sp", None)

    def test_param_mapping(self):
        assert logical_spec(("embed", "mlp")) == P("fsdp", "tp")

    def test_unknown_name_raises(self):
        with pytest.raises(KeyError):
            logical_spec(("act_batch", "bogus_axis"))

    def test_none_dim(self):
        assert logical_spec((None, "heads")) == P(None, "tp")

    def test_merge_rules_override(self):
        rules = merge_rules(DEFAULT_RULES, {"act_seq": None})
        assert logical_spec(("act_seq",), rules) == P(None)

    def test_constrain_under_mesh(self, devices8):
        mesh = Mesh(np.asarray(devices8).reshape(2, 2, 2), ("dp", "fsdp", "tp"))
        rules = merge_rules(DEFAULT_RULES, {})

        @jax.jit
        def f(x):
            with mesh:
                return constrain(x * 2, ("act_batch", None), rules)

        x = jnp.ones((8, 4))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out), 2.0)
