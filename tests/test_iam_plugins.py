"""Cloud-IAM plugin conformance: every plugin behind the Profile
controller's seam must satisfy the same contract.

The reference shipped two cloud-IAM impls behind one Plugin interface —
GCP workload identity (plugin_workload_identity.go:44-166) and AWS IRSA
(plugin_iam.go:32-283). One conformance suite parametrized over both
proves the seam isn't shaped around its only user: idempotent apply,
revoke-on-delete via the finalizer, and the applied-plugins revoke ledger
must hold for each.
"""

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta, Profile, ProfileSpec
from kubeflow_tpu.controlplane.api.types import ProfilePluginSpec
from kubeflow_tpu.controlplane.controllers import ProfileController
from kubeflow_tpu.controlplane.controllers.profile import (
    PLUGIN_FINALIZER,
    AwsIamForServiceAccountPlugin,
    WorkloadIdentityPlugin,
)
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry

CASES = [
    pytest.param(
        WorkloadIdentityPlugin,
        {"gcpServiceAccount": "ml@proj.iam.gserviceaccount.com"},
        "iam.gke.io/gcp-service-account",
        "ml@proj.iam.gserviceaccount.com",
        lambda ns: f"serviceAccount:{ns}/default-editor",
        id="gcp-workload-identity",
    ),
    pytest.param(
        AwsIamForServiceAccountPlugin,
        {"awsIamRole": "arn:aws:iam::12345:role/kf-user"},
        "eks.amazonaws.com/role-arn",
        "arn:aws:iam::12345:role/kf-user",
        lambda ns: f"system:serviceaccount:{ns}:default-editor",
        id="aws-irsa",
    ),
]


@pytest.mark.parametrize(
    "plugin_cls,params,annotation,grant_key,principal", CASES)
class TestPluginConformance:
    def _world(self, plugin):
        api = InMemoryApiServer()
        reg = MetricsRegistry()
        mgr = ControllerManager(api)
        mgr.register(ProfileController(
            api, reg, plugins={plugin.KIND: plugin}))
        return api, mgr

    def _profile(self, plugin_cls, params, name="team-a"):
        return Profile(
            metadata=ObjectMeta(name=name),
            spec=ProfileSpec(
                owner="alice@example.com",
                plugins=[ProfilePluginSpec(kind=plugin_cls.KIND,
                                           params=dict(params))],
            ),
        )

    def test_apply_grants_and_annotates(
            self, plugin_cls, params, annotation, grant_key, principal):
        plugin = plugin_cls()
        api, mgr = self._world(plugin)
        api.create(self._profile(plugin_cls, params))
        mgr.run_until_idle()
        sa = api.get("ServiceAccount", "default-editor", "team-a")
        assert sa.metadata.annotations[annotation] == params[
            list(params)[0]]
        assert principal("team-a") in plugin.iam[grant_key]
        prof = api.get("Profile", "team-a")
        assert prof.status.phase == "Ready"
        assert [p.kind for p in prof.status.applied_plugins] == \
            [plugin_cls.KIND]
        assert PLUGIN_FINALIZER in prof.metadata.finalizers

    def test_apply_is_idempotent(
            self, plugin_cls, params, annotation, grant_key, principal):
        plugin = plugin_cls()
        api, mgr = self._world(plugin)
        api.create(self._profile(plugin_cls, params))
        mgr.run_until_idle()
        # a second full reconcile pass must not duplicate grants or ledger
        ctl = [c for c in mgr.controllers
               if isinstance(c, ProfileController)][0]
        ctl.reconcile("", "team-a")
        ctl.reconcile("", "team-a")
        mgr.run_until_idle()
        assert plugin.iam[grant_key] == {principal("team-a")}
        prof = api.get("Profile", "team-a")
        assert len(prof.status.applied_plugins) == 1

    def test_delete_revokes_via_finalizer(
            self, plugin_cls, params, annotation, grant_key, principal):
        plugin = plugin_cls()
        api, mgr = self._world(plugin)
        api.create(self._profile(plugin_cls, params))
        mgr.run_until_idle()
        api.delete("Profile", "team-a")
        mgr.run_until_idle()
        assert plugin.iam[grant_key] == set()
        assert api.try_get("Profile", "team-a") is None

    def test_ledger_revokes_edited_grant(
            self, plugin_cls, params, annotation, grant_key, principal):
        """Editing the plugin params revokes the OLD grant (the ledger
        diff), not just adds the new one."""
        plugin = plugin_cls()
        api, mgr = self._world(plugin)
        api.create(self._profile(plugin_cls, params))
        mgr.run_until_idle()
        prof = api.get("Profile", "team-a")
        key = list(params)[0]
        new_params = {key: params[key].replace("kf-user", "other")
                      .replace("ml@", "other@")}
        prof.spec.plugins = [ProfilePluginSpec(kind=plugin_cls.KIND,
                                               params=new_params)]
        api.update(prof)
        mgr.run_until_idle()
        assert plugin.iam[grant_key] == set()          # old grant revoked
        assert principal("team-a") in plugin.iam[new_params[key]]
        sa = api.get("ServiceAccount", "default-editor", "team-a")
        assert sa.metadata.annotations[annotation] == new_params[key]

    def test_missing_params_fail_loudly(
            self, plugin_cls, params, annotation, grant_key, principal):
        plugin = plugin_cls()
        api, mgr = self._world(plugin)
        api.create(self._profile(plugin_cls, {}))
        mgr.run_until_idle()
        prof = api.get("Profile", "team-a")
        assert prof.status.phase == "Failed"
        assert prof.status.conditions[-1].reason == "PluginError"


class TestBothRegisteredByDefault:
    def test_default_plugin_set(self):
        api = InMemoryApiServer()
        ctl = ProfileController(api, MetricsRegistry())
        assert set(ctl.plugins) == {
            "WorkloadIdentity", "AwsIamForServiceAccount"}
