"""Schema-grounded manifest validation (VERDICT r4 Missing #4).

The reference vendors the k8s OpenAPI spec so its emitted manifests are
checked against the real API schema (bootstrap/k8sSpec/v1.11.7) and runs
controllers against a real apiserver (profile-controller suite_test.go).
Here the same contract is enforced by the vendored structural schemas
(runtime/k8s_schema.py) + the k8s wire adapter (runtime/k8swire.py):

1. everything release.py emits validates;
2. everything the CONTROLLERS produce validates through to_wire and
   round-trips without spec drift;
3. injected structural errors (wrong field name, wrong type, bad DNS
   name, two-slash annotation key) FAIL — the classes a mirror-image
   parser would wave through;
4. the kubectl adapter refuses to exec an invalid manifest, and the
   kubectl test double rejects invalid incoming objects apiserver-style.
"""

import copy
import json
import subprocess
import sys
from pathlib import Path

import pytest

from kubeflow_tpu.controlplane.api import ObjectMeta
from kubeflow_tpu.controlplane.api.core import (
    Container,
    Pod,
    PodSpec,
    Service,
    ServicePort,
    ServiceSpec,
    Volume,
)
from kubeflow_tpu.controlplane.api.serde import to_dict
from kubeflow_tpu.controlplane.api.types import (
    Notebook,
    NotebookSpec,
    PlatformConfig,
    Profile,
    ProfileSpec,
    TpuJob,
    TpuJobSpec,
)
from kubeflow_tpu.controlplane.platform import Platform
from kubeflow_tpu.controlplane.runtime.k8s_schema import (
    validate,
    validate_metadata,
)
from kubeflow_tpu.controlplane.runtime.k8swire import from_wire, to_wire
from kubeflow_tpu.tools.release import build_k8s_manifests

WIRE_KINDS = ("Pod", "Service", "Namespace", "ServiceAccount",
              "ResourceQuota", "RoleBinding", "VirtualService",
              "AuthorizationPolicy", "Event")


@pytest.fixture(scope="module")
def platform_objects():
    """A reconciled platform with a profile, a notebook and a gang job —
    every wire-crossing kind the controllers emit, as wire manifests."""
    pf = Platform()
    pf.apply_config(PlatformConfig(metadata=ObjectMeta(name="kubeflow-tpu")))
    pf.api.create(Profile(metadata=ObjectMeta(name="team-a"),
                          spec=ProfileSpec(owner="a@x.com",
                                           tpu_chip_quota=32)))
    pf.reconcile()
    pf.api.create(Notebook(metadata=ObjectMeta(name="nb", namespace="team-a"),
                           spec=NotebookSpec(image="jupyter:latest")))
    pf.api.create(TpuJob(metadata=ObjectMeta(name="job", namespace="team-a"),
                         spec=TpuJobSpec(slice_type="v5e-16",
                                         model="llama-tiny")))
    pf.reconcile()
    pf.reconcile()
    out = []
    for kind in WIRE_KINDS:
        items = list(pf.api.list(kind, namespace="team-a"))
        if kind == "Namespace":
            items += list(pf.api.list(kind))
        out.extend((kind, o) for o in items)
    return out


class TestEmittedManifests:
    def test_release_manifests_all_validate(self):
        docs = build_k8s_manifests()
        assert len(docs) >= 20
        kinds = set()
        for d in docs:
            errs = validate(d)
            assert not errs, (d["kind"], d["metadata"]["name"], errs)
            kinds.add(d["kind"])
        # The full fresh-cluster shape is covered, not a token subset.
        assert {"CustomResourceDefinition", "Deployment", "Service",
                "ServiceAccount", "ClusterRole", "ClusterRoleBinding",
                "Namespace", "Secret"} <= kinds

    def test_controller_objects_all_validate(self, platform_objects):
        assert len(platform_objects) >= 15   # pods, services, rbac, ...
        seen = set()
        for kind, obj in platform_objects:
            wire = to_wire(obj)
            errs = validate(wire)
            assert not errs, (kind, obj.metadata.name, errs)
            seen.add(kind)
        assert set(WIRE_KINDS) <= seen, (
            f"fixture no longer produces {set(WIRE_KINDS) - seen}")

    def test_wire_roundtrip_preserves_spec(self, platform_objects):
        for kind, obj in platform_objects:
            wire = json.loads(json.dumps(to_wire(obj)))  # through JSON
            back = from_wire(wire)
            assert to_dict(back).get("spec") == to_dict(obj).get("spec"), (
                kind, obj.metadata.name)


class TestWireShapes:
    """The adapter emits REAL k8s shapes, not the internal ones."""

    def test_pod_wire_shape(self):
        pod = Pod(
            metadata=ObjectMeta(name="w0", namespace="team-a"),
            spec=PodSpec(
                containers=[Container(
                    name="main", image="img:1", ports=[8471],
                    resources={"google.com/tpu": "4"})],
                volumes=[Volume(name="ckpt", pvc="ckpt-claim")],
                service_account="runner",
                scheduler_hints={"gang-size": "4"},
            ),
        )
        wire = to_wire(pod)
        c = wire["spec"]["containers"][0]
        assert c["ports"] == [{"containerPort": 8471}]
        assert c["resources"]["limits"] == {"google.com/tpu": "4"}
        assert c["resources"]["requests"] == {"google.com/tpu": "4"}
        assert wire["spec"]["volumes"][0]["persistentVolumeClaim"] == {
            "claimName": "ckpt-claim"}
        assert wire["spec"]["serviceAccountName"] == "runner"
        # hints ride a single-slash qualified annotation key
        anno = wire["metadata"]["annotations"]
        assert anno["scheduler-hints.tpu.kubeflow.org/gang-size"] == "4"
        assert not validate(wire), validate(wire)

    def test_pod_wire_accepts_real_cluster_extras(self):
        """from_wire must swallow the fields a live apiserver adds."""
        wire = to_wire(Pod(
            metadata=ObjectMeta(name="w0", namespace="team-a"),
            spec=PodSpec(containers=[Container(name="m", image="i")])))
        wire["metadata"]["managedFields"] = [{"manager": "kubectl"}]
        wire["metadata"]["creationTimestamp"] = "2026-01-01T00:00:00Z"
        wire["spec"]["nodeName"] = "node-1"
        wire["spec"]["dnsPolicy"] = "ClusterFirst"
        wire["spec"]["containers"][0]["imagePullPolicy"] = "IfNotPresent"
        wire["status"] = {"phase": "Running", "podIP": "10.0.0.7",
                          "qosClass": "Guaranteed"}
        pod = from_wire(wire)
        assert pod.status.phase == "Running"
        assert pod.status.pod_ip == "10.0.0.7"
        assert pod.metadata.creation_timestamp > 0

    def test_service_wire_shape(self):
        svc = Service(
            metadata=ObjectMeta(name="gang", namespace="team-a"),
            spec=ServiceSpec(selector={"app": "gang"},
                             ports=[ServicePort(name="grpc", port=8471,
                                                target_port=8471)],
                             cluster_ip="None"))
        wire = to_wire(svc)
        assert wire["spec"]["clusterIP"] == "None"
        assert wire["spec"]["ports"][0] == {
            "name": "grpc", "port": 8471, "targetPort": 8471}
        assert not validate(wire)

    def test_istio_kinds_nest_under_spec(self, platform_objects):
        by_kind = {k: o for k, o in platform_objects}
        vs = to_wire(by_kind["VirtualService"])
        assert "hosts" in vs["spec"] and "http" in vs["spec"]
        assert vs["spec"]["http"][0]["route"][0]["destination"]["port"]
        ap = to_wire(by_kind["AuthorizationPolicy"])
        assert ap["spec"]["action"] == "ALLOW"
        assert ap["spec"]["rules"][0]["when"][0]["key"].startswith(
            "request.headers[")

    def test_event_wire_has_involved_object(self, platform_objects):
        ev = next(o for k, o in platform_objects if k == "Event")
        wire = to_wire(ev)
        assert wire["involvedObject"]["kind"]
        assert "involvedKind" not in wire


class TestInjectedErrors:
    """A structural error in ANY emitted manifest must fail validation —
    the self-consistent-loop problem this tier exists to break."""

    @pytest.fixture()
    def deployment(self):
        return copy.deepcopy(next(
            d for d in build_k8s_manifests() if d["kind"] == "Deployment"))

    def test_misspelled_field_fails(self, deployment):
        spec = deployment["spec"]["template"]["spec"]
        spec["serviceAcountName"] = spec.pop("serviceAccountName")
        assert any("serviceAcountName" in e for e in validate(deployment))

    def test_wrong_type_fails(self, deployment):
        deployment["spec"]["replicas"] = "1"
        assert any("replicas" in e and "integer" in e
                   for e in validate(deployment))

    def test_container_port_as_bare_int_fails(self, deployment):
        spec = deployment["spec"]["template"]["spec"]
        spec["containers"][0]["ports"] = [8080]   # the OLD internal shape
        assert validate(deployment)

    def test_flat_resources_fails(self, deployment):
        spec = deployment["spec"]["template"]["spec"]
        spec["containers"][0]["resources"] = {"cpu": "1"}  # old shape
        assert any("resources" in e for e in validate(deployment))

    def test_bad_quantity_fails(self, deployment):
        spec = deployment["spec"]["template"]["spec"]
        spec["containers"][0]["resources"] = {
            "limits": {"cpu": "lots"}, "requests": {"cpu": "1"}}
        assert any("quantity" in e for e in validate(deployment))

    def test_bad_dns_name_fails(self, deployment):
        deployment["metadata"]["name"] = "Bad_Name"
        assert any("DNS-1123" in e for e in validate(deployment))

    def test_two_slash_annotation_key_fails(self):
        errs = validate_metadata(
            {"name": "x", "annotations": {"a.b/c/d": "v"}})
        assert errs

    def test_unknown_kind_fails(self):
        assert validate({"apiVersion": "v9", "kind": "Gizmo",
                         "metadata": {"name": "x"}})

    def test_rbac_path_segment_names_allowed(self):
        # kfam's namespaceAdmin binding is legal RBAC (path-segment rule)
        doc = {"apiVersion": "rbac.authorization.k8s.io/v1",
               "kind": "RoleBinding",
               "metadata": {"name": "namespaceAdmin", "namespace": "a"},
               "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                           "kind": "ClusterRole", "name": "kubeflow-admin"},
               "subjects": [{"apiGroup": "rbac.authorization.k8s.io",
                             "kind": "User", "name": "a@x.com"}]}
        assert not validate(doc)


class TestKubectlBoundary:
    def test_adapter_refuses_invalid_manifest(self):
        """A controller bug producing an invalid manifest dies in-process,
        not at the cluster."""
        from kubeflow_tpu.controlplane.runtime.apiserver import ApiError
        from kubeflow_tpu.controlplane.runtime.kubectl import (
            KubectlApiServer,
        )

        api = KubectlApiServer(kubectl="/nonexistent-kubectl")
        pod = Pod(metadata=ObjectMeta(name="UPPER", namespace="x"),
                  spec=PodSpec(containers=[Container(name="c", image="i")]))
        with pytest.raises(ApiError, match="DNS-1123"):
            api.create(pod)

    def test_fake_kubectl_rejects_invalid_incoming(self, tmp_path):
        """The test double validates with the SAME schemas — apiserver
        style — instead of its own permissive parser."""
        fake = Path(__file__).parent / "fake_kubectl.py"
        bad = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p"},
               "spec": {"containers": [{"name": "c", "image": "i",
                                        "ports": [8080]}]}}
        out = subprocess.run(
            [sys.executable, "-S", str(fake), "create", "-f", "-",
             "-o", "json"],
            input=json.dumps(bad), capture_output=True, text=True,
            env={"FAKE_KUBECTL_DIR": str(tmp_path)},
        )
        assert out.returncode != 0
        assert "error validating data" in out.stderr

        good = copy.deepcopy(bad)
        good["spec"]["containers"][0]["ports"] = [{"containerPort": 8080}]
        out = subprocess.run(
            [sys.executable, "-S", str(fake), "create", "-f", "-",
             "-o", "json"],
            input=json.dumps(good), capture_output=True, text=True,
            env={"FAKE_KUBECTL_DIR": str(tmp_path)},
        )
        assert out.returncode == 0, out.stderr


class TestReviewRegressions:
    """Round-5 review findings, pinned."""

    def test_owner_references_carry_api_version(self, platform_objects):
        owned = [o for _, o in platform_objects
                 if o.metadata.owner_references]
        assert owned, "fixture lost its owned objects"
        for o in owned:
            wire = to_wire(o)
            for ref in wire["metadata"]["ownerReferences"]:
                assert ref.get("apiVersion"), (o.metadata.name, ref)

    def test_missing_owner_ref_api_version_fails_validation(self):
        wire = to_wire(Pod(
            metadata=ObjectMeta(name="p", namespace="a"),
            spec=PodSpec(containers=[Container(name="c", image="i")])))
        wire["metadata"]["ownerReferences"] = [
            {"kind": "Notebook", "name": "nb", "uid": "u1"}]
        assert any("apiVersion" in e for e in validate(wire))

    def test_pod_conditions_round_trip_rfc3339(self):
        from kubeflow_tpu.controlplane.api.meta import Condition
        from kubeflow_tpu.controlplane.api.core import PodStatus

        pod = Pod(
            metadata=ObjectMeta(name="p", namespace="a"),
            spec=PodSpec(containers=[Container(name="c", image="i")]),
            status=PodStatus(phase="Pending", message="unschedulable",
                             conditions=[Condition(
                                 type="PodScheduled", status="False",
                                 reason="Unschedulable",
                                 last_transition_time=1700000000.0)]))
        wire = to_wire(pod)
        # Pending status persists, with RFC3339 condition stamps.
        assert wire["status"]["message"] == "unschedulable"
        ts = wire["status"]["conditions"][0]["lastTransitionTime"]
        assert ts.endswith("Z") and "T" in ts
        assert not validate(wire), validate(wire)
        back = from_wire(json.loads(json.dumps(wire)))
        assert back.status.message == "unschedulable"
        cond = back.status.conditions[0]
        assert cond.last_transition_time == 1700000000.0
        assert cond.reason == "Unschedulable"

    def test_spec_node_name_read_back_into_status(self):
        wire = to_wire(Pod(
            metadata=ObjectMeta(name="p", namespace="a"),
            spec=PodSpec(containers=[Container(name="c", image="i")])))
        wire["spec"]["nodeName"] = "tpu-node-3"
        pod = from_wire(wire)
        assert pod.status.node_name == "tpu-node-3"
